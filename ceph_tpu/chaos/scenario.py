"""Seeded composed-chaos storylines (docs/CHAOS.md).

Every hostile scenario before this subsystem was hand-built and
singular — one recovery storm (tests/test_chaos.py), one chip
straggler (tests/test_incident.py), one abusive client
(docs/QOS.md).  Production failure is combinatorial, so this module
COMPOSES the existing primitive inventory into multi-fault storylines:
the fault-site catalog (``FaultRegistry.sites()``), the traffic
harness's first-class topology events (``TrafficSpec.events`` — OSD
kill/out/revive and the elastic-membership mesh_chip_add/retire), the
abusive-client rate dial (``TrafficSpec.rate_multipliers``), and the
mgr control plane's enable knob.

Determinism is the whole contract:

- ``compose_scenario(seed)`` consumes exactly one ``random.Random
  (seed)`` stream plus the ``chaos_storyline_legs_max`` option — same
  seed, same conf => an IDENTICAL :class:`ScenarioSpec` (dataclass
  equality over the full schedule; pinned in
  tests/test_chaos_composer.py).
- Storylines schedule on harness ROUNDS — the deterministic cluster
  clock surface (one ``network.pump`` per round, ``cluster.tick``
  every ``tick_every`` rounds).  No event ever consults the wall
  clock; wall time only ever appears inside measured latencies.
- The spec is DECLARATIVE — tuples of :class:`ScenarioEvent`, no
  callables — so two specs can be compared, dumped over the admin
  socket (``chaos compose``), and replayed byte-for-byte.  The engine
  (engine.py) compiles it into ``TrafficSpec.events`` + ``hooks``.

This module is pure host Python: no jax, no numpy — composing a
scenario allocates nothing on any device (the fence-count extension
in tests/test_observability.py pins that).
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Tuple

from ..common.config import g_conf

# base mesh the engine runs storylines on (ec_mesh_chips at scenario
# boot); legs that name chips sample inside this bound
BASE_MESH_CHIPS = 8


@dataclass(frozen=True)
class ScenarioEvent:
    """One declarative storyline step: *round* is the harness round it
    fires at (passed-round semantics, like ``TrafficSpec.events``);
    *detail* is a sorted tuple of (key, value) pairs so the event is
    hashable and two schedules compare by value."""
    round: int
    action: str
    detail: Tuple[Tuple[str, Any], ...] = ()

    def dump(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"round": self.round, "action": self.action}
        d.update(dict(self.detail))
        return d


@dataclass(frozen=True)
class ScenarioSpec:
    """One composed storyline — the unit of determinism and equality.

    ``expected_checks`` are the health checks the storyline MUST raise
    AND clear (universal acceptance); ``settle_clears`` are the fault
    sites the engine disarms only AFTER the expected raise (phased
    clear — the hysteresis needs the fault live until detection);
    ``journal_expect`` are the event types the injected storyline must
    leave in the causally-ordered journal."""
    seed: int
    legs: Tuple[str, ...]
    events: Tuple[ScenarioEvent, ...]
    expected_checks: Tuple[str, ...]
    settle_clears: Tuple[str, ...]
    journal_expect: Tuple[str, ...]
    rate_multipliers: Tuple[float, ...]
    tolerates_missing_bundle: bool

    def dump(self) -> Dict[str, Any]:
        return {
            "seed": self.seed,
            "legs": list(self.legs),
            "events": [e.dump() for e in self.events],
            "expected_checks": list(self.expected_checks),
            "settle_clears": list(self.settle_clears),
            "journal_expect": list(self.journal_expect),
            "rate_multipliers": list(self.rate_multipliers),
            "tolerates_missing_bundle": self.tolerates_missing_bundle,
        }


# ---------------------------------------------------------------------------
# the leg catalog — each builder consumes the shared seeded rng and
# returns the leg's declarative contribution.  Phases are sampled in
# rounds 1..~12, inside the window open-loop traffic is guaranteed to
# span (the run loops empty rounds until every scheduled step fired).

def _leg_recovery_storm(rng: random.Random) -> Dict[str, Any]:
    """An OSD dies and is marked out mid-traffic (backfill to a spare
    starts under load), then revives and rejoins — the full storm
    cycle from docs/RECOVERY.md as one leg."""
    osd = rng.randrange(3)
    r0 = 1 + rng.randrange(3)
    dur = 6 + rng.randrange(6)
    return {
        "events": [
            ScenarioEvent(r0, "osd_kill", (("osd", osd),)),
            ScenarioEvent(r0 + 1, "osd_out", (("osd", osd),)),
            ScenarioEvent(r0 + dur, "osd_revive", (("osd", osd),)),
            ScenarioEvent(r0 + dur + 1, "osd_in", (("osd", osd),)),
        ],
        "journal_expect": ("osd_down", "osd_out", "osd_in"),
    }


def _leg_chip_straggler(rng: random.Random) -> Dict[str, Any]:
    """One mesh chip serves 10x slow (the skew scoreboard's SUSPECT
    shape): TPU_MESH_SKEW must raise while the fault is live and clear
    after the settle-phase disarm — the only leg with a deterministic
    health-check contract, so it anchors the bundle oracle."""
    chip = 1 + rng.randrange(BASE_MESH_CHIPS - 2)
    r0 = 1 + rng.randrange(3)
    return {
        "events": [
            ScenarioEvent(r0, "fault_arm", (
                ("delay_us", 30_000),
                ("match", f"chip={chip}/"),
                ("mode", "always"),
                ("site", "mesh.chip_slowdown"))),
        ],
        "expected_checks": ("TPU_MESH_SKEW",),
        "settle_clears": ("mesh.chip_slowdown",),
        "journal_expect": ("fault_arm", "fault_fire",
                           "chip_suspect_mark"),
    }


def _leg_abusive_client(rng: random.Random) -> Dict[str, Any]:
    """One tenant turns its arrival rate up 8-12x (docs/QOS.md's
    saturation dial).  Compose-time traffic shape, not a scheduled
    step — recorded at round 0 so the storyline dump tells it."""
    mult = float(rng.choice((8, 10, 12)))
    return {
        "events": [
            ScenarioEvent(0, "traffic_abuse", (
                ("client", 0), ("multiplier", mult))),
        ],
        "rate_multipliers": (mult,),
    }


def _leg_chip_fail(rng: random.Random) -> Dict[str, Any]:
    """A bounded burst of hard per-chip failures: with the rateless
    coder on (engine base knobs) the flush completes from the first
    sufficient subset, so this leg must cost bandwidth, never an op."""
    chip = rng.randrange(BASE_MESH_CHIPS)
    r0 = 1 + rng.randrange(3)
    dur = 4 + rng.randrange(5)
    count = 2 + rng.randrange(3)
    return {
        "events": [
            ScenarioEvent(r0, "fault_arm", (
                ("count", count),
                ("match", f"chip={chip}/"),
                ("mode", "always"),
                ("site", "mesh.chip_fail"))),
            ScenarioEvent(r0 + dur, "fault_clear", (
                ("site", "mesh.chip_fail"),)),
        ],
        # NOT fault_clear: count=K self-disarms after K fires, so the
        # scheduled clear is usually a journal-silent no-op — the
        # storyline is told by the arm and the fires themselves
        "journal_expect": ("fault_arm", "fault_fire"),
    }


def _leg_degraded_read_straggler(rng: random.Random) -> Dict[str, Any]:
    """A data shard dies while one mesh chip serves 10x slow: the
    open-loop reads that land on the dead shard reconstruct through
    the MESHED decode path (ceph_tpu/mesh decode_stacked) with the
    straggler live — the rateless drain completes each group from the
    first spanning subset, so every read stays byte-exact with zero
    single-device fallbacks (the straggler-proof read PR's composed
    shape).  TPU_MESH_SKEW must raise while the slowdown is armed and
    clear after the settle-phase disarm, like the chip_straggler leg;
    the OSD revives before settle so acceptance judges a whole
    degrade/recover cycle."""
    osd = rng.randrange(3)
    chip = 1 + rng.randrange(BASE_MESH_CHIPS - 2)
    r0 = 1 + rng.randrange(3)
    dur = 5 + rng.randrange(5)
    return {
        "events": [
            ScenarioEvent(r0, "osd_kill", (("osd", osd),)),
            ScenarioEvent(r0, "fault_arm", (
                ("delay_us", 30_000),
                ("match", f"chip={chip}/"),
                ("mode", "always"),
                ("site", "mesh.chip_slowdown"))),
            ScenarioEvent(r0 + dur, "osd_revive", (("osd", osd),)),
        ],
        "expected_checks": ("TPU_MESH_SKEW",),
        "settle_clears": ("mesh.chip_slowdown",),
        "journal_expect": ("osd_down", "fault_arm", "fault_fire",
                           "chip_suspect_mark"),
    }


def _leg_msg_drop(rng: random.Random) -> Dict[str, Any]:
    """Seeded probabilistic loss of EC sub-op WRITES (``match=
    "MOSDECSubOpWrite "``): the pipeline's inflight sweep resends
    unacked sub-writes after ``ec_subwrite_retry_timeout`` on the
    deterministic tick clock, and shard-side replay is version-deduped,
    so every dropped message is recovered by design.  Client REQUESTS
    (``MOSDOp``) are deliberately NOT in scope: the open-loop harness
    client resends only on a reply, so a silently dropped request would
    hang the op to max_rounds — unrecoverable, hence un-composable."""
    r0 = 1 + rng.randrange(3)
    dur = 4 + rng.randrange(5)
    p = round(0.03 + 0.03 * rng.random(), 3)
    return {
        "events": [
            ScenarioEvent(r0, "fault_arm", (
                ("match", "MOSDECSubOpWrite "),
                ("mode", "prob"),
                ("p", p),
                ("seed", rng.randrange(1 << 16)),
                ("site", "msg.drop"))),
            ScenarioEvent(r0 + dur, "fault_clear", (
                ("site", "msg.drop"),)),
        ],
        "journal_expect": ("fault_arm", "fault_clear"),
    }


def _leg_shard_eio(rng: random.Random) -> Dict[str, Any]:
    """Every Nth shard read fails EIO: reads reconstruct from
    survivors (never more than m failures per read by construction —
    the n >= 4 bound from tests/test_chaos.py's determinism notes)."""
    r0 = 1 + rng.randrange(3)
    dur = 4 + rng.randrange(5)
    n = 4 + rng.randrange(4)
    return {
        "events": [
            ScenarioEvent(r0, "fault_arm", (
                ("mode", "nth"), ("n", n),
                ("site", "osd.shard_read_eio"))),
            ScenarioEvent(r0 + dur, "fault_clear", (
                ("site", "osd.shard_read_eio"),)),
        ],
        "journal_expect": ("fault_arm", "fault_clear"),
    }


def _leg_device_error(rng: random.Random) -> Dict[str, Any]:
    """Transient device-call failures on the batched encode path: the
    bounded retry absorbs them below the breaker threshold."""
    r0 = 1 + rng.randrange(3)
    dur = 4 + rng.randrange(5)
    n = 3 + rng.randrange(3)
    return {
        "events": [
            ScenarioEvent(r0, "fault_arm", (
                ("mode", "nth"), ("n", n),
                ("site", "device.encode_batch"))),
            ScenarioEvent(r0 + dur, "fault_clear", (
                ("site", "device.encode_batch"),)),
        ],
        "journal_expect": ("fault_arm", "fault_clear"),
    }


def _leg_capture_drop(rng: random.Random) -> Dict[str, Any]:
    """The forensics pipeline itself fails once (`mgr.incident_capture`
    once-shot): a raise during the armed window drops ITS bundle —
    journaled as incident_drop — and must never wedge the mgr tick, so
    acceptance tolerates a missing bundle IFF the drop was journaled."""
    r0 = 1 + rng.randrange(3)
    return {
        "events": [
            ScenarioEvent(r0, "fault_arm", (
                ("mode", "once"),
                ("site", "mgr.incident_capture"))),
        ],
        "journal_expect": ("fault_arm",),
        "tolerates_missing_bundle": True,
    }


def _leg_mesh_membership(rng: random.Random) -> Dict[str, Any]:
    """Elastic membership as just another fault: retire 1-2 chips
    mid-traffic (drain on the old mesh, scoreboard-informed retire),
    add them back later (real stripes within one flush of the plan
    rebuild) — the injectargs-live ``ec_mesh_chips`` path."""
    k = 1 + rng.randrange(2)
    r0 = 2 + rng.randrange(3)
    dur = 4 + rng.randrange(5)
    return {
        "events": [
            ScenarioEvent(r0, "mesh_chip_retire", (("chips", k),)),
            ScenarioEvent(r0 + dur, "mesh_chip_add", (("chips", k),)),
        ],
        "journal_expect": ("mesh_chip_retire", "mesh_chip_add"),
    }


def _leg_control_flap(rng: random.Random) -> Dict[str, Any]:
    """The SLO controller goes away and comes back mid-storyline: the
    cluster must hold every invariant with and without the feedback
    loop (the controller is an optimisation, never a crutch)."""
    r0 = 1 + rng.randrange(3)
    dur = 3 + rng.randrange(4)
    return {
        "events": [
            ScenarioEvent(r0, "conf_set", (
                ("option", "mgr_control_enable"), ("value", False))),
            ScenarioEvent(r0 + dur, "conf_set", (
                ("option", "mgr_control_enable"), ("value", True))),
        ],
    }


LEG_BUILDERS: Dict[str, Callable[[random.Random], Dict[str, Any]]] = {
    "abusive_client": _leg_abusive_client,
    "capture_drop": _leg_capture_drop,
    "chip_fail": _leg_chip_fail,
    "chip_straggler": _leg_chip_straggler,
    "control_flap": _leg_control_flap,
    "degraded_read_straggler": _leg_degraded_read_straggler,
    "device_error": _leg_device_error,
    "mesh_membership": _leg_mesh_membership,
    "msg_drop": _leg_msg_drop,
    "recovery_storm": _leg_recovery_storm,
    "shard_eio": _leg_shard_eio,
}


def leg_names() -> List[str]:
    """The composable leg catalog, sorted — the `chaos dump` pane."""
    return sorted(LEG_BUILDERS)


def compose_scenario(seed: int,
                     legs: Tuple[str, ...] = None) -> ScenarioSpec:
    """Sample one multi-fault storyline from *seed*.

    With *legs* None the storyline samples 1..``chaos_storyline_legs_
    max`` distinct legs from the catalog; passing *legs* pins WHICH
    primitives compose while the seed still shapes every phase (the
    tier-1 acceptance smoke pins storm+straggler+abusive this way).
    Pure and deterministic: same (seed, legs, conf) => equal spec.
    """
    rng = random.Random(int(seed))
    names = leg_names()
    if legs is None:
        legs_max = max(int(g_conf.get_val("chaos_storyline_legs_max")),
                       1)
        n = 1 + rng.randrange(min(legs_max, len(names)))
        legs = tuple(sorted(rng.sample(names, n)))
    else:
        legs = tuple(legs)
        for name in legs:
            if name not in LEG_BUILDERS:
                raise ValueError(f"unknown storyline leg '{name}' "
                                 f"(catalog: {names})")
    events: List[ScenarioEvent] = []
    expected_checks: List[str] = []
    settle_clears: List[str] = []
    journal_expect: List[str] = []
    rate_multipliers: Tuple[float, ...] = ()
    tolerates = False
    for name in legs:           # build order = leg order = rng order
        leg = LEG_BUILDERS[name](rng)
        events.extend(leg["events"])
        expected_checks.extend(leg.get("expected_checks", ()))
        settle_clears.extend(leg.get("settle_clears", ()))
        journal_expect.extend(leg.get("journal_expect", ()))
        rate_multipliers = rate_multipliers + tuple(
            leg.get("rate_multipliers", ()))
        tolerates = tolerates or leg.get("tolerates_missing_bundle",
                                         False)
    _validate_fault_sites(events)
    events.sort(key=lambda e: (e.round, e.action, e.detail))
    return ScenarioSpec(
        seed=int(seed), legs=legs, events=tuple(events),
        expected_checks=tuple(sorted(set(expected_checks))),
        settle_clears=tuple(sorted(set(settle_clears))),
        journal_expect=tuple(sorted(set(journal_expect))),
        rate_multipliers=rate_multipliers,
        tolerates_missing_bundle=tolerates)


def _validate_fault_sites(events: List[ScenarioEvent]) -> None:
    """Every fault-backed step must name a REGISTERED site — the
    composer enumerates primitives from the machine-readable catalog,
    it never invents them (satellite contract: `FaultRegistry.sites()`
    is the enumeration surface, and every site is documented in
    docs/ROBUSTNESS.md by the tier-1 lint)."""
    from ..fault import g_faults
    catalog = g_faults.sites()
    for ev in events:
        if ev.action in ("fault_arm", "fault_clear"):
            site = dict(ev.detail)["site"]
            if site not in catalog:
                raise ValueError(
                    f"storyline names unregistered fault site "
                    f"'{site}' (see `fault list format=json`)")
