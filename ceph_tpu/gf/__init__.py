from .tables import (  # noqa: F401
    GF_POLY,
    gf_exp,
    gf_log,
    gf_mul,
    gf_mul_scalar,
    gf_div,
    gf_inv,
    gf_pow,
    MUL_TABLE,
    gf_mult_bitmatrix,
    expand_to_bitmatrix,
)
from .matrices import (  # noqa: F401
    gf_gen_rs_matrix,
    gf_gen_cauchy1_matrix,
    jerasure_reed_sol_van_matrix,
    gf_invert_matrix,
    gf_matmul,
)
