"""GF(2) bitmatrix machinery for the scheduled-XOR code family.

The reference's jerasure plugin executes cauchy_orig/cauchy_good/
liberation/blaum_roth/liber8tion as *bitmatrix* codes
(src/erasure-code/jerasure/ErasureCodeJerasure.cc:259-269,340-348: encode =
jerasure_schedule_encode over a (m*w x k*w) 0/1 matrix, decode =
jerasure_schedule_decode_lazy; the jerasure library itself is an empty
submodule, so these constructions are reimplemented from the published
algorithm definitions — J. Plank's jerasure 2.0 and the Liberation /
Blaum-Roth code papers).

Semantics: each chunk is a sequence of super-blocks of w *packets*
(packetsize bytes each); coding packet (i, l) is the XOR of every data
packet (j, x) whose bitmatrix entry [i*w+l, j*w+x] is 1.  XOR of byte
packets with 0/1 coefficients is GF(2^8)-linear, so the whole family runs
on the existing matrix codec + MXU bit-matmul backend over *virtual packet
chunks* — chunk j contributes rows j*w..j*w+w-1.

GF(2^w) scalar arithmetic (matrix construction only; never on the data
path) uses the jerasure/gf-complete default primitive polynomials so the
coefficient matrices match the reference's field choices.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

# jerasure/gf-complete default primitive polynomials (galois.c prim_poly_*)
PRIM_POLY = {4: 0x13, 8: 0x11D, 16: 0x1100B, 32: 0x100400007}


def gfw_mul(a: int, b: int, w: int) -> int:
    """Shift-and-xor GF(2^w) multiply (construction-time only)."""
    poly = PRIM_POLY[w]
    top = 1 << w
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & top:
            a ^= poly
    return r


def gfw_pow(a: int, n: int, w: int) -> int:
    r = 1
    base = a
    while n:
        if n & 1:
            r = gfw_mul(r, base, w)
        base = gfw_mul(base, base, w)
        n >>= 1
    return r


def gfw_inv(a: int, w: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gfw_inv(0)")
    return gfw_pow(a, (1 << w) - 2, w)


def gfw_div(a: int, b: int, w: int) -> int:
    return gfw_mul(a, gfw_inv(b, w), w)


def element_bitmatrix(e: int, w: int) -> np.ndarray:
    """w x w GF(2) matrix M with M[l, x] = bit l of e * 2^x — the companion
    representation jerasure_matrix_to_bitmatrix uses per element."""
    m = np.zeros((w, w), dtype=np.uint8)
    v = e
    for x in range(w):
        for l in range(w):
            m[l, x] = (v >> l) & 1
        v = gfw_mul(v, 2, w)
    return m


def n_ones(e: int, w: int) -> int:
    """Ones in the element's bitmatrix (cauchy_n_ones role)."""
    total = 0
    v = e
    for _ in range(w):
        total += bin(v).count("1")
        v = gfw_mul(v, 2, w)
    return total


def matrix_to_bitmatrix(matrix: np.ndarray, w: int) -> np.ndarray:
    """(m, k) GF(2^w) coefficients -> (m*w, k*w) GF(2) bitmatrix
    (jerasure_matrix_to_bitmatrix semantics)."""
    m, k = matrix.shape
    out = np.zeros((m * w, k * w), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            out[i * w:(i + 1) * w, j * w:(j + 1) * w] = \
                element_bitmatrix(int(matrix[i, j]), w)
    return out


def gf2_invert(mat: np.ndarray) -> np.ndarray:
    """Invert a square 0/1 matrix over GF(2) (Gaussian elimination)."""
    n = mat.shape[0]
    a = mat.astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        piv = None
        for r in range(col, n):
            if a[r, col]:
                piv = r
                break
        if piv is None:
            raise np.linalg.LinAlgError("GF(2) matrix is singular")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        rows = np.nonzero(a[:, col])[0]
        rows = rows[rows != col]
        a[rows] ^= a[col]
        inv[rows] ^= inv[col]
    return inv


# ---- coefficient-matrix constructions --------------------------------------

def cauchy_original_matrix(k: int, m: int, w: int) -> np.ndarray:
    """cauchy_original_coding_matrix: row i col j = 1/(i ^ (m+j)) over
    GF(2^w); requires k + m <= 2^w."""
    if k + m > (1 << w):
        raise ValueError(f"k+m={k + m} > 2^w for w={w}")
    a = np.zeros((m, k), dtype=np.int64)
    for i in range(m):
        for j in range(k):
            a[i, j] = gfw_inv(i ^ (m + j), w)
    return a


def cauchy_good_matrix(k: int, m: int, w: int) -> np.ndarray:
    """cauchy_good_general_coding_matrix: the original Cauchy matrix
    improved to minimize bitmatrix density (cauchy.c
    cauchy_improve_coding_matrix semantics): normalize row 0 to all ones
    by column division, then divide each later row by whichever of its
    elements minimizes the row's total bitmatrix ones."""
    mat = cauchy_original_matrix(k, m, w)
    # column scaling: make row 0 all ones
    for j in range(k):
        e = int(mat[0, j])
        if e != 1:
            inv = gfw_inv(e, w)
            for i in range(m):
                mat[i, j] = gfw_mul(int(mat[i, j]), inv, w)
    # row scaling: greedily minimize ones
    for i in range(1, m):
        best = sum(n_ones(int(e), w) for e in mat[i])
        best_div = None
        for j in range(k):
            e = int(mat[i, j])
            if e != 1:
                inv = gfw_inv(e, w)
                tot = sum(n_ones(gfw_mul(int(x), inv, w), w)
                          for x in mat[i])
                if tot < best:
                    best = tot
                    best_div = inv
        if best_div is not None:
            for j in range(k):
                mat[i, j] = gfw_mul(int(mat[i, j]), best_div, w)
    return mat


def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    """Liberation RAID-6 bitmatrix (Plank, 'The RAID-6 Liberation Codes';
    liberation.c liberation_coding_bitmatrix semantics): m=2, w prime,
    k <= w.  Row block 0: identities (parity).  Row block 1, column j: the
    identity shifted down by j, plus for j > 0 one extra 1 at row
    i = (j*(w-1)/2) mod w, column (i+j-1) mod w."""
    if k > w:
        raise ValueError("liberation needs k <= w")
    if not _is_prime(w):
        raise ValueError("liberation needs prime w")
    bm = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        bm[np.arange(w), j * w + np.arange(w)] = 1            # parity I
        for i in range(w):
            bm[w + i, j * w + (j + i) % w] = 1                # shifted I
        if j > 0:
            i = (j * ((w - 1) // 2)) % w
            bm[w + i, j * w + (i + j - 1) % w] = 1            # extra bit
    return bm


def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    """Blaum-Roth RAID-6 bitmatrix: m=2, w+1 prime, k <= w.

    Over the ring F2[x]/M_p(x) with M_p = 1 + x + ... + x^w (p = w+1
    prime), the Q row's block for column j is the matrix of
    multiplication by x^j; multiplication by x maps coefficient vector v
    to (v_{w-1}, v_0 + v_{w-1}, ..., v_{w-2} + v_{w-1})."""
    if k > w:
        raise ValueError("blaum_roth needs k <= w")
    if not _is_prime(w + 1):
        raise ValueError("blaum_roth needs w+1 prime")
    T = np.zeros((w, w), dtype=np.uint8)
    for i in range(1, w):
        T[i, i - 1] = 1
    T[:, w - 1] ^= 1  # x^w = 1 + x + ... + x^{w-1}
    bm = np.zeros((2 * w, k * w), dtype=np.uint8)
    blk = np.eye(w, dtype=np.uint8)
    for j in range(k):
        bm[:w, j * w:(j + 1) * w] = np.eye(w, dtype=np.uint8)
        bm[w:, j * w:(j + 1) * w] = blk
        blk = (blk @ T) % 2
    return bm


def liber8tion_bitmatrix(k: int) -> np.ndarray:
    """liber8tion-class RAID-6 bitmatrix for w=8, k <= 8, m=2.

    The reference's liber8tion matrices come from Plank's published
    search ('Uber-CSHR and Liber8tion' codes) carried by the jerasure
    library — an empty submodule in the reference tree, so the exact
    searched constants are not reproducible here.  This builds the same
    *interface* of code deterministically: P row = XOR of all columns,
    Q block for column j = the companion-matrix power of the GF(2^8)
    generator (multiplication by 2^j), i.e. the RAID-6 [1..1; 1,2,4,..]
    matrix as a bitmatrix — provably MDS for any two erasures (the 2x2
    minors [[1,1],[2^i,2^j]] are nonsingular), denser than Plank's
    searched optimum but byte-stable and corpus-pinned."""
    w = 8
    if k > 8:
        raise ValueError("liber8tion needs k <= 8")
    mat = np.zeros((2, k), dtype=np.int64)
    mat[0, :] = 1
    for j in range(k):
        mat[1, j] = gfw_pow(2, j, w)
    return matrix_to_bitmatrix(mat, w)


def _is_prime(v: int) -> bool:
    if v < 2:
        return False
    for d in range(2, int(v ** 0.5) + 1):
        if v % d == 0:
            return False
    return True


# ---- packet-layout codec ---------------------------------------------------

class BitmatrixPacketCodec:
    """Chunk-level executor for a (m*w, k*w) bitmatrix with jerasure's
    packet layout (jerasure_schedule_encode semantics).

    Exposes the MatrixRSCodec surface (``matrix``, ``encode``, ``decode``)
    over whole chunks; internally chunks are reshaped into virtual packet
    chunks and run through a GF(2^8) matrix codec whose coefficients are
    the 0/1 bitmatrix — XOR of byte packets.  The ``matrix`` attribute is
    the virtual systematic matrix, so the device backend
    (ops/gf_matmul.DeviceRSBackend) executes the same code on the MXU.
    """

    def __init__(self, coding_bitmatrix: np.ndarray, k: int, m: int,
                 w: int, packetsize: int):
        from ..ec.rs_codec import MatrixRSCodec
        mw, kw = coding_bitmatrix.shape
        assert mw == m * w and kw == k * w
        self.k, self.m, self.w = k, m, w
        self.packetsize = packetsize
        full = np.zeros(((k + m) * w, k * w), dtype=np.uint8)
        full[:k * w] = np.eye(k * w, dtype=np.uint8)
        full[k * w:] = coding_bitmatrix
        self.matrix = full
        self.inner = MatrixRSCodec(full)

    # -- layout -------------------------------------------------------------
    def to_virtual(self, chunks: np.ndarray) -> np.ndarray:
        """(n, C) chunks -> (n*w, C//w) virtual packet chunks."""
        n, C = chunks.shape
        w, ps = self.w, self.packetsize
        assert C % (w * ps) == 0, (C, w, ps)
        nb = C // (w * ps)
        v = chunks.reshape(n, nb, w, ps).transpose(0, 2, 1, 3)
        return np.ascontiguousarray(v).reshape(n * w, nb * ps)

    def from_virtual(self, virt: np.ndarray, n: int) -> np.ndarray:
        """(n*w, C//w) virtual chunks -> (n, C)."""
        w, ps = self.w, self.packetsize
        nw, cv = virt.shape
        assert nw == n * w and cv % ps == 0
        nb = cv // ps
        c = virt.reshape(n, w, nb, ps).transpose(0, 2, 1, 3)
        return np.ascontiguousarray(c).reshape(n, nb * w * ps)

    # -- chunk-level MatrixRSCodec surface -----------------------------------
    def encode(self, data: np.ndarray) -> np.ndarray:
        """(k, C) -> (m, C) coding chunks (host XOR path)."""
        dv = self.to_virtual(data)
        cv = self.inner.encode(dv)
        return self.from_virtual(cv, self.m)

    def decode(self, chunks: Dict[int, np.ndarray],
               want: Sequence[int]) -> Dict[int, np.ndarray]:
        if len(chunks) < self.k:
            raise IOError(
                f"need at least k={self.k} chunks, have {len(chunks)}")
        w = self.w
        virt: Dict[int, np.ndarray] = {}
        for cid, buf in chunks.items():
            rows = self.to_virtual(buf[None, :])
            for l in range(w):
                virt[cid * w + l] = rows[l]
        want_rows = [c * w + l for c in want for l in range(w)]
        out_rows = self.inner.decode(virt, want_rows)
        out: Dict[int, np.ndarray] = {}
        for c in want:
            stack = np.stack([out_rows[c * w + l] for l in range(w)])
            out[c] = self.from_virtual(stack, 1)[0]
        return out
