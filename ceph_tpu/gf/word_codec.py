"""GF(2^w) word-layout RS codecs for w=16/32 (jerasure reed_sol family).

jerasure's reed_sol techniques at w=16/32 operate on little-endian w-bit
*words*: chunk bytes are viewed as u16/u32 arrays and every word is
multiplied in GF(2^w) (galois_w16/w32_region_multiply semantics behind
jerasure_matrix_encode, src/erasure-code/jerasure/ErasureCodeJerasure.cc:155
with w from the profile).  This module supplies:

- matrix generators over GF(2^w) (extended-Vandermonde systematization and
  the RAID-6 [1..1; 1,2,4..] rows), mirroring the w=8 versions in
  gf/matrices.py;
- a host codec whose multiply uses per-coefficient byte split tables (the
  isa-l ec_init_tables idea generalized: product(a, d) = XOR over bytes b
  of T_ab[d byte b]) — fully vectorized numpy over whole chunks;
- GF(2^w) matrix inversion for decode, signature-cached like the w=8 path.

The device path lives in ops/gf_matmul.gfw_bit_matmul: the same MXU 0/1
matmul with the (k*w, m*w) companion bitmatrix, unpacking each LE word
into its w bits.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ..ec.rs_codec import MatrixRSCodec
from .bitmatrix import gfw_div, gfw_inv, gfw_mul

_WORD_DTYPE = {16: np.dtype("<u2"), 32: np.dtype("<u4")}


def extended_vandermonde_w(rows: int, cols: int, w: int) -> np.ndarray:
    """jerasure's extended Vandermonde matrix over GF(2^w)."""
    v = np.zeros((rows, cols), dtype=np.int64)
    v[0, 0] = 1
    if rows == 1:
        return v
    v[rows - 1, cols - 1] = 1
    for i in range(1, rows - 1):
        acc = 1
        for j in range(cols):
            v[i, j] = acc
            acc = gfw_mul(acc, i, w)
    return v


def reed_sol_van_matrix_w(k: int, m: int, w: int) -> np.ndarray:
    """m x k coding matrix matching jerasure reed_sol_van over GF(2^w)
    (same column-elimination systematization as the w=8 generator)."""
    rows, cols = k + m, k
    dist = extended_vandermonde_w(rows, cols, w)
    for i in range(1, cols):
        j = i
        while j < rows and dist[j, i] == 0:
            j += 1
        if j >= rows:
            raise ValueError("singular extended Vandermonde matrix")
        if j > i:
            dist[[i, j], :] = dist[[j, i], :]
        if dist[i, i] != 1:
            inv = gfw_div(1, int(dist[i, i]), w)
            for r in range(rows):
                dist[r, i] = gfw_mul(inv, int(dist[r, i]), w)
        for jj in range(cols):
            t = int(dist[i, jj])
            if jj != i and t != 0:
                for r in range(rows):
                    dist[r, jj] ^= gfw_mul(t, int(dist[r, i]), w)
    return dist[k:, :].copy()


def reed_sol_r6_matrix_w(k: int, w: int) -> np.ndarray:
    """RAID6 rows over GF(2^w): ones and powers of 2."""
    m = np.zeros((2, k), dtype=np.int64)
    m[0, :] = 1
    p = 1
    for j in range(k):
        m[1, j] = p
        p = gfw_mul(p, 2, w)
    return m


def gfw_invert_matrix(mat: np.ndarray, w: int) -> np.ndarray:
    """Invert a k x k matrix over GF(2^w) (Gauss-Jordan, scalar ops)."""
    k = mat.shape[0]
    a = mat.astype(np.int64).copy()
    inv = np.eye(k, dtype=np.int64)
    for col in range(k):
        pivot = col
        while pivot < k and a[pivot, col] == 0:
            pivot += 1
        if pivot == k:
            raise np.linalg.LinAlgError("singular GF(2^w) matrix")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        piv = gfw_inv(int(a[col, col]), w)
        if piv != 1:
            for c in range(k):
                a[col, c] = gfw_mul(piv, int(a[col, c]), w)
                inv[col, c] = gfw_mul(piv, int(inv[col, c]), w)
        for r in range(k):
            if r != col and a[r, col]:
                f = int(a[r, col])
                for c in range(k):
                    a[r, c] ^= gfw_mul(f, int(a[col, c]), w)
                    inv[r, c] ^= gfw_mul(f, int(inv[col, c]), w)
    return inv


class _SplitMul:
    """Per-coefficient byte split tables: product = XOR_b T[b][byte_b]."""

    def __init__(self, coeff: int, w: int):
        nb = w // 8
        dt = _WORD_DTYPE[w]
        self.tables = []
        for b in range(nb):
            t = np.zeros(256, dtype=dt)
            for v in range(256):
                t[v] = gfw_mul(coeff, v << (8 * b), w)
            self.tables.append(t)

    def __call__(self, words: np.ndarray) -> np.ndarray:
        acc = self.tables[0][words & 0xFF]
        for b in range(1, len(self.tables)):
            acc = acc ^ self.tables[b][(words >> (8 * b)) & 0xFF]
        return acc


class WordMatrixCodec(MatrixRSCodec):
    """Systematic (k+m, k) GF(2^w) code executor over LE word chunks.

    Inherits MatrixRSCodec's encode/decode scaffolding (signature-cached
    inversion, plan_decode routing) and swaps the two field primitives:
    the matvec runs split-table word multiplies, the inversion runs
    GF(2^w) Gauss-Jordan."""

    _matrix_dtype = np.int64

    def __init__(self, encode_matrix: np.ndarray, w: int):
        assert w in _WORD_DTYPE
        self.w = w
        super().__init__(encode_matrix)
        self._mul_cache: Dict[int, _SplitMul] = {}

    def _mul(self, coeff: int) -> _SplitMul:
        sm = self._mul_cache.get(coeff)
        if sm is None:
            sm = self._mul_cache[coeff] = _SplitMul(coeff, self.w)
        return sm

    def _matvec(self, rows: np.ndarray, data: np.ndarray) -> np.ndarray:
        """rows (r, k) GF(2^w) x data (k, C) uint8 -> (r, C) uint8."""
        r, k = rows.shape
        kk, C = data.shape
        assert k == kk and C % (self.w // 8) == 0
        dt = _WORD_DTYPE[self.w]
        words = np.ascontiguousarray(data).view(dt)   # (k, C/ws)
        out = np.zeros((r, words.shape[1]), dtype=dt)
        for i in range(r):
            acc = out[i]
            for j in range(k):
                c = int(rows[i, j])
                if c == 0:
                    continue
                if c == 1:
                    acc ^= words[j]
                else:
                    acc ^= self._mul(c)(words[j])
            out[i] = acc
        return out.view(np.uint8).reshape(r, C)

    def _invert(self, sub: np.ndarray) -> np.ndarray:
        return gfw_invert_matrix(sub, self.w)
