"""RS coding-matrix generation and GF(2^8) linear algebra.

Matrix layouts follow the conventions of the reference's native libraries so
that coding chunks are byte-identical:

- ``gf_gen_rs_matrix`` / ``gf_gen_cauchy1_matrix`` reproduce the isa-l
  generators selected in the reference's isa plugin
  (src/erasure-code/isa/ErasureCodeIsa.cc:383-386): an (k+m) x k matrix whose
  top k rows are the identity (systematic code).
- ``jerasure_reed_sol_van_matrix`` reproduces jerasure's
  ``reed_sol_vandermonde_coding_matrix`` (the reed_sol_van technique,
  src/erasure-code/jerasure/ErasureCodeJerasure.cc:155): the m x k coding
  rows derived from an extended Vandermonde matrix reduced to systematic form.

Both libraries are empty submodules in the reference tree; these generators
are clean implementations of the published algorithms, validated by MDS
sweeps in tests/test_gf.py.
"""
from __future__ import annotations

import numpy as np

from .tables import gf_mul, gf_inv, gf_div, MUL_TABLE


def gf_gen_rs_matrix(rows: int, k: int) -> np.ndarray:
    """isa-l style systematic Vandermonde-ish matrix (rows x k).

    Row k+i is [g^0, g^1, ..] evaluated with a generator that doubles per
    row.  Only MDS for limited (k, m); the reference enforces k<=32, m<=4
    (k<=21 when m=4) — see ErasureCodeIsa.cc:330-361.
    """
    a = np.zeros((rows, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    gen = 1
    for i in range(k, rows):
        p = 1
        for j in range(k):
            a[i, j] = p
            p = gf_mul(p, gen)
        gen = gf_mul(gen, 2)
    return a


def gf_gen_cauchy1_matrix(rows: int, k: int) -> np.ndarray:
    """isa-l style systematic Cauchy matrix (rows x k): coding row i, col j
    = inv(i ^ j) for i in [k, rows)."""
    a = np.zeros((rows, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    for i in range(k, rows):
        for j in range(k):
            a[i, j] = gf_inv(i ^ j)
    return a


def jerasure_reed_sol_van_matrix(k: int, m: int) -> np.ndarray:
    """m x k coding matrix matching jerasure reed_sol_van (w=8).

    The construction (extended Vandermonde + jerasure's column-elimination
    systematization) is shared with the w=16/32 paths; this is the w=8
    instance (gfw_mul(a, b, 8) == gf_mul(a, b): same 0x11D polynomial,
    verified in tests/test_jerasure_bitmatrix.py).
    """
    from .word_codec import reed_sol_van_matrix_w
    return reed_sol_van_matrix_w(k, m, 8).astype(np.uint8)


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product (small matrices; host-side)."""
    n, k = a.shape
    k2, mcols = b.shape
    assert k == k2
    out = np.zeros((n, mcols), dtype=np.uint8)
    for i in range(n):
        for j in range(mcols):
            acc = 0
            for t in range(k):
                acc ^= int(MUL_TABLE[a[i, t], b[t, j]])
            out[i, j] = acc
    return out


def gf_invert_matrix(m: np.ndarray) -> np.ndarray:
    """Invert a k x k matrix over GF(2^8) by Gauss-Jordan elimination."""
    k = m.shape[0]
    assert m.shape == (k, k)
    a = m.astype(np.uint8).copy()
    inv = np.eye(k, dtype=np.uint8)
    for col in range(k):
        pivot = col
        while pivot < k and a[pivot, col] == 0:
            pivot += 1
        if pivot == k:
            raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        piv = gf_inv(int(a[col, col]))
        if piv != 1:
            a[col] = MUL_TABLE[piv][a[col]]
            inv[col] = MUL_TABLE[piv][inv[col]]
        for r in range(k):
            if r != col and a[r, col]:
                f = int(a[r, col])
                a[r] ^= MUL_TABLE[f][a[col]]
                inv[r] ^= MUL_TABLE[f][inv[col]]
    return inv
