"""RS coding-matrix generation and GF(2^8) linear algebra.

Matrix layouts follow the conventions of the reference's native libraries so
that coding chunks are byte-identical:

- ``gf_gen_rs_matrix`` / ``gf_gen_cauchy1_matrix`` reproduce the isa-l
  generators selected in the reference's isa plugin
  (src/erasure-code/isa/ErasureCodeIsa.cc:383-386): an (k+m) x k matrix whose
  top k rows are the identity (systematic code).
- ``jerasure_reed_sol_van_matrix`` reproduces jerasure's
  ``reed_sol_vandermonde_coding_matrix`` (the reed_sol_van technique,
  src/erasure-code/jerasure/ErasureCodeJerasure.cc:155): the m x k coding
  rows derived from an extended Vandermonde matrix reduced to systematic form.

Both libraries are empty submodules in the reference tree; these generators
are clean implementations of the published algorithms, validated by MDS
sweeps in tests/test_gf.py.
"""
from __future__ import annotations

import numpy as np

from .tables import gf_mul, gf_inv, gf_div, MUL_TABLE


def gf_gen_rs_matrix(rows: int, k: int) -> np.ndarray:
    """isa-l style systematic Vandermonde-ish matrix (rows x k).

    Row k+i is [g^0, g^1, ..] evaluated with a generator that doubles per
    row.  Only MDS for limited (k, m); the reference enforces k<=32, m<=4
    (k<=21 when m=4) — see ErasureCodeIsa.cc:330-361.
    """
    a = np.zeros((rows, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    gen = 1
    for i in range(k, rows):
        p = 1
        for j in range(k):
            a[i, j] = p
            p = gf_mul(p, gen)
        gen = gf_mul(gen, 2)
    return a


def gf_gen_cauchy1_matrix(rows: int, k: int) -> np.ndarray:
    """isa-l style systematic Cauchy matrix (rows x k): coding row i, col j
    = inv(i ^ j) for i in [k, rows)."""
    a = np.zeros((rows, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    for i in range(k, rows):
        for j in range(k):
            a[i, j] = gf_inv(i ^ j)
    return a


def _extended_vandermonde(rows: int, cols: int) -> np.ndarray:
    """jerasure's extended Vandermonde matrix: row 0 = e_0, last row =
    e_{cols-1}, middle rows i hold powers i^j (GF multiply chain)."""
    v = np.zeros((rows, cols), dtype=np.uint8)
    v[0, 0] = 1
    if rows == 1:
        return v
    v[rows - 1, cols - 1] = 1
    for i in range(1, rows - 1):
        acc = 1
        for j in range(cols):
            v[i, j] = acc
            acc = gf_mul(acc, i)
    return v


def jerasure_reed_sol_van_matrix(k: int, m: int) -> np.ndarray:
    """m x k coding matrix matching jerasure reed_sol_van (w=8).

    Builds the (k+m) x k extended Vandermonde matrix, then performs the same
    column-elimination sequence jerasure uses to force the top k x k block to
    identity; the bottom m rows are the coding matrix.
    """
    rows, cols = k + m, k
    dist = _extended_vandermonde(rows, cols)
    for i in range(1, cols):
        # pivot search in column i at/below row i
        j = i
        while j < rows and dist[j, i] == 0:
            j += 1
        if j >= rows:
            raise ValueError("singular extended Vandermonde matrix")
        if j > i:
            dist[[i, j], :] = dist[[j, i], :]
        # scale column i so dist[i, i] == 1
        if dist[i, i] != 1:
            inv = gf_div(1, int(dist[i, i]))
            for r in range(rows):
                dist[r, i] = gf_mul(inv, int(dist[r, i]))
        # eliminate the rest of row i by column ops
        for jj in range(cols):
            t = int(dist[i, jj])
            if jj != i and t != 0:
                for r in range(rows):
                    dist[r, jj] ^= gf_mul(t, int(dist[r, i]))
    return dist[k:, :].copy()


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product (small matrices; host-side)."""
    n, k = a.shape
    k2, mcols = b.shape
    assert k == k2
    out = np.zeros((n, mcols), dtype=np.uint8)
    for i in range(n):
        for j in range(mcols):
            acc = 0
            for t in range(k):
                acc ^= int(MUL_TABLE[a[i, t], b[t, j]])
            out[i, j] = acc
    return out


def gf_invert_matrix(m: np.ndarray) -> np.ndarray:
    """Invert a k x k matrix over GF(2^8) by Gauss-Jordan elimination."""
    k = m.shape[0]
    assert m.shape == (k, k)
    a = m.astype(np.uint8).copy()
    inv = np.eye(k, dtype=np.uint8)
    for col in range(k):
        pivot = col
        while pivot < k and a[pivot, col] == 0:
            pivot += 1
        if pivot == k:
            raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        piv = gf_inv(int(a[col, col]))
        if piv != 1:
            a[col] = MUL_TABLE[piv][a[col]]
            inv[col] = MUL_TABLE[piv][inv[col]]
        for r in range(k):
            if r != col and a[r, col]:
                f = int(a[r, col])
                a[r] ^= MUL_TABLE[f][a[col]]
                inv[r] ^= MUL_TABLE[f][inv[col]]
    return inv
