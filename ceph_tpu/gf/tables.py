"""GF(2^8) arithmetic core.

The field is GF(2^8) with the primitive polynomial x^8+x^4+x^3+x^2+1 (0x11d)
and generator element 2 — the same field used by both native EC libraries the
reference builds on (isa-l and jerasure/gf-complete, w=8), so chunk bytes
produced here are comparable byte-for-byte with the reference CPU paths
(reference: src/erasure-code/isa/ErasureCodeIsa.cc, jerasure plugin w=8).

Everything here is host-side numpy; the TPU path consumes only
``expand_to_bitmatrix`` output (GF(2) bit-matrices that turn the GF(2^8)
matrix multiply into a plain 0/1 matmul for the MXU — see
ceph_tpu/ops/gf_matmul.py).
"""
from __future__ import annotations

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1, primitive over GF(2)


def _build_tables():
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    # replicate so exp[log a + log b] needs no mod
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    log[0] = -1  # sentinel; never a valid index
    return exp, log


gf_exp, gf_log = _build_tables()


def gf_mul(a: int, b: int) -> int:
    """Scalar GF(2^8) multiply."""
    if a == 0 or b == 0:
        return 0
    return int(gf_exp[int(gf_log[a]) + int(gf_log[b])])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(gf_exp[(int(gf_log[a]) * n) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    return int(gf_exp[255 - int(gf_log[a])])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("gf_div by 0")
    if a == 0:
        return 0
    return int(gf_exp[int(gf_log[a]) - int(gf_log[b]) + 255])


def _build_mul_table():
    t = np.zeros((256, 256), dtype=np.uint8)
    la = gf_log.copy()
    for a in range(1, 256):
        idx = int(la[a]) + la[1:256]
        t[a, 1:256] = gf_exp[idx]
    return t


# MUL_TABLE[a][b] = a*b in GF(2^8).  64 KiB; the host codec's workhorse.
MUL_TABLE = _build_mul_table()


def gf_mul_scalar(coeff: int, data: np.ndarray) -> np.ndarray:
    """Multiply every byte of ``data`` (uint8 ndarray) by ``coeff``."""
    if coeff == 0:
        return np.zeros_like(data)
    if coeff == 1:
        return data.copy()
    return MUL_TABLE[coeff][data]


def gf_mult_bitmatrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix M such that bits(c*x) = M @ bits(x) (mod 2).

    Multiplication by a constant is GF(2)-linear; column j holds the bits of
    c * 2^j.  Bit order: index 0 = LSB.
    """
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        p = gf_mul(c, 1 << j)
        for i in range(8):
            m[i, j] = (p >> i) & 1
    return m


def expand_to_bitmatrix(coding: np.ndarray) -> np.ndarray:
    """Expand an (m, k) GF(2^8) coefficient matrix to an (k*8, m*8) GF(2)
    matrix B so that for data-bit row vectors d (length k*8, LSB-first per
    byte), the coding bits are ``(d @ B) mod 2``.

    This is the bridge from GF(2^8) RS coding to a plain 0/1 matmul that XLA
    tiles straight onto the TPU MXU (int8/bf16 matmul + parity).
    """
    mm, kk = coding.shape
    out = np.zeros((kk * 8, mm * 8), dtype=np.uint8)
    for r in range(mm):
        for c in range(kk):
            bm = gf_mult_bitmatrix(int(coding[r, c]))  # bits(out) = bm @ bits(in)
            # out_bit[r*8+i] += in_bit[c*8+j] * bm[i, j]
            out[c * 8:(c + 1) * 8, r * 8:(r + 1) * 8] = bm.T
    return out
