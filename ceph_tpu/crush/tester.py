"""CrushTester — the crushtool --test engine.

Mirrors the reference harness (src/crush/CrushTester.{h,cc}: test() at
:472): sweep x over [min_x, max_x] for each rule and numrep in the rule's
mask range, with per-device utilization statistics, bad-mapping detection,
and adjustable device weights (--weight).  The sweep itself runs through
the batch mapper stack (device fast path → host), so the harness doubles
as the device/host parity oracle the reference uses golden files for.
"""
from __future__ import annotations

import sys
from collections import defaultdict
from typing import Dict, List, Optional, TextIO

import numpy as np

from .constants import CRUSH_ITEM_NONE
from .mapper import crush_do_rule
from .wrapper import CrushWrapper


class CrushTester:
    def __init__(self, crush: CrushWrapper, out: TextIO = sys.stdout):
        self.crush = crush
        self.out = out
        self.min_rule = -1
        self.max_rule = -1
        self.min_x = -1
        self.max_x = -1
        self.min_rep = -1
        self.max_rep = -1
        self.ruleset = -1
        self.device_weight: Dict[int, int] = {}
        self.output_statistics = False
        self.output_mappings = False
        self.output_bad_mappings = False
        self.output_utilization = False
        self.use_device = True
        self.bad_mappings = 0

    # ---- knobs (crushtool flags) ------------------------------------------
    def set_output_statistics(self, b: bool) -> None:
        self.output_statistics = b

    def set_output_mappings(self, b: bool) -> None:
        self.output_mappings = b

    def set_output_bad_mappings(self, b: bool) -> None:
        self.output_bad_mappings = b

    def set_output_utilization(self, b: bool) -> None:
        self.output_utilization = b

    def set_min_x(self, x: int) -> None:
        self.min_x = x

    def set_max_x(self, x: int) -> None:
        self.max_x = x

    def set_num_rep(self, n: int) -> None:
        self.min_rep = self.max_rep = n

    def set_rule(self, r: int) -> None:
        self.min_rule = self.max_rule = r

    def set_device_weight(self, dev: int, weight_f: float) -> None:
        w = int(weight_f * 0x10000)
        self.device_weight[dev] = max(0, min(0x10000, w))

    def _weights(self) -> List[int]:
        weight = []
        present = set()
        for b in self.crush.crush.buckets:
            if b is not None:
                present.update(i for i in b.items if i >= 0)
        for o in range(self.crush.get_max_devices()):
            if o in self.device_weight:
                weight.append(self.device_weight[o])
            elif o in present:
                weight.append(0x10000)
            else:
                weight.append(0)
        return weight

    def _map_batch(self, ruleno: int, xs, numrep: int, weight) -> np.ndarray:
        if self.use_device:
            try:
                from ..ops.crush_fast import compile_fast_rule
                fr = compile_fast_rule(self.crush.crush, ruleno, numrep)
                res, cnt = fr.map_batch(np.asarray(xs, dtype=np.uint32),
                                        np.asarray(weight, dtype=np.uint32))
                return res, cnt
            except Exception:
                pass
        out = np.full((len(xs), numrep), CRUSH_ITEM_NONE, dtype=np.int32)
        cnt = np.zeros(len(xs), dtype=np.int32)
        for i, x in enumerate(xs):
            r = crush_do_rule(self.crush.crush, ruleno, int(x), numrep,
                              weight)
            out[i, :len(r)] = r
            cnt[i] = len(r)
        return out, cnt

    # ---- the sweep --------------------------------------------------------
    def test(self) -> int:
        crush = self.crush
        min_rule = self.min_rule if self.min_rule >= 0 else 0
        max_rule = self.max_rule if self.max_rule >= 0 \
            else crush.crush.max_rules - 1
        min_x = self.min_x if self.min_x >= 0 else 0
        max_x = self.max_x if self.max_x >= 0 else 1023
        weight = self._weights()
        xs = list(range(min_x, max_x + 1))
        self.bad_mappings = 0

        for r in range(min_rule, max_rule + 1):
            if not crush.rule_exists(r):
                if self.output_statistics:
                    print(f"rule {r} dne", file=self.out)
                continue
            rule = crush.crush.rules[r]
            if self.ruleset >= 0 and rule.ruleset != self.ruleset:
                continue
            if self.min_rep < 0 or self.max_rep < 0:
                minr, maxr = rule.min_size, rule.max_size
            else:
                minr, maxr = self.min_rep, self.max_rep
            if self.output_statistics:
                print(f"rule {r} ({crush.rule_name_map.get(r, r)}), "
                      f"x = {min_x}..{max_x}, numrep = {minr}..{maxr}",
                      file=self.out)
            for nr in range(minr, maxr + 1):
                res, cnt = self._map_batch(r, xs, nr, weight)
                per = np.zeros(crush.get_max_devices(), dtype=np.int64)
                sizes: Dict[int, int] = defaultdict(int)
                for i, x in enumerate(xs):
                    row = [int(o) for o in res[i, :cnt[i]]
                           if o != CRUSH_ITEM_NONE]
                    sizes[len(row)] += 1
                    if len(row) != nr and (self.output_bad_mappings
                                           or self.output_statistics):
                        self.bad_mappings += 1
                        print(f"bad mapping rule {r} x {x} num_rep {nr} "
                              f"result {row}", file=self.out)
                    for o in row:
                        per[o] += 1
                    if self.output_mappings:
                        print(f"CRUSH rule {r} x {x} {row}", file=self.out)
                if self.output_statistics:
                    for sz in sorted(sizes):
                        n = sizes[sz]
                        frac = n / len(xs)
                        print(f"rule {r} ({crush.rule_name_map.get(r, r)})"
                              f" num_rep {nr} result size == {sz}:\t"
                              f"{n}/{len(xs)} ({frac:.6g})", file=self.out)
                if self.output_utilization:
                    total = int(per.sum())
                    for o in range(len(per)):
                        if weight[o] or per[o]:
                            expected = (total * weight[o]
                                        / max(1, sum(weight)))
                            print(f"  device {o}:\t\tstored : {per[o]}\t"
                                  f" expected : {expected:.6g}",
                                  file=self.out)
        return 0

    def check_overlapped_rules(self) -> int:
        """Warn when rulesets overlap (crushtool --check analog)."""
        seen = {}
        overlaps = 0
        for i, rule in enumerate(self.crush.crush.rules):
            if rule is None:
                continue
            key = (rule.ruleset, rule.type)
            prev = seen.get(key)
            if prev is not None:
                pr = self.crush.crush.rules[prev]
                if not (rule.min_size > pr.max_size
                        or rule.max_size < pr.min_size):
                    print(f"overlapped rules {prev} and {i} in ruleset "
                          f"{rule.ruleset}", file=self.out)
                    overlaps += 1
            else:
                seen[key] = i
        return -22 if overlaps else 0
