"""CrushTester — the crushtool --test engine.

Mirrors the reference harness (src/crush/CrushTester.{h,cc}: test() at
:472): sweep x over [min_x, max_x] for each rule and numrep in the rule's
mask range, with per-device utilization statistics (float32 expected-
object math like the reference's `vector<float>`), bad-mapping
detection, adjustable device weights (--weight), the choose-tries
histogram (mapper profile), and the --output-csv data files.  The
sweep itself runs through the batch mapper stack (device fast path →
host), so the harness doubles as the device/host parity oracle the
reference uses golden files for.
"""
from __future__ import annotations

import sys
from collections import defaultdict
from typing import Dict, List, Optional, TextIO

import numpy as np

from .constants import CRUSH_ITEM_NONE
from .mapper import crush_do_rule
from .wrapper import CrushWrapper


class CrushTester:
    def __init__(self, crush: CrushWrapper, out: TextIO = sys.stdout):
        self.crush = crush
        self.out = out
        self.min_rule = -1
        self.max_rule = -1
        self.min_x = -1
        self.max_x = -1
        self.min_rep = -1
        self.max_rep = -1
        self.ruleset = -1
        self.device_weight: Dict[int, int] = {}
        self.output_statistics = False
        self.output_mappings = False
        self.output_bad_mappings = False
        self.output_utilization = False
        self.output_utilization_all = False
        self.output_choose_tries = False
        self.output_csv = False
        self.output_name = ""
        self.use_device = True
        self.bad_mappings = 0
        self.pool_id = -1          # --pool-id: real_x = H(x, pool)
        self.num_batches = 1       # --batches (batch CSV rounds)
        self.simulate = False      # --simulate: RNG placement

    # ---- knobs (crushtool flags) ------------------------------------------
    def set_output_statistics(self, b: bool) -> None:
        self.output_statistics = b

    def set_output_mappings(self, b: bool) -> None:
        self.output_mappings = b

    def set_output_bad_mappings(self, b: bool) -> None:
        self.output_bad_mappings = b

    def set_output_utilization(self, b: bool) -> None:
        self.output_utilization = b

    def set_output_utilization_all(self, b: bool) -> None:
        self.output_utilization_all = b

    def set_output_choose_tries(self, b: bool) -> None:
        self.output_choose_tries = b

    def set_output_csv(self, b: bool, name: str = "") -> None:
        self.output_csv = b
        self.output_name = name

    def set_pool_id(self, pid: int) -> None:
        self.pool_id = pid

    def set_batches(self, n: int) -> None:
        self.num_batches = max(1, n)

    def set_simulate(self, b: bool) -> None:
        self.simulate = b

    def set_min_x(self, x: int) -> None:
        self.min_x = x

    def set_max_x(self, x: int) -> None:
        self.max_x = x

    def set_num_rep(self, n: int) -> None:
        self.min_rep = self.max_rep = n

    def set_rule(self, r: int) -> None:
        self.min_rule = self.max_rule = r

    def set_min_rule(self, r: int) -> None:
        self.min_rule = r

    def set_max_rule(self, r: int) -> None:
        self.max_rule = r

    def set_ruleset(self, rs: int) -> None:
        self.ruleset = rs

    def set_device_weight(self, dev: int, weight_f: float) -> None:
        w = int(weight_f * 0x10000)
        self.device_weight[dev] = max(0, min(0x10000, w))

    def _weights(self) -> List[int]:
        weight = []
        present = set()
        for b in self.crush.crush.buckets:
            if b is not None:
                present.update(i for i in b.items if i >= 0)
        for o in range(self.crush.get_max_devices()):
            if o in self.device_weight:
                weight.append(self.device_weight[o])
            elif o in present:
                weight.append(0x10000)
            else:
                weight.append(0)
        return weight

    def _map_batch(self, ruleno: int, xs, numrep: int, weight) -> np.ndarray:
        if self.pool_id >= 0:
            # CrushTester.cc:614-617: the tested input is the pool-
            # salted hash of x, like real PG placement seeds
            from .hash import crush_hash32_2
            xs = [crush_hash32_2(int(x), self.pool_id) for x in xs]
        if self.simulate:
            # random_placement: weighted draws without replacement —
            # the RNG baseline the reference compares CRUSH against
            rng = np.random.default_rng()
            w = np.asarray(weight, dtype=np.float64)
            out = np.full((len(xs), numrep), CRUSH_ITEM_NONE,
                          dtype=np.int32)
            cnt = np.zeros(len(xs), dtype=np.int32)
            p = w / w.sum() if w.sum() else None
            for i in range(len(xs)):
                k = min(numrep, int((w > 0).sum()))
                picks = rng.choice(len(w), size=k, replace=False, p=p)
                out[i, :k] = picks
                cnt[i] = k
            return out, cnt
        if self.use_device and not self.output_choose_tries:
            try:
                from ..ops.crush_fast import compile_fast_rule
                fr = compile_fast_rule(self.crush.crush, ruleno, numrep)
                res, cnt = fr.map_batch(np.asarray(xs, dtype=np.uint32),
                                        np.asarray(weight, dtype=np.uint32))
                return res, cnt
            except Exception:
                pass
        out = np.full((len(xs), numrep), CRUSH_ITEM_NONE, dtype=np.int32)
        cnt = np.zeros(len(xs), dtype=np.int32)
        for i, x in enumerate(xs):
            r = crush_do_rule(self.crush.crush, ruleno, int(x), numrep,
                              weight)
            out[i, :len(r)] = r
            cnt[i] = len(r)
        return out, cnt

    def _max_affected_by_rule(self, ruleno: int) -> int:
        """CrushTester::get_maximum_affected_by_rule (:34): the
        smallest bucket-type population a choose step constrains the
        result to."""
        cw = self.crush
        rule = cw.crush.rules[ruleno]
        affected: List[int] = []
        reps: Dict[int, int] = {}
        for step in rule.steps:
            if step.op >= 2 and step.op != 4:    # choose* ops
                affected.append(step.arg2)
                reps[step.arg2] = step.arg1
        count: Dict[int, int] = defaultdict(int)
        for t in affected:
            for item in cw.name_map:
                btype = cw.crush.bucket(item).type if item < 0 else 0
                if btype == t:
                    count[t] += 1
        for t in affected:
            if 0 < reps.get(t, 0) < count[t]:
                count[t] = reps[t]
        max_affected = max(len(cw.crush.buckets),
                           cw.get_max_devices())
        for t in affected:
            if 0 < count[t] < max_affected:
                max_affected = count[t]
        return max_affected

    # ---- the sweep --------------------------------------------------------
    def test(self) -> int:
        crush = self.crush
        min_rule = self.min_rule if self.min_rule >= 0 else 0
        max_rule = self.max_rule if self.max_rule >= 0 \
            else crush.crush.max_rules - 1
        min_x = self.min_x if self.min_x >= 0 else 0
        max_x = self.max_x if self.max_x >= 0 else 1023
        weight = self._weights()
        total_weight = sum(weight)
        xs = list(range(min_x, max_x + 1))
        num_objects = len(xs)
        self.bad_mappings = 0
        if self.output_choose_tries:
            # start_choose_profile: tries histogram, +1 for the
            # off-by-one retries->tries adjustment
            crush.crush.choose_tries = \
                [0] * (crush.crush.choose_total_tries + 1)

        for r in range(min_rule, max_rule + 1):
            if not crush.rule_exists(r):
                if self.output_statistics:
                    print(f"rule {r} dne", file=self.out)
                continue
            rule = crush.crush.rules[r]
            if self.ruleset >= 0 and rule.ruleset != self.ruleset:
                continue
            if self.min_rep < 0 or self.max_rep < 0:
                minr, maxr = rule.min_size, rule.max_size
            else:
                minr, maxr = self.min_rep, self.max_rep
            if self.output_statistics:
                print(f"rule {r} ({crush.rule_name_map.get(r, r)}), "
                      f"x = {min_x}..{max_x}, numrep = {minr}..{maxr}",
                      file=self.out)
            for nr in range(minr, maxr + 1):
                res, cnt = self._map_batch(r, xs, nr, weight)
                per = np.zeros(crush.get_max_devices(), dtype=np.int64)
                sizes: Dict[int, int] = defaultdict(int)
                placement: List[List[int]] = []
                for i, x in enumerate(xs):
                    # the RAW result vector, CRUSH_ITEM_NONE slots
                    # included — indep holes print as 2147483647,
                    # count toward out.size(), and flag bad mappings
                    # (CrushTester.cc:631-646)
                    raw = [int(o) for o in res[i, :cnt[i]]]
                    row = [o for o in raw if o != CRUSH_ITEM_NONE]
                    placement.append(raw)
                    sizes[len(raw)] += 1
                    vec = "[" + ",".join(str(o) for o in raw) + "]"
                    bad = len(raw) != nr or len(row) != len(raw)
                    if bad and self.output_bad_mappings:
                        print(f"bad mapping rule {r} x {x} num_rep "
                              f"{nr} result {vec}", file=self.out)
                    if bad:
                        self.bad_mappings += 1
                    for o in row:
                        per[o] += 1
                    if self.output_mappings:
                        print(f"CRUSH rule {r} x {x} {vec}",
                              file=self.out)
                # expected-objects math in float32, like the
                # reference's vector<float> (CrushTester.cc:562-593)
                expected_objects = np.float32(
                    min(nr, self._max_affected_by_rule(r))
                    * num_objects)
                prop = np.zeros(len(per), dtype=np.float32)
                if total_weight:
                    prop = (np.asarray(weight, dtype=np.float32)
                            / np.float32(total_weight))
                n_expected = prop * expected_objects
                if (self.output_utilization
                        and not self.output_statistics):
                    for o in range(len(per)):
                        print(f"  device {o}:\t{per[o]}",
                              file=self.out)
                if self.output_statistics:
                    for sz in sorted(sizes):
                        n = sizes[sz]
                        print(f"rule {r} ({crush.rule_name_map.get(r, r)})"
                              f" num_rep {nr} result size == {sz}:\t"
                              f"{n}/{len(xs)}", file=self.out)
                if self.output_statistics:
                    for o in range(len(per)):
                        e = float(n_expected[o])
                        if self.output_utilization:
                            if e > 0 and per[o] > 0:
                                print(f"  device {o}:\t\t stored "
                                      f": {per[o]}\t expected : {e:g}",
                                      file=self.out)
                        elif self.output_utilization_all:
                            print(f"  device {o}:\t\t stored "
                                  f": {per[o]}\t expected : {e:g}",
                                  file=self.out)
                if self.output_csv:
                    batch_per = None
                    if self.num_batches > 1:
                        # per-round device counts (batch_per), split
                        # the way the reference's batch loop does
                        opb = max(1, num_objects // self.num_batches)
                        batch_per = []
                        for bi in range(self.num_batches):
                            lo = bi * opb
                            hi = num_objects if \
                                bi == self.num_batches - 1 \
                                else (bi + 1) * opb
                            bp = np.zeros(len(per), dtype=np.int64)
                            for row in placement[lo:hi]:
                                for o in row:
                                    if o != CRUSH_ITEM_NONE:
                                        bp[o] += 1
                            batch_per.append(bp)
                    self._write_csv(
                        self.output_name
                        + crush.rule_name_map.get(r, str(r)),
                        nr, per, n_expected, prop, placement, min_x,
                        weight, batch_per)
        if self.output_choose_tries:
            prof = crush.crush.choose_tries or []
            # get_choose_profile returns choose_total_tries entries
            for i in range(crush.crush.choose_total_tries):
                v = prof[i] if i < len(prof) else 0
                print(f"{i:>2}: {v:>9}", file=self.out)
            crush.crush.choose_tries = None
        return 0

    def _write_csv(self, tag: str, nr: int, per, n_expected, prop,
                   placement, min_x: int, weight,
                   batch_per=None) -> None:
        """write_data_set_to_csv (CrushTester.h:104): the six
        non-batch data files with the reference's headers.  (The
        batch files require --batches > 1, like the reference.)"""
        hdr_util = ("Device ID, Number of Objects Stored, "
                    "Number of Objects Expected\n")
        with open(f"{tag}-device_utilization_all.csv", "w") as f:
            f.write(hdr_util)
            for o in range(len(per)):
                f.write(f"{o},{per[o]},{float(n_expected[o]):g}\n")
        with open(f"{tag}-device_utilization.csv", "w") as f:
            f.write(hdr_util)
            for o in range(len(per)):
                if n_expected[o] > 0 and per[o] > 0:
                    f.write(f"{o},{per[o]},"
                            f"{float(n_expected[o]):g}\n")
        with open(f"{tag}-placement_information.csv", "w") as f:
            f.write("Input" + "".join(f", OSD{i}" for i in range(nr))
                    + "\n")
            for i, row in enumerate(placement):
                f.write(f"{min_x + i},"
                        + ",".join(str(o) for o in row) + "\n")
        with open(f"{tag}-proportional_weights.csv", "w") as f:
            f.write("Device ID, Proportional Weight\n")
            for o in range(len(prop)):
                if prop[o] > 0:
                    f.write(f"{o},{float(prop[o]):g}\n")
        with open(f"{tag}-proportional_weights_all.csv", "w") as f:
            f.write("Device ID, Proportional Weight\n")
            for o in range(len(prop)):
                f.write(f"{o},{float(prop[o]):g}\n")
        with open(f"{tag}-absolute_weights.csv", "w") as f:
            f.write("Device ID, Absolute Weight\n")
            for o in range(len(weight)):
                f.write(f"{o},{weight[o] / 0x10000:g}\n")
        if batch_per is not None:
            # the two batch files exist only with --batches > 1
            # (write_data_set_to_csv's num_batches guard)
            nd = len(per)
            hdr = "Batch Round" + "".join(
                f", Objects Stored on OSD{i}" for i in range(nd))
            with open(f"{tag}-batch_device_utilization_all.csv",
                      "w") as f:
                f.write(hdr + "\n")
                for bi, bp in enumerate(batch_per):
                    f.write(f"{bi},"
                            + ",".join(str(v) for v in bp) + "\n")
            hdr = "Batch Round" + "".join(
                f", Objects Expected on OSD{i}" for i in range(nd))
            with open(
                    f"{tag}-batch_device_expected_utilization_all"
                    f".csv", "w") as f:
                f.write(hdr + "\n")
                for bi, bp in enumerate(batch_per):
                    f.write(f"{bi},"
                            + ",".join(str(v) for v in bp) + "\n")

    def check_overlapped_rules(self) -> int:
        """Warn when rulesets overlap (crushtool --check analog)."""
        seen = {}
        overlaps = 0
        for i, rule in enumerate(self.crush.crush.rules):
            if rule is None:
                continue
            key = (rule.ruleset, rule.type)
            prev = seen.get(key)
            if prev is not None:
                pr = self.crush.crush.rules[prev]
                if not (rule.min_size > pr.max_size
                        or rule.max_size < pr.min_size):
                    print(f"overlapped rules {prev} and {i} in ruleset "
                          f"{rule.ruleset}", file=self.out)
                    overlaps += 1
            else:
                seen[key] = i
        return -22 if overlaps else 0
