"""The CRUSH rule interpreter — exact host implementation.

Reproduces crush_do_rule's semantics step for step (reference
src/crush/mapper.c:883-1087, crush_choose_firstn :443, crush_choose_indep
:638, bucket choosers :58-367) so that mappings are bit-identical: the same
rjenkins hashes, the same fixed-point straw2 draw (crush_ln LUT + s64
truncated division), the same r' = r + ftotal retry sequences, collision and
out-rejection logic, and the same firstn/indep output conventions
(CRUSH_ITEM_NONE padding for indep).

This is the oracle the vmapped device mapper (ceph_tpu/ops/crush_kernels.py)
is tested against.  It is deliberately written for clarity+exactness, not
speed; batch host mapping uses numpy vectorization at the OSDMap layer and
the TPU path for scale.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from .constants import (
    CRUSH_BUCKET_UNIFORM, CRUSH_BUCKET_LIST, CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE, CRUSH_ITEM_UNDEF,
    CRUSH_RULE_TAKE, CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_EMIT, CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES, CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R, CRUSH_RULE_SET_CHOOSELEAF_STABLE,
    S64_MIN,
)
from .hash import crush_hash32_2, crush_hash32_3, crush_hash32_4
from .ln import crush_ln
from .types import Bucket, CrushMap, ChooseArg


def crush_find_rule(map: CrushMap, ruleset: int, type: int, size: int) -> int:
    for i, r in enumerate(map.rules):
        if (r is not None and r.ruleset == ruleset and r.type == type
                and r.min_size <= size <= r.max_size):
            return i
    return -1


# ---- bucket choosers ------------------------------------------------------

def _perm_choose(bucket: Bucket, x: int, r: int) -> int:
    """Pseudo-random permutation choose (uniform buckets).

    The reference memoizes the permutation in a workspace
    (mapper.c:76-131); the permutation itself is a deterministic
    Fisher-Yates keyed on (bucket, x), so recomputing the prefix gives
    identical results.
    """
    size = bucket.size
    pr = r % size
    perm = list(range(size))
    for p in range(pr + 1):
        if p < size - 1:
            i = crush_hash32_3(x, bucket.id, p) % (size - p)
            if i:
                perm[p], perm[p + i] = perm[p + i], perm[p]
    return bucket.items[perm[pr]]


def _list_choose(bucket, x: int, r: int) -> int:
    for i in range(bucket.size - 1, -1, -1):
        w = crush_hash32_4(x, bucket.items[i], r, bucket.id)
        w &= 0xFFFF
        w = (w * bucket.sum_weights[i]) >> 16
        if w < bucket.item_weights[i]:
            return bucket.items[i]
    return bucket.items[0]


def _tree_choose(bucket, x: int, r: int) -> int:
    n = bucket.num_nodes >> 1
    while not (n & 1):
        w = bucket.node_weights[n]
        t = (crush_hash32_4(x, n, r, bucket.id) * w) >> 32
        # descend: left child is n - 2^(h-1), right is n + 2^(h-1)
        h = (n & -n).bit_length() - 1
        left = n - (1 << (h - 1))
        if t < bucket.node_weights[left]:
            n = left
        else:
            n = left + (1 << h)
    return bucket.items[n >> 1]


def _straw_choose(bucket, x: int, r: int) -> int:
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        draw = crush_hash32_3(x, bucket.items[i], r) & 0xFFFF
        draw *= bucket.straws[i]
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def _straw2_choose(bucket, x: int, r: int,
                   arg: Optional[ChooseArg], position: int) -> int:
    weights = bucket.item_weights
    ids = bucket.items
    if arg is not None:
        if arg.weight_set:
            pos = min(position, len(arg.weight_set) - 1)
            weights = arg.weight_set[pos].weights
        if arg.ids:
            ids = arg.ids
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        w = weights[i]
        if w:
            u = crush_hash32_3(x, ids[i], r) & 0xFFFF
            ln = crush_ln(u) - 0x1000000000000
            # s64 division truncating toward zero; ln <= 0, w > 0
            draw = -((-ln) // w)
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def _bucket_choose(map: CrushMap, bucket: Bucket, x: int, r: int,
                   choose_args, position: int) -> int:
    assert bucket.size > 0
    if bucket.alg == CRUSH_BUCKET_UNIFORM:
        return _perm_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_LIST:
        return _list_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_TREE:
        return _tree_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_STRAW:
        return _straw_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_STRAW2:
        arg = None
        if choose_args is not None:
            bno = -1 - bucket.id
            if bno < len(choose_args):
                arg = choose_args[bno]
        return _straw2_choose(bucket, x, r, arg, position)
    return bucket.items[0]


def _is_out(map: CrushMap, weight: Sequence[int], item: int, x: int) -> bool:
    if item >= len(weight):
        return True
    w = weight[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (crush_hash32_2(x, item) & 0xFFFF) >= w


# ---- choose: firstn -------------------------------------------------------

def _choose_firstn(map: CrushMap, bucket: Bucket, weight, x: int,
                   numrep: int, type: int, out: List[int], outpos: int,
                   out_size: int, tries: int, recurse_tries: int,
                   local_retries: int, local_fallback_retries: int,
                   recurse_to_leaf: bool, vary_r: int, stable: int,
                   out2: Optional[List[int]], parent_r: int,
                   choose_args) -> int:
    count = out_size
    rep = 0 if stable else outpos
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        retry_descent = True
        item = 0
        while retry_descent:
            retry_descent = False
            in_bucket = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                collide = False
                r = rep + parent_r + ftotal
                if in_bucket.size == 0:
                    reject = True
                else:
                    if (local_fallback_retries > 0
                            and flocal >= (in_bucket.size >> 1)
                            and flocal > local_fallback_retries):
                        item = _perm_choose(in_bucket, x, r)
                    else:
                        item = _bucket_choose(map, in_bucket, x, r,
                                              choose_args, outpos)
                    if item >= map.max_devices:
                        skip_rep = True
                        break
                    itemtype = map.bucket(item).type if item < 0 else 0
                    if itemtype != type:
                        sub = map.bucket(item) if item < 0 else None
                        if sub is None:
                            skip_rep = True
                            break
                        in_bucket = sub
                        retry_bucket = True
                        continue
                    # collision?
                    for i in range(outpos):
                        if out[i] == item:
                            collide = True
                            break
                    reject = False
                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = r >> (vary_r - 1) if vary_r else 0
                            if _choose_firstn(
                                    map, map.bucket(item), weight, x,
                                    1 if stable else outpos + 1, 0,
                                    out2, outpos, count,
                                    recurse_tries, 0,
                                    local_retries, local_fallback_retries,
                                    False, vary_r, stable, None, sub_r,
                                    choose_args) <= outpos:
                                reject = True
                        else:
                            out2[outpos] = item
                    if not reject and not collide and itemtype == 0:
                        reject = _is_out(map, weight, item, x)
                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (local_fallback_retries > 0
                          and flocal <= in_bucket.size + local_fallback_retries):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                        break
                    else:
                        skip_rep = True
                        break
        if not skip_rep:
            out[outpos] = item
            outpos += 1
            count -= 1
            # choose-tries profile (mapper.c:624: userspace-only
            # histogram behind crush.start_choose_profile)
            prof = getattr(map, "choose_tries", None)
            if prof is not None and ftotal <= map.choose_total_tries:
                prof[ftotal] += 1
        rep += 1
    return outpos


# ---- choose: indep --------------------------------------------------------

def _choose_indep(map: CrushMap, bucket: Bucket, weight, x: int,
                  left: int, numrep: int, type: int,
                  out: List[int], outpos: int, tries: int,
                  recurse_tries: int, recurse_to_leaf: bool,
                  out2: Optional[List[int]], parent_r: int,
                  choose_args) -> None:
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = CRUSH_ITEM_UNDEF
        if out2 is not None:
            out2[rep] = CRUSH_ITEM_UNDEF
    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != CRUSH_ITEM_UNDEF:
                continue
            in_bucket = bucket
            while True:
                r = rep + parent_r
                if (in_bucket.alg == CRUSH_BUCKET_UNIFORM
                        and in_bucket.size % numrep == 0):
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal
                if in_bucket.size == 0:
                    break
                item = _bucket_choose(map, in_bucket, x, r,
                                      choose_args, outpos)
                if item >= map.max_devices:
                    out[rep] = CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[rep] = CRUSH_ITEM_NONE
                    left -= 1
                    break
                itemtype = map.bucket(item).type if item < 0 else 0
                if itemtype != type:
                    sub = map.bucket(item) if item < 0 else None
                    if sub is None:
                        out[rep] = CRUSH_ITEM_NONE
                        if out2 is not None:
                            out2[rep] = CRUSH_ITEM_NONE
                        left -= 1
                        break
                    in_bucket = sub
                    continue
                collide = False
                for i in range(outpos, endpos):
                    if out[i] == item:
                        collide = True
                        break
                if collide:
                    break
                if recurse_to_leaf:
                    if item < 0:
                        _choose_indep(map, map.bucket(item), weight, x,
                                      1, numrep, 0, out2, rep,
                                      recurse_tries, 0, False, None, r,
                                      choose_args)
                        if out2[rep] == CRUSH_ITEM_NONE:
                            break
                    else:
                        out2[rep] = item
                if itemtype == 0 and _is_out(map, weight, item, x):
                    break
                out[rep] = item
                left -= 1
                break
        ftotal += 1
    for rep in range(outpos, endpos):
        if out[rep] == CRUSH_ITEM_UNDEF:
            out[rep] = CRUSH_ITEM_NONE
        if out2 is not None and out2[rep] == CRUSH_ITEM_UNDEF:
            out2[rep] = CRUSH_ITEM_NONE
    # choose-tries profile: indep records once per invocation with
    # the loop-exit ftotal (mapper.c:809)
    prof = getattr(map, "choose_tries", None)
    if prof is not None and ftotal <= map.choose_total_tries:
        prof[ftotal] += 1


# ---- do_rule --------------------------------------------------------------

def crush_do_rule(map: CrushMap, ruleno: int, x: int, result_max: int,
                  weight: Sequence[int],
                  choose_args: Optional[List[ChooseArg]] = None) -> List[int]:
    """Evaluate rule *ruleno* for input *x*; returns the result vector."""
    if ruleno < 0 or ruleno >= map.max_rules or map.rules[ruleno] is None:
        return []
    rule = map.rules[ruleno]

    result: List[int] = []
    w: List[int] = [0] * result_max
    o: List[int] = [0] * result_max
    c: List[int] = [0] * result_max
    wsize = 0

    # off-by-one adjustment: stored tunable counts "retries" (mapper.c:905)
    choose_tries = map.choose_total_tries + 1
    choose_leaf_tries = 0
    choose_local_retries = map.choose_local_tries
    choose_local_fallback_retries = map.choose_local_fallback_tries
    vary_r = map.chooseleaf_vary_r
    stable = map.chooseleaf_stable

    for step in rule.steps:
        op = step.op
        if op == CRUSH_RULE_TAKE:
            valid_dev = 0 <= step.arg1 < map.max_devices
            valid_bucket = map.bucket(step.arg1) is not None
            if valid_dev or valid_bucket:
                w[0] = step.arg1
                wsize = 1
        elif op == CRUSH_RULE_SET_CHOOSE_TRIES:
            if step.arg1 > 0:
                choose_tries = step.arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            if step.arg1 > 0:
                choose_leaf_tries = step.arg1
        elif op == CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
            if step.arg1 >= 0:
                choose_local_retries = step.arg1
        elif op == CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if step.arg1 >= 0:
                choose_local_fallback_retries = step.arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
            if step.arg1 >= 0:
                vary_r = step.arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
            if step.arg1 >= 0:
                stable = step.arg1
        elif op in (CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSE_FIRSTN,
                    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_INDEP):
            if wsize == 0:
                continue
            firstn = op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                            CRUSH_RULE_CHOOSE_FIRSTN)
            recurse_to_leaf = op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                     CRUSH_RULE_CHOOSELEAF_INDEP)
            osize = 0
            for i in range(wsize):
                numrep = step.arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                bucket = map.bucket(w[i])
                if bucket is None:
                    continue  # w[i] is probably CRUSH_ITEM_NONE
                # the reference passes offset pointers (o+osize, c+osize);
                # sub-lists indexed from 0 reproduce that exactly
                room = result_max - osize
                sub_o = [0] * room
                sub_c = [0] * room
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif map.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    n = _choose_firstn(
                        map, bucket, weight, x, numrep, step.arg2,
                        sub_o, 0, room,
                        choose_tries, recurse_tries,
                        choose_local_retries,
                        choose_local_fallback_retries,
                        recurse_to_leaf, vary_r, stable, sub_c, 0,
                        choose_args)
                    o[osize:osize + n] = sub_o[:n]
                    c[osize:osize + n] = sub_c[:n]
                    osize += n
                else:
                    out_size = min(numrep, room)
                    _choose_indep(
                        map, bucket, weight, x, out_size, numrep,
                        step.arg2, sub_o, 0, choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf, sub_c, 0, choose_args)
                    o[osize:osize + out_size] = sub_o[:out_size]
                    c[osize:osize + out_size] = sub_c[:out_size]
                    osize += out_size
            if recurse_to_leaf:
                o[:osize] = c[:osize]
            w, o = o, w
            wsize = osize
        elif op == CRUSH_RULE_EMIT:
            for i in range(wsize):
                if len(result) >= result_max:
                    break
                result.append(w[i])
            wsize = 0
    return result
