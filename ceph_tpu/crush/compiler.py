"""CrushCompiler — crushmap text format ⇄ CrushWrapper.

Implements the reference's textual map grammar (src/crush/CrushCompiler.cc,
grammar in src/crush/grammar.h): tunable lines, device lines (with device
class), type lines, bucket blocks (id/alg/hash/item weight), and rule
blocks (ruleset/type/min_size/max_size/step...).  compile() parses text
into a CrushWrapper; decompile() emits text that re-compiles to the same
map — the crushtool -c/-d round-trip contract.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, TextIO

from .constants import (
    CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM,
    CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE, CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE, PG_POOL_TYPE_ERASURE, PG_POOL_TYPE_REPLICATED,
)
from .types import Rule, RuleStep
from .wrapper import CrushWrapper

ALG_NAMES = {
    CRUSH_BUCKET_UNIFORM: "uniform",
    CRUSH_BUCKET_LIST: "list",
    CRUSH_BUCKET_TREE: "tree",
    CRUSH_BUCKET_STRAW: "straw",
    CRUSH_BUCKET_STRAW2: "straw2",
}
ALG_IDS = {v: k for k, v in ALG_NAMES.items()}

RULE_TYPE_NAMES = {PG_POOL_TYPE_REPLICATED: "replicated",
                   PG_POOL_TYPE_ERASURE: "erasure"}
RULE_TYPE_IDS = {v: k for k, v in RULE_TYPE_NAMES.items()}

STEP_SET_OPS = {
    "set_choose_tries": CRUSH_RULE_SET_CHOOSE_TRIES,
    "set_chooseleaf_tries": CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    "set_choose_local_tries": CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    "set_choose_local_fallback_tries":
        CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    "set_chooseleaf_vary_r": CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    "set_chooseleaf_stable": CRUSH_RULE_SET_CHOOSELEAF_STABLE,
}
STEP_SET_NAMES = {v: k for k, v in STEP_SET_OPS.items()}

TUNABLES = ("choose_local_tries", "choose_local_fallback_tries",
            "choose_total_tries", "chooseleaf_descend_once",
            "chooseleaf_vary_r", "chooseleaf_stable", "straw_calc_version",
            "allowed_bucket_algs")


class CrushCompiler:
    def __init__(self, crush: Optional[CrushWrapper] = None):
        self.crush = crush or CrushWrapper()

    # ---- decompile ---------------------------------------------------------
    def decompile(self) -> str:
        """Reference-exact text form (CrushCompiler::decompile): the
        tunable lines appear only when they differ from LEGACY
        defaults, weights print at the reference's 3-decimal
        fixedpoint, buckets carry the advisory comments, rules use the
        'id N' header — recorded reference decompiles (multitype.after,
        add-item.t) and ours compare byte-for-byte."""
        cw = self.crush
        m = cw.crush
        out: List[str] = ["# begin crush map"]
        legacy = {"choose_local_tries": 2,
                  "choose_local_fallback_tries": 5,
                  "choose_total_tries": 19,
                  "chooseleaf_descend_once": 0,
                  "chooseleaf_vary_r": 0,
                  "chooseleaf_stable": 0,
                  "straw_calc_version": 0,
                  # CRUSH_LEGACY_ALLOWED_BUCKET_ALGS (crush.h:198)
                  "allowed_bucket_algs": (1 << 1) | (1 << 2) | (1 << 4)}
        for t in TUNABLES:
            v = getattr(m, t, legacy[t])
            if v != legacy[t]:
                out.append(f"tunable {t} {v}")
        out.append("")
        out.append("# devices")
        for d in range(m.max_devices):
            name = cw.name_map.get(d)
            if name is None:
                continue
            cls = cw.item_class.get(d)
            suffix = f" class {cw.class_map[cls]}" \
                if cls is not None else ""
            out.append(f"device {d} {name}{suffix}")
        out.append("")
        out.append("# types")
        if cw.type_map and 0 not in cw.type_map:
            out.append("type 0 osd")
        for t in sorted(cw.type_map):
            out.append(f"type {t} {cw.type_map[t]}")
        out.append("")
        out.append("# buckets")

        def item_name(it: int) -> str:
            return cw.name_map.get(
                it, f"device{it}" if it >= 0 else f"bucket{-1 - it}")

        emitted = set()

        def emit_bucket(bid: int):
            if bid in emitted:
                return
            b = m.bucket(bid)
            if b is None:
                return
            emitted.add(bid)
            if "~" in cw.name_map.get(bid, ""):
                return              # shadow trees are implementation
            for it in b.items:
                if it < 0:
                    emit_bucket(it)
            tname = cw.type_map.get(b.type, f"type{b.type}")
            out.append(f"{tname} {item_name(bid)} {{")
            out.append(f"\tid {bid}\t\t# do not change unnecessarily")
            for cls, cid in sorted(
                    cw.class_bucket.get(bid, {}).items()):
                out.append(f"\tid {cid} class {cw.class_map[cls]}"
                           f"\t\t# do not change unnecessarily")
            out.append(f"\t# weight {b.weight / 0x10000:.3f}")
            alg = ALG_NAMES.get(b.alg, str(b.alg))
            note = ""
            dopos = False
            from .constants import (
                CRUSH_BUCKET_LIST, CRUSH_BUCKET_TREE,
                CRUSH_BUCKET_UNIFORM)
            if b.alg == CRUSH_BUCKET_UNIFORM:
                note = ("\t# do not change bucket size "
                        f"({b.size}) unnecessarily")
                dopos = True
            elif b.alg == CRUSH_BUCKET_LIST:
                note = ("\t# add new items at the end; "
                        "do not change order unnecessarily")
            elif b.alg == CRUSH_BUCKET_TREE:
                note = ("\t# do not change pos for existing "
                        "items unnecessarily")
                dopos = True
            out.append(f"\talg {alg}{note}")
            out.append("\thash 0\t# rjenkins1")
            ws = getattr(b, "item_weights", None)
            for i, it in enumerate(b.items):
                if b.alg == CRUSH_BUCKET_UNIFORM:
                    w = b.item_weight
                elif b.alg == CRUSH_BUCKET_TREE:
                    # tree stores weights at the leaf NODES
                    # (crush_calc_tree_node)
                    w = b.node_weights[((i + 1) << 1) - 1]
                elif ws is not None and i < len(ws):
                    w = ws[i]
                else:
                    w = 0
                pos = f" pos {i}" if dopos else ""
                out.append(f"\titem {item_name(it)} weight "
                           f"{w / 0x10000:.3f}{pos}")
            out.append("}")

        for b in m.buckets:
            if b is not None:
                emit_bucket(b.id)
        out.append("")
        out.append("# rules")
        for rno, rule in enumerate(m.rules):
            if rule is None:
                continue
            rname = cw.rule_name_map.get(rno, f"rule-{rno}")
            out.append(f"rule {rname} {{")
            out.append(f"\tid {rno}")
            if rule.ruleset != rno:
                out.append(f"\t# WARNING: ruleset {rule.ruleset} != "
                           f"id {rno}; this will not recompile to the "
                           f"same map")
            out.append(f"\ttype "
                       f"{RULE_TYPE_NAMES.get(rule.type, rule.type)}")
            out.append(f"\tmin_size {rule.min_size}")
            out.append(f"\tmax_size {rule.max_size}")
            for step in rule.steps:
                out.append("\t" + self._step_text(step))
            out.append("}")
        if m.choose_args:
            out.append("")
            out.append("# choose_args")
            for key in sorted(m.choose_args):
                out.append(f"choose_args {key} {{")
                args = m.choose_args[key]
                for bi, arg in enumerate(args):
                    if arg is None or (not arg.ids
                                       and not arg.weight_set):
                        continue
                    out.append("  {")
                    out.append(f"    bucket_id {-1 - bi}")
                    if arg.weight_set:
                        out.append("    weight_set [")
                        for ws in arg.weight_set:
                            # .3f matches the reference's
                            # print_fixedpoint exactly (and shares its
                            # round-trip granularity limit)
                            row = " ".join(f"{w / 0x10000:.3f}"
                                           for w in ws.weights)
                            out.append(f"      [ {row} ]")
                        out.append("    ]")
                    if arg.ids:
                        row = " ".join(str(x) for x in arg.ids)
                        out.append(f"    ids [ {row} ]")
                    out.append("  }")
                out.append("}")
        out.append("")
        out.append("# end crush map")
        return "\n".join(out) + "\n"

    def _step_text(self, step: RuleStep) -> str:
        cw = self.crush
        op = step.op
        if op == CRUSH_RULE_TAKE:
            orig, c = cw.split_id_class(step.arg1)
            if c is not None:
                return (f"step take {cw.name_map.get(orig, orig)} "
                        f"class {cw.class_map[c]}")
            return f"step take {cw.name_map.get(step.arg1, step.arg1)}"
        if op == CRUSH_RULE_EMIT:
            return "step emit"
        if op in STEP_SET_NAMES:
            return f"step {STEP_SET_NAMES[op]} {step.arg1}"
        mode = {
            CRUSH_RULE_CHOOSE_FIRSTN: "choose firstn",
            CRUSH_RULE_CHOOSE_INDEP: "choose indep",
            CRUSH_RULE_CHOOSELEAF_FIRSTN: "chooseleaf firstn",
            CRUSH_RULE_CHOOSELEAF_INDEP: "chooseleaf indep",
        }.get(op)
        if mode is None:
            return f"step op{op} {step.arg1} {step.arg2}"
        tname = cw.type_map.get(step.arg2, f"type{step.arg2}")
        return f"step {mode} {step.arg1} type {tname}"

    # ---- compile -----------------------------------------------------------
    def compile(self, text: str) -> CrushWrapper:
        cw = CrushWrapper()
        cw.type_map = {}
        # "always start with legacy tunables, so that the compiled result
        # of a given crushmap is fixed" (CrushCompiler.cc:1205-1207) —
        # including straw_calc_version=0; tunable lines in the text
        # override from there
        cw.crush.set_tunables_profile("legacy")
        lines = []
        for raw in text.splitlines():
            line = raw.split("#", 1)[0].strip()
            if line:
                lines.append(line)
        i = 0
        pending_buckets: List[dict] = []
        self._pending_choose_args: List = []
        rule_starts: List[int] = []
        max_dev = 0
        while i < len(lines):
            line = lines[i]
            toks = line.split()
            if toks[0] == "tunable":
                setattr(cw.crush, toks[1], int(toks[2]))
                i += 1
            elif toks[0] == "device":
                dev = int(toks[1])
                cw.set_item_name(dev, toks[2])
                max_dev = max(max_dev, dev + 1)
                if len(toks) >= 5 and toks[3] == "class":
                    cw.set_item_class(dev, toks[4])
                i += 1
            elif toks[0] == "type":
                cw.set_type_name(int(toks[1]), toks[2])
                i += 1
            elif toks[0] == "rule":
                # rules reference bucket names: parse after buckets build
                rule_starts.append(i)
                while i < len(lines) and lines[i] != "}":
                    i += 1
                i += 1
            elif toks[0] == "choose_args":
                i = self._parse_choose_args(cw, lines, i)
            elif len(toks) == 3 and toks[2] == "{":
                i = self._parse_bucket(cw, lines, i, pending_buckets)
            else:
                raise ValueError(f"cannot parse line: {line!r}")
        cw.set_max_devices(max_dev)
        self._build_buckets(cw, pending_buckets)
        if cw.item_class:
            # shadow class trees exist from the moment the map is
            # complete (finalize/rebuild_roots_with_classes): rules
            # may 'take X class Y' and the binary carries the shadows.
            # Decompiled maps pin their shadow ids in 'id N class C'
            # lines; honor them so the round-trip keeps ids stable.
            pins = {}
            for spec in pending_buckets:
                for cname, sid in spec.get("class_ids", {}).items():
                    pins[(spec["name"], cname)] = sid
            cw.rebuild_roots_with_classes(pins)
        for start in rule_starts:
            self._parse_rule(cw, lines, start)
        self._install_choose_args(cw)
        self.crush = cw
        return cw

    def _parse_choose_args(self, cw: CrushWrapper, lines: List[str],
                           i: int) -> int:
        """choose_args <key> { { bucket_id N [weight_set [...]]
        [ids [...]] } ... }  (CrushCompiler.cc parse_choose_args)."""
        from .types import ChooseArg, WeightSet
        key = int(lines[i].split()[1])
        i += 1
        entries = []
        while i < len(lines) and lines[i].split()[0] != "}":
            t = lines[i].split()
            if t[0] != "{":
                raise ValueError(f"choose_args: bad line {lines[i]!r}")
            i += 1
            arg = ChooseArg(ids=None, weight_set=None)
            bucket_id = None
            while i < len(lines) and lines[i].split()[0] != "}":
                t = lines[i].split()
                if t[0] == "bucket_id":
                    bucket_id = int(t[1])
                elif t[0] == "ids":
                    arg.ids = [int(x) for x in t[2:-1]]
                elif t[0] == "weight_set":
                    i += 1
                    arg.weight_set = []
                    while i < len(lines) and \
                            lines[i].split()[0] == "[":
                        ws = lines[i].split()[1:-1]
                        arg.weight_set.append(WeightSet(
                            weights=[int(round(float(x) * 0x10000))
                                     for x in ws]))
                        i += 1
                    if i >= len(lines) or lines[i].split()[0] != "]":
                        raise ValueError("choose_args: unterminated "
                                         "weight_set")
                else:
                    raise ValueError(
                        f"choose_args: bad line {lines[i]!r}")
                i += 1
            if bucket_id is None or bucket_id >= 0:
                raise ValueError("choose_args: entry needs a negative "
                                 "bucket_id")
            entries.append((bucket_id, arg))
            i += 1
        # arg maps are positional over max_buckets: installed after the
        # buckets exist (compile() defers to _install_choose_args)
        self._pending_choose_args.append((key, entries))
        return i + 1

    def _install_choose_args(self, cw: CrushWrapper) -> None:
        for key, entries in self._pending_choose_args:
            args = [None] * len(cw.crush.buckets)
            for bucket_id, arg in entries:
                bi = -1 - bucket_id
                if bi >= len(args):
                    raise ValueError(
                        f"choose_args: no bucket {bucket_id}")
                args[bi] = arg
            cw.crush.choose_args[key] = args

    def _parse_bucket(self, cw: CrushWrapper, lines: List[str], i: int,
                      pending: List[dict]) -> int:
        toks = lines[i].split()
        btype, name = toks[0], toks[1]
        if "~" in name:
            # the reference grammar rejects '~' in names — it marks
            # shadow (per-class) buckets, which are never declared
            raise ValueError(f"invalid crush name '{name}'")
        spec = {"type": btype, "name": name, "id": None,
                "alg": "straw2", "items": []}
        i += 1
        while i < len(lines) and lines[i] != "}":
            t = lines[i].split()
            if t[0] == "id":
                if len(t) >= 4 and t[2] == "class":
                    # a decompiled shadow-id pin: 'id -4 class ssd'
                    spec.setdefault("class_ids", {})[t[3]] = int(t[1])
                else:
                    spec["id"] = int(t[1])
            elif t[0] == "alg":
                spec["alg"] = t[1]
            elif t[0] == "hash":
                pass
            elif t[0] == "item":
                w = 0x10000
                if "weight" in t:
                    w = int(round(float(t[t.index("weight") + 1]) * 0x10000))
                spec["items"].append((t[1], w))
            else:
                raise ValueError(f"bucket {name}: bad line {lines[i]!r}")
            i += 1
        pending.append(spec)
        return i + 1

    def _build_buckets(self, cw: CrushWrapper, pending: List[dict]) -> None:
        # leaves first: a bucket can be built once all its items exist
        remaining = list(pending)
        while remaining:
            progressed = False
            for spec in list(remaining):
                try:
                    items = [cw.get_item_id(n) if not n.startswith("osd.")
                             else int(n[4:]) for n, _ in spec["items"]]
                except KeyError:
                    continue
                weights = [w for _, w in spec["items"]]
                tid = cw.get_type_id(spec["type"])
                if tid < 0:
                    raise ValueError(f"unknown type {spec['type']!r}")
                cw.add_bucket(ALG_IDS[spec["alg"]], tid, spec["name"],
                              items, weights,
                              id=spec["id"] if spec["id"] is not None else 0)
                remaining.remove(spec)
                progressed = True
            if not progressed:
                names = [s["name"] for s in remaining]
                raise ValueError(f"unresolvable bucket items in {names}")

    def _parse_rule(self, cw: CrushWrapper, lines: List[str],
                    i: int) -> int:
        toks = lines[i].split()
        name = toks[1]
        self._rule_name = name
        ruleset = -1
        rtype = PG_POOL_TYPE_REPLICATED
        min_size, max_size = 1, 10
        steps: List[RuleStep] = []
        i += 1
        while i < len(lines) and lines[i] != "}":
            t = lines[i].split()
            if t[0] == "ruleset" or t[0] == "id":
                ruleset = int(t[1])
            elif t[0] == "type":
                rtype = RULE_TYPE_IDS.get(t[1], int(t[1])
                                          if t[1].isdigit() else 1)
            elif t[0] == "min_size":
                min_size = int(t[1])
            elif t[0] == "max_size":
                max_size = int(t[1])
            elif t[0] == "step":
                steps.append(self._parse_step(cw, t[1:]))
            else:
                raise ValueError(f"rule {name}: bad line {lines[i]!r}")
            i += 1
        rule = Rule(steps=steps, ruleset=ruleset, type=rtype,
                    min_size=min_size, max_size=max_size)
        try:
            rno = cw.add_rule(rule, name,
                              ruleno=ruleset if ruleset >= 0 else -1)
        except ValueError:
            # the reference's parse_rule diagnostic
            raise ValueError(f"rule {ruleset} already exists") from None
        rule.ruleset = rno if ruleset < 0 else ruleset
        return i + 1

    def _parse_step(self, cw: CrushWrapper, t: List[str]) -> RuleStep:
        if t[0] == "take":
            if t[1].startswith("osd."):
                item = int(t[1][4:])
            else:
                try:
                    item = cw.get_item_id(t[1])
                except KeyError:
                    # the reference's diagnostic, verbatim
                    # (CrushCompiler::parse_step_take)
                    raise ValueError(
                        f"in rule '{self._rule_name}' item "
                        f"'{t[1]}' not defined") from None
            if len(t) >= 4 and t[2] == "class":
                cls = t[3]
                if not cw.class_exists(cls):
                    raise ValueError(
                        f"in rule '{self._rule_name}' class "
                        f"'{cls}' not defined")
                c = cw.get_or_create_class_id(cls)
                shadow = cw.class_bucket.get(item, {}).get(c)
                if shadow is None:
                    raise ValueError(
                        f"in rule '{self._rule_name}' no class "
                        f"'{cls}' tree under '{t[1]}'")
                item = shadow
            return RuleStep(CRUSH_RULE_TAKE, item, 0)
        if t[0] == "emit":
            return RuleStep(CRUSH_RULE_EMIT, 0, 0)
        if t[0] in STEP_SET_OPS:
            return RuleStep(STEP_SET_OPS[t[0]], int(t[1]), 0)
        if t[0] in ("choose", "chooseleaf"):
            mode = t[1]  # firstn | indep
            n = int(t[2])
            assert t[3] == "type"
            tid = cw.get_type_id(t[4])
            if tid < 0:
                raise ValueError(f"unknown type {t[4]!r}")
            op = {
                ("choose", "firstn"): CRUSH_RULE_CHOOSE_FIRSTN,
                ("choose", "indep"): CRUSH_RULE_CHOOSE_INDEP,
                ("chooseleaf", "firstn"): CRUSH_RULE_CHOOSELEAF_FIRSTN,
                ("chooseleaf", "indep"): CRUSH_RULE_CHOOSELEAF_INDEP,
            }[(t[0], mode)]
            return RuleStep(op, n, tid)
        raise ValueError(f"unknown step {' '.join(t)!r}")
