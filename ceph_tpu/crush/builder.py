"""Bucket construction — weights, straw scalers, tree node weights.

Mirrors the construction semantics of the reference builder
(src/crush/builder.c): list buckets carry cumulative sums, tree buckets an
implicit binary heap of node weights, legacy straw buckets the
double-precision straw scalers (crush_calc_straw, both straw_calc versions).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from .constants import (
    CRUSH_BUCKET_UNIFORM, CRUSH_BUCKET_LIST, CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
)
from .types import (
    Bucket, CrushMap, ListBucket, StrawBucket, Straw2Bucket, TreeBucket,
    UniformBucket,
)


def make_uniform_bucket(type: int, items: Sequence[int],
                        item_weight: int, id: int = 0) -> UniformBucket:
    b = UniformBucket(id=id, type=type, items=list(items),
                      item_weight=item_weight)
    b.weight = item_weight * len(b.items)
    return b


def make_list_bucket(type: int, items: Sequence[int],
                     weights: Sequence[int], id: int = 0) -> ListBucket:
    b = ListBucket(id=id, type=type, items=list(items),
                   item_weights=list(weights))
    s = 0
    b.sum_weights = []
    for w in weights:
        s += w
        b.sum_weights.append(s)
    b.weight = s
    return b


def _tree_depth(size: int) -> int:
    depth = 1
    t = 1
    while t < size:
        t <<= 1
        depth += 1
    return depth


def make_tree_bucket(type: int, items: Sequence[int],
                     weights: Sequence[int], id: int = 0) -> TreeBucket:
    """Binary-heap tree: leaf i lives at node (i<<1)+1; internal node n
    weights are sums of children (builder.c crush_make_tree_bucket)."""
    b = TreeBucket(id=id, type=type, items=list(items))
    size = len(b.items)
    depth = _tree_depth(size)
    b.num_nodes = 1 << depth
    node_weights = [0] * b.num_nodes
    for i, w in enumerate(weights):
        node_weights[(i << 1) + 1] = w

    # internal node n (height h = trailing zeros) = sum of children
    def fill(n: int) -> int:
        if n & 1:
            return node_weights[n]
        h = (n & -n).bit_length() - 1
        left = n - (1 << (h - 1))
        right = n + (1 << (h - 1))
        lw = fill(left) if left < b.num_nodes else 0
        rw = fill(right) if right < b.num_nodes else 0
        node_weights[n] = lw + rw
        return node_weights[n]

    root = b.num_nodes >> 1
    b.weight = fill(root)
    b.node_weights = node_weights
    return b


def calc_straws(weights: Sequence[int], straw_calc_version: int = 1
                ) -> List[int]:
    """Straw scalers for legacy straw buckets (builder.c crush_calc_straw)."""
    size = len(weights)
    reverse = sorted(range(size), key=lambda i: (weights[i], i))
    # insertion sort in the reference is stable with ties keeping original
    # relative order; python sorted() is stable on the key
    straws = [0] * size
    numleft = size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0
    i = 0
    while i < size:
        if straw_calc_version == 0:
            if weights[reverse[i]] == 0:
                straws[reverse[i]] = 0
                i += 1
                continue
            straws[reverse[i]] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            if weights[reverse[i]] == weights[reverse[i - 1]]:
                continue
            wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
            j = i
            while j < size and weights[reverse[j]] == weights[reverse[i]]:
                numleft -= 1
                j += 1
            wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= (1.0 / pbelow) ** (1.0 / numleft)
            lastw = float(weights[reverse[i - 1]])
        else:
            if weights[reverse[i]] == 0:
                straws[reverse[i]] = 0
                i += 1
                numleft -= 1
                continue
            straws[reverse[i]] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
            numleft -= 1
            wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= (1.0 / pbelow) ** (1.0 / numleft)
            lastw = float(weights[reverse[i - 1]])
    return straws


def make_straw_bucket(type: int, items: Sequence[int],
                      weights: Sequence[int], id: int = 0,
                      straw_calc_version: int = 1) -> StrawBucket:
    b = StrawBucket(id=id, type=type, items=list(items),
                    item_weights=list(weights))
    b.weight = sum(weights)
    b.straws = calc_straws(weights, straw_calc_version)
    return b


def make_straw2_bucket(type: int, items: Sequence[int],
                       weights: Sequence[int], id: int = 0) -> Straw2Bucket:
    b = Straw2Bucket(id=id, type=type, items=list(items),
                     item_weights=list(weights))
    b.weight = sum(weights)
    return b


def make_bucket(alg: int, type: int, items: Sequence[int],
                weights: Sequence[int], id: int = 0,
                straw_calc_version: int = 1) -> Bucket:
    if alg == CRUSH_BUCKET_UNIFORM:
        iw = weights[0] if weights else 0x10000
        return make_uniform_bucket(type, items, iw, id)
    if alg == CRUSH_BUCKET_LIST:
        return make_list_bucket(type, items, weights, id)
    if alg == CRUSH_BUCKET_TREE:
        return make_tree_bucket(type, items, weights, id)
    if alg == CRUSH_BUCKET_STRAW:
        return make_straw_bucket(type, items, weights, id, straw_calc_version)
    if alg == CRUSH_BUCKET_STRAW2:
        return make_straw2_bucket(type, items, weights, id)
    raise ValueError(f"unknown bucket alg {alg}")
