"""`crushtool --dump` — the reference's JSON map dump, byte-exact.

Mirrors CrushWrapper::dump (src/crush/CrushWrapper.cc): devices,
types, buckets (every slot, shadows included), rules with symbolic
step ops, the tunables block with profile / minimum-required-version
detection, and choose_args.  The emitter reproduces the reference
Formatter's pretty-JSON shape: 4-space indent and printf-%f floats
(weight_set weights print as 1.000000), which stock json.dumps cannot
produce.
"""
from __future__ import annotations

from typing import Any, Dict, List

from .constants import (
    CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM,
    CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE, CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
)

ALG_NAMES = {CRUSH_BUCKET_UNIFORM: "uniform", CRUSH_BUCKET_LIST: "list",
             CRUSH_BUCKET_TREE: "tree", CRUSH_BUCKET_STRAW: "straw",
             CRUSH_BUCKET_STRAW2: "straw2"}
LEGACY_ALGS = (1 << CRUSH_BUCKET_UNIFORM) | (1 << CRUSH_BUCKET_LIST) \
    | (1 << CRUSH_BUCKET_STRAW)
HAMMER_ALGS = LEGACY_ALGS | (1 << CRUSH_BUCKET_STRAW2)


class _F:
    """A float that prints like printf %f (Formatter::dump_float)."""

    def __init__(self, v: float):
        self.v = v


def _tunables_match(m, local, fallback, total, once, vary, stable,
                    algs) -> bool:
    return (m.choose_local_tries == local
            and m.choose_local_fallback_tries == fallback
            and m.choose_total_tries == total
            and m.chooseleaf_descend_once == once
            and m.chooseleaf_vary_r == vary
            and m.chooseleaf_stable == stable
            and m.allowed_bucket_algs == algs)


def _profile(m) -> str:
    if _tunables_match(m, 0, 0, 50, 1, 1, 1, HAMMER_ALGS):
        return "jewel"
    if _tunables_match(m, 0, 0, 50, 1, 1, 0, HAMMER_ALGS):
        return "hammer"
    if _tunables_match(m, 0, 0, 50, 1, 1, 0, LEGACY_ALGS):
        return "firefly"
    if _tunables_match(m, 0, 0, 50, 1, 0, 0, LEGACY_ALGS):
        return "bobtail"
    if _tunables_match(m, 2, 5, 19, 0, 0, 0, LEGACY_ALGS):
        return "argonaut"
    return "unknown"


def _has_v2_rules(m) -> bool:
    v2 = {CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_CHOOSELEAF_INDEP,
          CRUSH_RULE_SET_CHOOSE_TRIES, CRUSH_RULE_SET_CHOOSELEAF_TRIES}
    return any(s.op in v2 for r in m.rules if r is not None
               for s in r.steps)


def _has_step(m, op) -> bool:
    return any(s.op == op for r in m.rules if r is not None
               for s in r.steps)


def _min_required_version(m) -> str:
    if _has_step(m, CRUSH_RULE_SET_CHOOSELEAF_STABLE) or \
            m.chooseleaf_stable != 0:
        return "jewel"
    if any(b is not None and b.alg == CRUSH_BUCKET_STRAW2
           for b in m.buckets):
        return "hammer"
    if m.chooseleaf_vary_r != 0:
        return "firefly"
    if m.chooseleaf_descend_once != 0 or m.choose_local_tries != 2 \
            or m.choose_local_fallback_tries != 5 \
            or m.choose_total_tries != 19:
        return "bobtail"
    return "argonaut"


def dump_map(cw) -> Dict[str, Any]:
    """The dict CrushWrapper::dump builds, in emission order."""
    m = cw.crush
    out: Dict[str, Any] = {}
    devices = []
    for d in range(m.max_devices):
        dev = {"id": d, "name": cw.name_map.get(d, f"device{d}")}
        if d in cw.item_class:
            dev["class"] = cw.class_map[cw.item_class[d]]
        devices.append(dev)
    out["devices"] = devices
    types = []
    if cw.type_map and 0 not in cw.type_map:
        types.append({"type_id": 0, "name": "device"})
    for t in sorted(cw.type_map):
        types.append({"type_id": t, "name": cw.type_map[t]})
    out["types"] = types
    buckets = []
    for b in m.buckets:
        if b is None:
            continue
        entry: Dict[str, Any] = {"id": b.id}
        if b.id in cw.name_map:
            entry["name"] = cw.name_map[b.id]
        entry["type_id"] = b.type
        if b.type in cw.type_map:
            entry["type_name"] = cw.type_map[b.type]
        entry["weight"] = b.weight
        entry["alg"] = ALG_NAMES.get(b.alg, str(b.alg))
        entry["hash"] = "rjenkins1" if getattr(b, "hash", 0) == 0 \
            else "unknown"
        entry["items"] = [
            {"id": it, "weight": cw._bucket_item_weight(b, j),
             "pos": j} for j, it in enumerate(b.items)]
        buckets.append(entry)
    out["buckets"] = buckets
    rules = []
    for rno, r in enumerate(m.rules):
        if r is None:
            continue
        rd: Dict[str, Any] = {"rule_id": rno}
        if rno in cw.rule_name_map:
            rd["rule_name"] = cw.rule_name_map[rno]
        rd["ruleset"] = r.ruleset
        rd["type"] = r.type
        rd["min_size"] = r.min_size
        rd["max_size"] = r.max_size
        steps = []
        opname = {CRUSH_RULE_CHOOSE_FIRSTN: "choose_firstn",
                  CRUSH_RULE_CHOOSE_INDEP: "choose_indep",
                  CRUSH_RULE_CHOOSELEAF_FIRSTN: "chooseleaf_firstn",
                  CRUSH_RULE_CHOOSELEAF_INDEP: "chooseleaf_indep"}
        # ONLY these two set_* steps have symbolic names in the
        # reference's dump_rule; every other op falls to the raw
        # opcode/arg1/arg2 default branch
        setname = {
            CRUSH_RULE_SET_CHOOSE_TRIES: "set_choose_tries",
            CRUSH_RULE_SET_CHOOSELEAF_TRIES: "set_chooseleaf_tries"}
        for s in r.steps:
            if s.op == CRUSH_RULE_TAKE:
                steps.append({"op": "take", "item": s.arg1,
                              "item_name":
                              cw.name_map.get(s.arg1, "")})
            elif s.op == CRUSH_RULE_EMIT:
                steps.append({"op": "emit"})
            elif s.op in opname:
                steps.append({"op": opname[s.op], "num": s.arg1,
                              "type": cw.type_map.get(s.arg2, "")})
            elif s.op in setname:
                steps.append({"op": setname[s.op], "num": s.arg1})
            elif s.op == 0:
                steps.append({"op": "noop"})
            else:
                steps.append({"opcode": s.op, "arg1": s.arg1,
                              "arg2": s.arg2})
        rd["steps"] = steps
        rules.append(rd)
    out["rules"] = rules
    tun: Dict[str, Any] = {
        "choose_local_tries": m.choose_local_tries,
        "choose_local_fallback_tries": m.choose_local_fallback_tries,
        "choose_total_tries": m.choose_total_tries,
        "chooseleaf_descend_once": m.chooseleaf_descend_once,
        "chooseleaf_vary_r": m.chooseleaf_vary_r,
        "chooseleaf_stable": m.chooseleaf_stable,
        "straw_calc_version": m.straw_calc_version,
        "allowed_bucket_algs": m.allowed_bucket_algs,
        "profile": _profile(m),
        "optimal_tunables": int(_profile(m) == "jewel"),
        "legacy_tunables": int(_profile(m) == "argonaut"),
        "minimum_required_version": _min_required_version(m),
        "require_feature_tunables": int(
            m.choose_local_tries != 2
            or m.choose_local_fallback_tries != 5
            or m.choose_total_tries != 19),
        "require_feature_tunables2": int(
            m.chooseleaf_descend_once != 0),
        "has_v2_rules": int(_has_v2_rules(m)),
        "require_feature_tunables3": int(m.chooseleaf_vary_r != 0),
        "has_v3_rules": int(_has_step(
            m, CRUSH_RULE_SET_CHOOSELEAF_VARY_R)),
        "has_v4_buckets": int(any(
            b is not None and b.alg == CRUSH_BUCKET_STRAW2
            for b in m.buckets)),
        "require_feature_tunables5": int(m.chooseleaf_stable != 0),
        "has_v5_rules": int(_has_step(
            m, CRUSH_RULE_SET_CHOOSELEAF_STABLE)),
    }
    out["tunables"] = tun
    cargs: Dict[str, Any] = {}
    for key in sorted(m.choose_args):
        entries = []
        for bi, arg in enumerate(m.choose_args[key]):
            if arg is None or (not arg.ids and not arg.weight_set):
                continue
            e: Dict[str, Any] = {"bucket_id": -1 - bi}
            if arg.weight_set:
                import numpy as _np
                # the reference divides in FLOAT32 before printf %f
                e["weight_set"] = [
                    [_F(float(_np.float32(w) / _np.float32(0x10000)))
                     for w in ws.weights]
                    for ws in arg.weight_set]
            if arg.ids:
                e["ids"] = list(arg.ids)
            entries.append(e)
        cargs[str(key)] = entries
    out["choose_args"] = cargs
    return out


def _emit(v: Any, indent: int) -> str:
    sp = " " * indent
    inner = " " * (indent + 4)
    if isinstance(v, _F):
        return f"{v.v:f}"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, str):
        import json as _json
        return _json.dumps(v)
    if isinstance(v, list):
        if not v:
            return "[]"
        body = ",\n".join(inner + _emit(x, indent + 4) for x in v)
        return "[\n" + body + "\n" + sp + "]"
    if isinstance(v, dict):
        if not v:
            return "{}"
        import json as _json
        body = ",\n".join(
            f"{inner}{_json.dumps(str(k))}: {_emit(x, indent + 4)}"
            for k, x in v.items())
        return "{\n" + body + "\n" + sp + "}"
    raise TypeError(type(v))


def dump_json(cw) -> str:
    """The `crushtool --dump` stdout (the reference Formatter's flush
    leaves a blank line after the closing brace)."""
    return _emit(dump_map(cw), 0) + "\n\n"
