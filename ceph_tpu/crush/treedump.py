"""Tree dumpers: the `crushtool --tree` / `osd tree` renderings
(crush/CrushTreeDumper.h traversal + common/TextTable.cc layout +
CrushWrapper.cc CrushTreePlainDumper / OSDMap.cc OSDTreePlainDumper),
pinned byte-exact by the crushtool/osdmaptool cram goldens.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

LEFT, RIGHT = 0, 1


class TextTable:
    """common/TextTable: every cell rendered pad(cell, width, align)
    + one space — including the last column (trailing spaces are part
    of the recorded output)."""

    def __init__(self):
        self.cols: List[Tuple[str, int, int]] = []  # heading, ha, ca
        self.rows: List[List[str]] = []

    def define_column(self, heading: str, hd_align: int,
                      col_align: int) -> None:
        self.cols.append((heading, hd_align, col_align))

    def add_row(self, cells: List[str]) -> None:
        self.rows.append([str(c) for c in cells])

    @staticmethod
    def _pad(s: str, width: int, align: int) -> str:
        if align == RIGHT:
            return s.rjust(width)
        return s.ljust(width)

    def render(self) -> List[str]:
        widths = [max(len(h), *(len(r[i]) for r in self.rows))
                  if self.rows else len(h)
                  for i, (h, _, _) in enumerate(self.cols)]
        out = ["".join(self._pad(h, widths[i], ha) + " "
                       for i, (h, ha, _) in enumerate(self.cols))]
        for r in self.rows:
            out.append("".join(
                self._pad(r[i], widths[i], ca) + " "
                for i, (_h, _ha, ca) in enumerate(self.cols)))
        return out


def weightf(v: float) -> str:
    """include/types.h weightf_t printing."""
    if v < -0.01:
        return "-"
    if v < 0.000001:
        return "0"
    return f"{v:.5f}"


class Item:
    def __init__(self, id: int, parent: int, depth: int,
                 weight: float):
        self.id = id
        self.parent = parent
        self.depth = depth
        self.weight = weight
        self.children: List[int] = []

    def is_bucket(self) -> bool:
        return self.id < 0


def _item_class_name(cw, item: int) -> str:
    cid = cw.item_class.get(item)
    if cid is None:
        return ""
    return cw.class_map.get(cid, "")


def _sort_key(cw, item: int) -> str:
    """CrushTreeDumper's (class, name) child ordering key."""
    if item >= 0:
        return f"{_item_class_name(cw, item)}_osd.{item:08d}"
    return "_" + cw.name_map.get(item, "")


def iter_tree(cw, show_shadow: bool = False):
    """Yield Items in CrushTreeDumper order: roots ascending, then
    depth-first with children sorted by (class, name)."""
    roots = sorted(b.id for b in cw.crush.buckets
                   if b is not None and cw._parent_of(b.id) is None
                   and (show_shadow
                        or "~" not in cw.name_map.get(b.id, "")))

    def walk(item: Item):
        kids: List[int] = []
        if item.is_bucket():
            b = cw.crush.bucket(item.id)
            kids = sorted(range(b.size),
                          key=lambda k: _sort_key(cw, b.items[k]))
            # the reference queues children in reverse-sorted order,
            # which is what its "children" arrays record
            item.children = [b.items[k] for k in reversed(kids)]
        yield item
        if not item.is_bucket():
            return
        b = cw.crush.bucket(item.id)
        for k in kids:
            yield from walk(Item(b.items[k], item.id, item.depth + 1,
                                 b.item_weights[k] / 0x10000))

    for r in roots:
        yield from walk(Item(r, 0, 0,
                             cw.crush.bucket(r).weight / 0x10000))


def _type_name_cell(cw, qi: Item) -> str:
    pad = "    " * qi.depth
    if qi.is_bucket():
        b = cw.crush.bucket(qi.id)
        return (f"{pad}{cw.get_type_name(b.type)} "
                f"{cw.name_map.get(qi.id, '')}")
    return f"{pad}osd.{qi.id}"


def _class_cell(cw, item: int) -> str:
    return _item_class_name(cw, item) if item >= 0 else ""


def crush_tree_lines(cw, show_shadow: bool = False) -> List[str]:
    """crushtool --tree (CrushTreePlainDumper): ID CLASS WEIGHT
    [per-choose-args weight-set column] TYPE NAME."""
    tbl = TextTable()
    tbl.define_column("ID", LEFT, RIGHT)
    tbl.define_column("CLASS", LEFT, RIGHT)
    tbl.define_column("WEIGHT", LEFT, RIGHT)
    ca_ids = sorted(getattr(cw.crush, "choose_args", {}))
    for cid in ca_ids:
        tbl.define_column("(compat)" if cid == -1 else str(cid),
                          LEFT, RIGHT)
    tbl.define_column("TYPE NAME", LEFT, LEFT)
    for qi in iter_tree(cw, show_shadow):
        row = [str(qi.id), _class_cell(cw, qi.id),
               weightf(qi.weight)]
        for cid in ca_ids:
            cell = ""
            if qi.parent < 0:
                arg = cw.crush.choose_args[cid][-1 - qi.parent] \
                    if -1 - qi.parent < len(
                        cw.crush.choose_args[cid]) else None
                ws = getattr(arg, "weight_set", None) if arg else None
                if ws:
                    b = cw.crush.bucket(qi.parent)
                    pos = b.items.index(qi.id) \
                        if qi.id in b.items else 0
                    cell = weightf(ws[0].weights[pos] / 0x10000)
            row.append(cell)
        row.append(_type_name_cell(cw, qi))
        tbl.add_row(row)
    return tbl.render()


def osd_tree_lines(osdmap) -> List[str]:
    """osdmaptool --tree=plain (OSDTreePlainDumper): adds
    STATUS/REWEIGHT/PRI-AFF; DNE osds show DNE / 0 / blank."""
    cw = osdmap.crush
    tbl = TextTable()
    tbl.define_column("ID", LEFT, RIGHT)
    tbl.define_column("CLASS", LEFT, RIGHT)
    tbl.define_column("WEIGHT", LEFT, RIGHT)
    tbl.define_column("TYPE NAME", LEFT, LEFT)
    tbl.define_column("STATUS", LEFT, RIGHT)
    tbl.define_column("REWEIGHT", LEFT, RIGHT)
    tbl.define_column("PRI-AFF", LEFT, RIGHT)
    for qi in iter_tree(cw):
        row = [str(qi.id), _class_cell(cw, qi.id),
               weightf(qi.weight), _type_name_cell(cw, qi)]
        if qi.is_bucket():
            row += ["", "", ""]
        elif not osdmap.exists(qi.id):
            row += ["DNE", "0", ""]
        else:
            status = "up" if osdmap.is_up(qi.id) else "down"
            row += [status,
                    weightf(osdmap.osd_weight[qi.id] / 0x10000),
                    weightf(_pri_aff(osdmap, qi.id))]
        tbl.add_row(row)
    return tbl.render()


def _pri_aff(osdmap, osd: int) -> float:
    pa = getattr(osdmap, "osd_primary_affinity", None)
    return (pa[osd] / 0x10000) if pa is not None else 1.0


def osd_tree_json(osdmap) -> str:
    """osdmaptool --tree=json-pretty: the FormattingDumper fields
    (dump_item_fields + OSD status extras), children DESCENDING, a
    pool_weights section on every non-root node, stray array."""
    from .dumpfmt import _F, _emit
    cw = osdmap.crush
    nodes = []
    for qi in iter_tree(cw):
        d: Dict = {"id": qi.id}
        c = _class_cell(cw, qi.id)
        if c:
            d["device_class"] = c
        if qi.is_bucket():
            b = cw.crush.bucket(qi.id)
            d["name"] = cw.name_map.get(qi.id, "")
            d["type"] = cw.get_type_name(b.type)
            d["type_id"] = b.type
        else:
            d["name"] = f"osd.{qi.id}"
            d["type"] = cw.get_type_name(0)
            d["type_id"] = 0
            d["crush_weight"] = _F(qi.weight)
            d["depth"] = qi.depth
        if qi.parent < 0:
            d["pool_weights"] = {}
        if qi.is_bucket():
            d["children"] = qi.children
        else:
            d["exists"] = 1 if osdmap.exists(qi.id) else 0
            d["status"] = "up" if osdmap.is_up(qi.id) else "down"
            d["reweight"] = _F(osdmap.osd_weight[qi.id] / 0x10000
                               if osdmap.exists(qi.id) else 0.0)
            d["primary_affinity"] = _F(_pri_aff(osdmap, qi.id))
        nodes.append(d)
    return _emit({"nodes": nodes, "stray": []}, 0) + "\n\n"
