"""rjenkins 32-bit hash family used by CRUSH.

Bit-exact with the reference (src/crush/hash.c): the Jenkins mix with seed
1315423911 and pad constants 231232/1232, in 1..5-argument variants.  The
scalar versions use masked Python ints (the oracle); the numpy versions are
vectorized for the batch host mapper; the device versions live in
ceph_tpu/ops/crush_kernels.py and share the same structure in uint32 lanes.
"""
from __future__ import annotations

import numpy as np

M32 = 0xFFFFFFFF
CRUSH_HASH_SEED = 1315423911


def _mix(a: int, b: int, c: int):
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 13
    b = (b - c) & M32; b = (b - a) & M32; b ^= (a << 8) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 13
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 12
    b = (b - c) & M32; b = (b - a) & M32; b ^= (a << 16) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 5
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 3
    b = (b - c) & M32; b = (b - a) & M32; b ^= (a << 10) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 15
    return a, b, c


def crush_hash32(a: int) -> int:
    a &= M32
    h = (CRUSH_HASH_SEED ^ a) & M32
    b, x, y = a, 231232, 1232
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


def crush_hash32_2(a: int, b: int) -> int:
    a &= M32; b &= M32
    h = (CRUSH_HASH_SEED ^ a ^ b) & M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def crush_hash32_3(a: int, b: int, c: int) -> int:
    a &= M32; b &= M32; c &= M32
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c) & M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def crush_hash32_4(a: int, b: int, c: int, d: int) -> int:
    a &= M32; b &= M32; c &= M32; d &= M32
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d) & M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


def crush_hash32_5(a: int, b: int, c: int, d: int, e: int) -> int:
    a &= M32; b &= M32; c &= M32; d &= M32; e &= M32
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d ^ e) & M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h


# ---- numpy vectorized (uint32 lanes) --------------------------------------

def _mix_np(a, b, c):
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(13))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(8))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(13))
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(12))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(16))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(5))
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(3))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(10))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(15))
    return a, b, c


def crush_hash32_2_np(a, b):
    a = np.asarray(a, dtype=np.uint32)
    b = np.asarray(b, dtype=np.uint32)
    h = np.uint32(CRUSH_HASH_SEED) ^ a ^ b
    x = np.uint32(231232) * np.ones_like(a)
    y = np.uint32(1232) * np.ones_like(a)
    a, b, h = _mix_np(a, b, h)
    x, a, h = _mix_np(x, a, h)
    b, y, h = _mix_np(b, y, h)
    return h


def crush_hash32_3_np(a, b, c):
    a = np.asarray(a, dtype=np.uint32)
    b = np.asarray(b, dtype=np.uint32)
    c = np.asarray(c, dtype=np.uint32)
    a, b, c = np.broadcast_arrays(a, b, c)
    h = np.uint32(CRUSH_HASH_SEED) ^ a ^ b ^ c
    x = np.full_like(a, 231232)
    y = np.full_like(a, 1232)
    a, b, h = _mix_np(a.copy(), b.copy(), h)
    c, x, h = _mix_np(c.copy(), x, h)
    y, a, h = _mix_np(y, a, h)
    b, x, h = _mix_np(b, x, h)
    y, c, h = _mix_np(y, c, h)
    return h
