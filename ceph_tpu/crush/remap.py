"""CRUSH constrained re-mapping — the upmap balancer's search engine.

Semantics-exact port of the reference's CrushWrapper remap helpers
(src/crush/CrushWrapper.cc): ``try_remap_rule`` walks a rule's steps
over an EXISTING mapping and swaps overfull devices for underfull ones
while preserving every placement constraint the rule encodes (failure
domains stay distinct, replacements stay inside the same take subtree,
intermediate buckets with overfull-but-unswappable leaves are replaced
by peers that do have underfull capacity).  ``OSDMap.calc_pg_upmaps``
drives it; byte-exact agreement with the reference's recorded
osdmaptool output is pinned by tests/test_osdmaptool_golden.py.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from .constants import (
    CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_TAKE,
)


def get_parent_of_type(cw, item: int, type: int) -> int:
    """First ancestor bucket of *type* above *item*; 0 when orphaned
    (CrushWrapper::get_parent_of_type)."""
    while True:
        p = cw._parent_of(item)
        if p is None:
            return 0
        item = p.id
        if cw.crush.bucket(item).type == type:
            return item


def subtree_contains(cw, root: int, item: int) -> bool:
    """(CrushWrapper::subtree_contains)"""
    if root == item:
        return True
    if root >= 0:
        return False
    b = cw.crush.bucket(root)
    if b is None:
        return False
    return any(subtree_contains(cw, c, item) for c in b.items)


def get_rule_weight_osd_map(cw, ruleno: int) -> Dict[int, float]:
    """osd -> normalized weight fraction under the rule's takes
    (CrushWrapper::get_rule_weight_osd_map).  float32 arithmetic, like
    the reference's ``float``, so downstream deviation compares that
    sit exactly on a threshold round the same way."""
    import numpy as np
    rule = cw.crush.rules[ruleno]
    if rule is None:
        raise KeyError(f"no rule {ruleno}")
    pmap: Dict[int, float] = {}
    for step in rule.steps:
        if step.op != CRUSH_RULE_TAKE:
            continue
        m: Dict[int, np.float32] = {}
        total = np.float32(0.0)
        n = step.arg1
        if n >= 0:
            m[n] = np.float32(1.0)
            total = np.float32(1.0)
        else:
            # breadth-first over the subtree (_get_take_weight_osd_map)
            queue = [n]
            while queue:
                bno = queue.pop(0)
                b = cw.crush.bucket(bno)
                for j, it in enumerate(b.items):
                    if it >= 0:
                        w = np.float32(
                            np.float32(b.item_weights[j]) /
                            np.float32(0x10000))
                        m[it] = w
                        total = np.float32(total + w)
                    else:
                        queue.append(it)
        for osd, w in m.items():
            pmap[osd] = float(np.float32(
                np.float32(pmap.get(osd, 0.0)) + np.float32(w / total)))
    return pmap


def _choose_type_stack(cw, stack: List[Tuple[int, int]],
                       overfull: Set[int], underfull: Sequence[int],
                       orig: Sequence[int], idx: List[int],
                       used: Set[int], pw: List[int]) -> List[int]:
    """(CrushWrapper::_choose_type_stack)  ``idx`` is the one-element
    mutable cursor into ``orig`` (the reference's iterator ``i``)."""
    w = list(pw)
    cumulative_fanout = [0] * len(stack)
    f = 1
    for j in range(len(stack) - 1, -1, -1):
        cumulative_fanout[j] = f
        f *= stack[j][1]

    # per-level buckets that hold at least one underfull device
    underfull_buckets: List[Set[int]] = [set()
                                         for _ in range(len(stack) - 1)]
    for osd in underfull:
        item = osd
        for j in range(len(stack) - 2, -1, -1):
            item = get_parent_of_type(cw, item, stack[j][0])
            underfull_buckets[j].add(item)

    for j in range(len(stack)):
        type_, fanout = stack[j]
        cum_fanout = cumulative_fanout[j]
        o: List[int] = []
        tmpi = idx[0]
        for from_ in w:
            leaves: List[Set[int]] = [set() for _ in range(fanout)]
            done = False
            for pos in range(fanout):
                if type_ > 0:
                    # non-leaf: record the choice + its leaf cohort
                    item = get_parent_of_type(cw, orig[tmpi], type_)
                    o.append(item)
                    n = cum_fanout
                    while n > 0 and tmpi < len(orig):
                        leaves[pos].add(orig[tmpi])
                        tmpi += 1
                        n -= 1
                else:
                    # leaf: swap an overfull device for an underfull one
                    replaced = False
                    if orig[idx[0]] in overfull:
                        for item in underfull:
                            if item in used:
                                continue
                            if not subtree_contains(cw, from_, item):
                                continue
                            if item in orig:
                                continue
                            o.append(item)
                            used.add(item)
                            replaced = True
                            idx[0] += 1
                            break
                    if not replaced:
                        o.append(orig[idx[0]])
                        idx[0] += 1
                    if idx[0] >= len(orig):
                        done = True
                        break
            if j + 1 < len(stack):
                # a chosen bucket with overfull leaves but NO underfull
                # candidates can't fix anything: swap it for a same-
                # parent peer that has spare underfull capacity
                for pos in range(fanout):
                    if pos >= len(o):
                        break
                    if o[pos] in underfull_buckets[j]:
                        continue
                    if not any(osd in overfull for osd in leaves[pos]):
                        continue
                    for alt in sorted(underfull_buckets[j]):
                        if alt in o:
                            continue
                        if j == 0 or \
                                get_parent_of_type(
                                    cw, o[pos], stack[j - 1][0]) == \
                                get_parent_of_type(
                                    cw, alt, stack[j - 1][0]):
                            o[pos] = alt
                            break
            if done or idx[0] >= len(orig):
                break
        w = o
    return w


def try_remap_rule(cw, ruleno: int, maxout: int, overfull: Set[int],
                   underfull: Sequence[int], orig: Sequence[int]
                   ) -> Optional[List[int]]:
    """Alternative mapping for ``orig`` under rule *ruleno* moving
    overfull->underfull (CrushWrapper::try_remap_rule); None on error."""
    rule = cw.crush.rules[ruleno]
    if rule is None:
        return None
    m = cw.crush
    w: List[int] = []
    out: List[int] = []
    idx = [0]
    used: Set[int] = set()
    type_stack: List[Tuple[int, int]] = []
    for step in rule.steps:
        if step.op == CRUSH_RULE_TAKE:
            ok = (0 <= step.arg1 < m.max_devices) or \
                (0 <= -1 - step.arg1 < len(m.buckets)
                 and m.bucket(step.arg1) is not None)
            if ok:
                w = [step.arg1]
        elif step.op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                         CRUSH_RULE_CHOOSELEAF_INDEP):
            numrep = step.arg1
            if numrep <= 0:
                numrep += maxout
            type_stack.append((step.arg2, numrep))
            type_stack.append((0, 1))
            w = _choose_type_stack(cw, type_stack, overfull, underfull,
                                   orig, idx, used, w)
            type_stack = []
        elif step.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                         CRUSH_RULE_CHOOSE_INDEP):
            numrep = step.arg1
            if numrep <= 0:
                numrep += maxout
            type_stack.append((step.arg2, numrep))
        elif step.op == CRUSH_RULE_EMIT:
            if type_stack:
                w = _choose_type_stack(cw, type_stack, overfull,
                                       underfull, orig, idx, used, w)
                type_stack = []
            out.extend(w)
            w = []
    return out
