"""Binary crushmap codec — the reference's on-disk/wire map format.

Implements CrushWrapper::encode/decode (src/crush/CrushWrapper.cc:2160,
:2335) byte-compatibly: magic 0x00010000, per-slot buckets with per-alg
payloads, rules (4-byte mask + 12-byte steps), the three name maps
(tolerating the historical 32-or-64-bit key encoding on decode), the
progressively-appended tunables tail, and the luminous section (device
classes + choose_args).  This is what lets our crushtool consume binary
maps produced by the reference crushtool (the src/test/cli/crushtool
fixtures decode directly) and emit maps the reference could read back.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional

from .constants import (
    CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM,
)
from .types import (
    ChooseArg, CrushMap, ListBucket, Rule, RuleStep, StrawBucket,
    Straw2Bucket, TreeBucket, UniformBucket, WeightSet,
)
from .wrapper import CrushWrapper

CRUSH_MAGIC = 0x00010000


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _unpack(self, fmt: str):
        v = struct.unpack_from(fmt, self.buf, self.pos)[0]
        self.pos += struct.calcsize(fmt)
        return v

    def u8(self): return self._unpack("<B")
    def u16(self): return self._unpack("<H")
    def u32(self): return self._unpack("<I")
    def s32(self): return self._unpack("<i")
    def s64(self): return self._unpack("<q")

    def raw(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            # the reference decoder throws buffer::end_of_buffer here —
            # a silently truncated read would fall back to legacy
            # tunables and produce wrong placements from a corrupt file
            raise ValueError(
                f"truncated crushmap: need {n} bytes at {self.pos}, "
                f"have {len(self.buf) - self.pos}")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def end(self) -> bool:
        return self.pos >= len(self.buf)

    def str_map(self) -> Dict[int, str]:
        """decode_32_or_64_string_map: keys may be 32 OR 64 bits (an old
        encoding bug); a zero 'strlen' means the key was 64-bit and the
        real length follows (strings are never empty)."""
        out: Dict[int, str] = {}
        n = self.u32()
        for _ in range(n):
            key = self.s32()
            strlen = self.u32()
            if strlen == 0:
                strlen = self.u32()
            out[key] = self.raw(strlen).decode()
        return out

    def s32_map(self) -> Dict[int, int]:
        n = self.u32()
        return {self.s32(): self.s32() for _ in range(n)}


class _Writer:
    def __init__(self):
        self.parts: List[bytes] = []

    def _pack(self, fmt: str, v) -> None:
        self.parts.append(struct.pack(fmt, v))

    def u8(self, v): self._pack("<B", v)
    def u16(self, v): self._pack("<H", v)
    def u32(self, v): self._pack("<I", v & 0xFFFFFFFF)
    def s32(self, v): self._pack("<i", v)
    def s64(self, v): self._pack("<q", v)

    def raw(self, b: bytes) -> None:
        self.parts.append(b)

    def str_map(self, m: Dict[int, str]) -> None:
        self.u32(len(m))
        for k in sorted(m):
            self.s32(k)
            b = m[k].encode()
            if not b:
                # strlen=0 is the decoder's 64-bit-key marker (the
                # historical encoding bug tolerance); the format cannot
                # represent empty names
                raise ValueError(f"empty name for id {k} is not "
                                 "representable in the crushmap format")
            self.u32(len(b))
            self.raw(b)

    def s32_map(self, m: Dict[int, int]) -> None:
        self.u32(len(m))
        for k in sorted(m):
            self.s32(k)
            self.s32(m[k])

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


def decode_crushmap(data: bytes) -> CrushWrapper:
    r = _Reader(data)
    if r.u32() != CRUSH_MAGIC:
        raise ValueError("bad crush magic")
    cw = CrushWrapper()
    m = cw.crush
    max_buckets = r.s32()
    max_rules = r.u32()
    m.max_devices = r.s32()
    # re-encode exactly what was stored (round-trip byte identity),
    # whatever slot-count policy the producer used
    m.exact_bucket_slots = True
    # "legacy tunables, unless we decode something newer"
    m.set_tunables_profile("legacy")

    m.buckets = []
    for _ in range(max_buckets):
        alg = r.u32()
        if alg == 0:
            m.buckets.append(None)
            continue
        bid = r.s32()
        btype = r.u16()
        alg2 = r.u8()
        bhash = r.u8()
        weight = r.u32()
        size = r.u32()
        items = [r.s32() for _ in range(size)]
        common = dict(id=bid, type=btype, alg=alg2, items=items,
                      weight=weight, hash=bhash)
        if alg2 == CRUSH_BUCKET_UNIFORM:
            b = UniformBucket(**common)
            b.item_weight = r.u32()
        elif alg2 == CRUSH_BUCKET_LIST:
            b = ListBucket(**common)
            for _j in range(size):
                b.item_weights.append(r.u32())
                b.sum_weights.append(r.u32())
        elif alg2 == CRUSH_BUCKET_TREE:
            b = TreeBucket(**common)
            b.num_nodes = r.u8()
            b.node_weights = [r.u32() for _j in range(b.num_nodes)]
        elif alg2 == CRUSH_BUCKET_STRAW:
            b = StrawBucket(**common)
            for _j in range(size):
                b.item_weights.append(r.u32())
                b.straws.append(r.u32())
        elif alg2 == CRUSH_BUCKET_STRAW2:
            b = Straw2Bucket(**common)
            b.item_weights = [r.u32() for _j in range(size)]
        else:
            raise ValueError(f"unknown bucket alg {alg2}")
        m.buckets.append(b)

    m.rules = []
    for _ in range(max_rules):
        if r.u32() == 0:
            m.rules.append(None)
            continue
        length = r.u32()
        ruleset, rtype, min_size, max_size = (r.u8(), r.u8(), r.u8(),
                                              r.u8())
        steps = [RuleStep(r.u32(), r.s32(), r.s32())
                 for _j in range(length)]
        m.rules.append(Rule(steps=steps, ruleset=ruleset, type=rtype,
                            min_size=min_size, max_size=max_size))

    cw.type_map = r.str_map()
    cw.name_map = r.str_map()
    cw.rule_name_map = r.str_map()

    # tunables tail (progressively appended across versions)
    if not r.end():
        m.choose_local_tries = r.u32()
        m.choose_local_fallback_tries = r.u32()
        m.choose_total_tries = r.u32()
    if not r.end():
        m.chooseleaf_descend_once = r.u32()
    if not r.end():
        m.chooseleaf_vary_r = r.u8()
    if not r.end():
        m.straw_calc_version = r.u8()
    if not r.end():
        m.allowed_bucket_algs = r.u32()
    if not r.end():
        m.chooseleaf_stable = r.u8()
    if not r.end():
        # luminous: device classes
        cw.item_class = r.s32_map()
        cw.class_map = r.str_map()
        n = r.u32()
        cw.class_bucket = {}
        for _ in range(n):
            root = r.s32()
            cw.class_bucket[root] = r.s32_map()
    if not r.end():
        # choose_args
        n_maps = r.u32()
        for _ in range(n_maps):
            key = r.s64()
            args: List[Optional[ChooseArg]] = [None] * max_buckets
            n_args = r.u32()
            for _j in range(n_args):
                bi = r.u32()
                arg = ChooseArg(ids=None, weight_set=None)
                ws_size = r.u32()
                if ws_size:
                    arg.weight_set = []
                    for _k in range(ws_size):
                        sz = r.u32()
                        arg.weight_set.append(WeightSet(
                            weights=[r.u32() for _l in range(sz)]))
                ids_size = r.u32()
                if ids_size:
                    arg.ids = [r.s32() for _k in range(ids_size)]
                args[bi] = arg
            m.choose_args[key] = args
    return cw


def encode_crushmap(cw: CrushWrapper) -> bytes:
    m = cw.crush
    w = _Writer()
    w.u32(CRUSH_MAGIC)
    # max_buckets carries the builder's allocation high-water: the
    # bucket array starts at 8 slots and doubles (builder.c
    # crush_add_bucket:150-156), so a reference-built 3-bucket map
    # stores 5 empty slots.  Stored maps already carry this padding
    # (decode preserves the None slots); maps built in-memory pad
    # here so our encodings are byte-identical to the reference's.
    slots = len(m.buckets)
    if slots and not getattr(m, "exact_bucket_slots", False):
        # decoded maps re-encode their stored slot count verbatim
        # (exact_bucket_slots); only in-memory-built maps pad here
        policy = 8
        while policy < slots:
            policy *= 2
        slots = max(slots, policy)
    w.s32(slots)
    w.u32(len(m.rules))
    w.s32(m.max_devices)

    for b in list(m.buckets) + [None] * (slots - len(m.buckets)):
        if b is None:
            w.u32(0)
            continue
        w.u32(b.alg)
        w.s32(b.id)
        w.u16(b.type)
        w.u8(b.alg)
        w.u8(b.hash)
        w.u32(b.weight)
        w.u32(b.size)
        for it in b.items:
            w.s32(it)
        if b.alg == CRUSH_BUCKET_UNIFORM:
            w.u32(b.item_weight)
        elif b.alg == CRUSH_BUCKET_LIST:
            for iw, sw in zip(b.item_weights, b.sum_weights):
                w.u32(iw)
                w.u32(sw)
        elif b.alg == CRUSH_BUCKET_TREE:
            w.u8(b.num_nodes)
            for nw in b.node_weights:
                w.u32(nw)
        elif b.alg == CRUSH_BUCKET_STRAW:
            for iw, st in zip(b.item_weights, b.straws):
                w.u32(iw)
                w.u32(st)
        elif b.alg == CRUSH_BUCKET_STRAW2:
            for iw in b.item_weights:
                w.u32(iw)
        else:
            raise ValueError(f"bucket alg {b.alg}")

    for rule in m.rules:
        if rule is None:
            w.u32(0)
            continue
        w.u32(1)
        w.u32(len(rule.steps))
        w.u8(rule.ruleset)
        w.u8(rule.type)
        w.u8(rule.min_size)
        w.u8(rule.max_size)
        for s in rule.steps:
            w.u32(s.op)
            w.s32(s.arg1)
            w.s32(s.arg2)

    w.str_map(cw.type_map)
    w.str_map(cw.name_map)
    w.str_map(cw.rule_name_map)

    w.u32(m.choose_local_tries)
    w.u32(m.choose_local_fallback_tries)
    w.u32(m.choose_total_tries)
    w.u32(m.chooseleaf_descend_once)
    w.u8(m.chooseleaf_vary_r)
    w.u8(m.straw_calc_version)
    w.u32(m.allowed_bucket_algs)
    w.u8(m.chooseleaf_stable)

    # luminous: device classes
    w.s32_map(cw.item_class)
    w.str_map(cw.class_map)
    w.u32(len(cw.class_bucket))
    for root in sorted(cw.class_bucket):
        w.s32(root)
        w.s32_map(cw.class_bucket[root])

    # choose_args
    w.u32(len(m.choose_args))
    for key in sorted(m.choose_args):
        w.s64(key)
        args = m.choose_args[key]
        present = [(i, a) for i, a in enumerate(args)
                   if a is not None and (a.weight_set or a.ids)]
        w.u32(len(present))
        for i, a in present:
            w.u32(i)
            w.u32(len(a.weight_set) if a.weight_set else 0)
            for ws in a.weight_set or []:
                w.u32(len(ws.weights))
                for wt in ws.weights:
                    w.u32(wt)
            w.u32(len(a.ids) if a.ids else 0)
            for i2 in a.ids or []:
                w.s32(i2)
    return w.getvalue()
