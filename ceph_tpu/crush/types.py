"""CRUSH data model: buckets, rules, tunables, the map.

Structure mirrors the reference's (src/crush/crush.h) because crush maps are
defined by it: buckets are uniform/list/tree/straw/straw2 (:123-191) holding
16.16 fixed-point weights; rules are short step programs (:52-70); tunables
gate retry semantics (:354-461); choose_args supply per-position replacement
weights for straw2 (:273, the upmap balancer's lever).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .constants import (
    CRUSH_BUCKET_UNIFORM, CRUSH_BUCKET_LIST, CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2, CRUSH_HASH_RJENKINS1,
    TUNABLE_PROFILES,
)


@dataclass
class Bucket:
    id: int                      # negative, unique
    type: int                    # user-defined type (host/rack/root/...)
    alg: int
    items: List[int] = field(default_factory=list)
    weight: int = 0              # 16.16 cumulative
    hash: int = CRUSH_HASH_RJENKINS1

    @property
    def size(self) -> int:
        return len(self.items)


@dataclass
class UniformBucket(Bucket):
    alg: int = CRUSH_BUCKET_UNIFORM
    item_weight: int = 0x10000


@dataclass
class ListBucket(Bucket):
    alg: int = CRUSH_BUCKET_LIST
    item_weights: List[int] = field(default_factory=list)
    sum_weights: List[int] = field(default_factory=list)  # cumulative [0..i]


@dataclass
class TreeBucket(Bucket):
    alg: int = CRUSH_BUCKET_TREE
    num_nodes: int = 0
    node_weights: List[int] = field(default_factory=list)


@dataclass
class StrawBucket(Bucket):
    alg: int = CRUSH_BUCKET_STRAW
    item_weights: List[int] = field(default_factory=list)
    straws: List[int] = field(default_factory=list)  # 16.16 scalers


@dataclass
class Straw2Bucket(Bucket):
    alg: int = CRUSH_BUCKET_STRAW2
    item_weights: List[int] = field(default_factory=list)


@dataclass
class RuleStep:
    op: int
    arg1: int = 0
    arg2: int = 0


@dataclass
class Rule:
    steps: List[RuleStep]
    ruleset: int = 0
    type: int = 1                # pool type mask
    min_size: int = 1
    max_size: int = 10


@dataclass
class WeightSet:
    weights: List[int]           # 16.16, one per bucket item


@dataclass
class ChooseArg:
    """Per-bucket straw2 replacements (crush.h crush_choose_arg)."""
    ids: Optional[List[int]] = None
    weight_set: Optional[List[WeightSet]] = None  # indexed by position


class CrushMap:
    """The placement map: buckets + rules + tunables (+ choose_args)."""

    def __init__(self):
        self.buckets: List[Optional[Bucket]] = []   # index b holds id -1-b
        self.rules: List[Optional[Rule]] = []
        self.max_devices = 0
        # tunables: default profile == jewel/optimal (CrushWrapper.h:208)
        for k, v in TUNABLE_PROFILES["default"].items():
            setattr(self, k, v)
        self.straw_calc_version = 1
        # choose_args sets keyed by an id (OSDMap stores them per map)
        self.choose_args: Dict[int, List[ChooseArg]] = {}

    # -- buckets ------------------------------------------------------------
    @property
    def max_buckets(self) -> int:
        return len(self.buckets)

    def bucket(self, item_id: int) -> Optional[Bucket]:
        bno = -1 - item_id
        if 0 <= bno < len(self.buckets):
            return self.buckets[bno]
        return None

    def add_bucket(self, bucket: Bucket, id: Optional[int] = None) -> int:
        if id is not None:
            bucket.id = id
        if bucket.id == 0:  # allocate lowest free
            bno = next((i for i, b in enumerate(self.buckets) if b is None),
                       len(self.buckets))
            bucket.id = -1 - bno
        bno = -1 - bucket.id
        while len(self.buckets) <= bno:
            self.buckets.append(None)
        if self.buckets[bno] is not None:
            raise ValueError(f"bucket id {bucket.id} already in use")
        self.buckets[bno] = bucket
        return bucket.id

    def set_tunables_profile(self, profile: str) -> None:
        for k, v in TUNABLE_PROFILES[profile].items():
            setattr(self, k, v)
        self.straw_calc_version = 0 if profile == "legacy" else 1

    # -- rules --------------------------------------------------------------
    def add_rule(self, rule: Rule, ruleno: int = -1) -> int:
        if ruleno < 0:
            ruleno = next((i for i, r in enumerate(self.rules) if r is None),
                          len(self.rules))
        while len(self.rules) <= ruleno:
            self.rules.append(None)
        if self.rules[ruleno] is not None:
            raise ValueError(f"rule {ruleno} already in use")
        self.rules[ruleno] = rule
        return ruleno

    @property
    def max_rules(self) -> int:
        return len(self.rules)
