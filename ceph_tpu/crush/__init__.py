from .constants import *  # noqa: F401,F403
from .types import (  # noqa: F401
    Bucket, UniformBucket, ListBucket, TreeBucket, StrawBucket, Straw2Bucket,
    Rule, RuleStep, CrushMap, ChooseArg, WeightSet,
)
from .mapper import crush_do_rule, crush_find_rule  # noqa: F401
from .wrapper import CrushWrapper  # noqa: F401
from . import builder  # noqa: F401
