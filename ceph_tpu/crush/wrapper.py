"""CrushWrapper — the named, user-facing façade over the raw map.

Semantics follow the reference C++ façade (src/crush/CrushWrapper.{h,cc}):
type/bucket/rule name registries, hierarchy construction, add_simple_rule
("firstn"/"indep" step templates incl. the indep SET_CHOOSELEAF_TRIES=5 /
SET_CHOOSE_TRIES=100 preamble, CrushWrapper.cc add_simple_rule_at), tunable
profiles, per-map choose_args, and the batch do_rule entry used by OSDMap.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .constants import (
    CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE, PG_POOL_TYPE_REPLICATED,
)
from . import builder
from .mapper import crush_do_rule, crush_find_rule
from .types import Bucket, ChooseArg, CrushMap, Rule, RuleStep


class CrushWrapper:
    def __init__(self):
        self.crush = CrushMap()
        self.type_map: Dict[int, str] = {0: "osd"}
        self.name_map: Dict[int, str] = {}       # item id -> name
        self.rule_name_map: Dict[int, str] = {}
        self.class_map: Dict[int, str] = {}      # class id -> name
        self.item_class: Dict[int, int] = {}     # device id -> class id
        # root bucket id -> class id -> shadow bucket id
        self.class_bucket: Dict[int, Dict[int, int]] = {}

    # ---- names ------------------------------------------------------------
    def set_type_name(self, t: int, name: str) -> None:
        self.type_map[t] = name

    def get_type_id(self, name: str) -> int:
        for t, n in self.type_map.items():
            if n == name:
                return t
        return -1

    def get_type_name(self, t: int) -> str:
        return self.type_map.get(t, f"type{t}")

    def set_item_name(self, item: int, name: str) -> None:
        self.name_map[item] = name

    def get_item_name(self, item: int) -> str:
        return self.name_map.get(
            item, f"osd.{item}" if item >= 0 else f"bucket{item}")

    def name_exists(self, name: str) -> bool:
        return name in self.name_map.values()

    def get_item_id(self, name: str) -> int:
        for i, n in self.name_map.items():
            if n == name:
                return i
        raise KeyError(name)

    def rule_exists(self, name_or_no) -> bool:
        if isinstance(name_or_no, str):
            return name_or_no in self.rule_name_map.values()
        return (0 <= name_or_no < self.crush.max_rules
                and self.crush.rules[name_or_no] is not None)

    def get_rule_id(self, name: str) -> int:
        for i, n in self.rule_name_map.items():
            if n == name:
                return i
        return -1

    def ruleset_exists(self, ruleset: int) -> bool:
        return any(r is not None and r.ruleset == ruleset
                   for r in self.crush.rules)

    # ---- device classes ---------------------------------------------------
    def get_or_create_class_id(self, name: str) -> int:
        for c, n in self.class_map.items():
            if n == name:
                return c
        c = max(self.class_map, default=-1) + 1
        self.class_map[c] = name
        return c

    def class_exists(self, name: str) -> bool:
        return name in self.class_map.values()

    def set_item_class(self, item: int, cls: str) -> int:
        c = self.get_or_create_class_id(cls)
        self.item_class[item] = c
        return c

    # ---- construction -----------------------------------------------------
    # ---- crush locations (crush/CrushLocation.cc + CrushWrapper
    # insert_item/create_or_move_item, CrushWrapper.cc) -------------------
    @staticmethod
    def parse_loc(spec) -> list:
        """"root=default host=h1" or dict -> [(type_name, name), ...]
        (the osd_crush_location config format)."""
        if isinstance(spec, dict):
            return list(spec.items())
        out = []
        for tok in str(spec).split():
            t, _, n = tok.partition("=")
            if not n:
                raise ValueError(f"bad crush location token {tok!r}")
            out.append((t, n))
        return out

    def _loc_chain(self, loc) -> int:
        """Ensure the bucket chain described by *loc* exists (creating
        straw2 buckets as needed, highest type first); returns the
        LEAF-most bucket id items should land in."""
        from .constants import CRUSH_BUCKET_STRAW2
        pairs = self.parse_loc(loc)
        typed = []
        for tname, name in pairs:
            t = self.get_type_id(tname)
            if t <= 0:
                raise ValueError(f"unknown crush type {tname!r}")
            typed.append((t, tname, name))
        typed.sort(reverse=True)           # root first
        parent = None
        for t, _tname, name in typed:
            if self.name_exists(name):
                bid = self.get_item_id(name)
                if bid >= 0:
                    raise ValueError(f"{name!r} names a device")
                # an existing but PARENTLESS bucket attaches under the
                # chain (insert_item's behavior); one already homed
                # elsewhere stays put — re-homing is move_bucket's job
                if parent is not None and self._parent_of(bid) is None:
                    self._bucket_link(parent, bid,
                                      self.crush.bucket(bid).weight)
            else:
                bid = self.add_bucket(CRUSH_BUCKET_STRAW2, t, name,
                                      [], [])
                if parent is not None:
                    self._bucket_link(parent, bid, 0)
            parent = bid
        if parent is None:
            raise ValueError("empty crush location")
        return parent

    def _parent_of(self, item: int):
        for b in self.crush.buckets:
            if b is not None and item in b.items:
                return b
        return None

    def _bucket_link(self, parent_id: int, item: int, weight: int) -> None:
        """Append an item and REBUILD the bucket: every alg's derived
        structure (list sums, straw scalers, tree nodes) must track the
        membership change or the binary codec writes inconsistent
        arrays (caught by add-item.t on a straw-v1 map)."""
        b = self.crush.bucket(parent_id)
        before = b.weight
        ws = self._bucket_weights(b)
        self.rebuild_bucket(parent_id, list(b.items) + [item],
                            ws + [weight])
        # uniform parents derive their weight from item_weight*size,
        # not the requested weight: ripple what actually changed
        self._propagate_above(
            parent_id, self.crush.bucket(parent_id).weight - before)

    def _bucket_unlink(self, item: int) -> int:
        """Detach *item* from its parent; returns its weight there."""
        p = self._parent_of(item)
        if p is None:
            return 0
        idx = p.items.index(item)
        before = p.weight
        ws = self._bucket_weights(p)
        w = ws[idx]
        items = list(p.items)
        del items[idx]
        del ws[idx]
        self.rebuild_bucket(p.id, items, ws)
        self._propagate_above(p.id,
                              self.crush.bucket(p.id).weight - before)
        return w

    def _propagate_above(self, bucket_id: int, delta: int) -> None:
        """Apply a weight delta to every ANCESTOR of bucket_id (its own
        weight was already re-derived by rebuild_bucket)."""
        p = self._parent_of(bucket_id)
        if p is None or not delta:
            return
        idx = p.items.index(bucket_id)
        ws = self._bucket_weights(p)
        ws[idx] += delta
        self.rebuild_bucket(p.id, list(p.items), ws)
        self._propagate_above(p.id, delta)

    def create_or_move_item(self, item: int, weight: int, name: str,
                            loc) -> None:
        """Place a DEVICE at the crush location, creating intermediate
        buckets and unlinking any previous position — the OSD-boot
        'ceph osd crush create-or-move' semantics
        (CrushWrapper::create_or_move_item)."""
        if item < 0:
            raise ValueError("devices only; use move_bucket for buckets")
        leaf = self._loc_chain(loc)
        self._bucket_unlink(item)
        self._bucket_link(leaf, item, weight)
        self.set_item_name(item, name)
        if item >= self.crush.max_devices:
            self.crush.max_devices = item + 1
        if self.item_class:
            self.rebuild_roots_with_classes()

    def move_bucket(self, name: str, loc) -> None:
        """Re-home an existing bucket under a new location chain
        (CrushWrapper::move_bucket)."""
        if not self.name_exists(name):
            raise ValueError(f"no bucket named {name!r}")
        bid = self.get_item_id(name)
        if bid >= 0:
            raise ValueError(f"{name!r} names a device, not a bucket")
        leaf = self._loc_chain(loc)
        # cycle guard (the reference returns -EINVAL): the destination
        # must not be the bucket itself or anything inside its subtree
        probe = leaf
        while probe is not None:
            if probe == bid:
                raise ValueError(
                    f"cannot move {name!r} under its own subtree")
            parent = self._parent_of(probe)
            probe = parent.id if parent is not None else None
        w = self.crush.bucket(bid).weight
        self._bucket_unlink(bid)
        self._bucket_link(leaf, bid, w)
        if self.item_class:
            self.rebuild_roots_with_classes()

    def get_default_bucket_alg(self) -> int:
        """Preference order over allowed_bucket_algs
        (CrushWrapper::get_default_bucket_alg)."""
        from .constants import (
            CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
            CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM)
        allowed = getattr(self.crush, "allowed_bucket_algs", 0)
        for alg in (CRUSH_BUCKET_STRAW2, CRUSH_BUCKET_STRAW,
                    CRUSH_BUCKET_TREE, CRUSH_BUCKET_LIST,
                    CRUSH_BUCKET_UNIFORM):
            if allowed & (1 << alg):
                return alg
        return CRUSH_BUCKET_STRAW2

    def _bucket_item_weight(self, b, idx: int) -> int:
        from .constants import CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM
        if b.alg == CRUSH_BUCKET_UNIFORM:
            return b.item_weight
        if b.alg == CRUSH_BUCKET_TREE:
            return b.node_weights[((idx + 1) << 1) - 1]
        return b.item_weights[idx]

    def _bucket_weights(self, b) -> list:
        return [self._bucket_item_weight(b, i)
                for i in range(len(b.items))]

    def _set_item_weight_in(self, bid: int, item: int,
                            weight: int) -> int:
        """Set *item*'s weight inside bucket *bid*, REBUILDING the
        bucket so every alg's derived structure (list sums, straw
        scalers, tree nodes) stays consistent; returns the bucket's
        weight delta.  Uniform buckets reweight EVERY item (the
        reference's crush_adjust_uniform_bucket_item_weight returns
        diff * size)."""
        from .constants import CRUSH_BUCKET_UNIFORM
        b = self.crush.bucket(bid)
        idx = b.items.index(item)
        if b.alg == CRUSH_BUCKET_UNIFORM:
            old_w = b.item_weight
            self.rebuild_bucket(bid, list(b.items),
                                [weight] * len(b.items))
            return (weight - old_w) * len(b.items)
        ws = self._bucket_weights(b)
        delta = weight - ws[idx]
        ws[idx] = weight
        self.rebuild_bucket(bid, list(b.items), ws)
        return delta

    def adjust_item_weight(self, item: int, weight: int) -> int:
        """Adjust *item*'s weight wherever it lives and propagate the
        change up EVERY ancestor chain — recursively over all buckets
        containing each changed bucket, so multi-root maps (an item
        linked under several trees) update every copy
        (CrushWrapper::adjust_item_weight's recursion,
        CrushWrapper.cc).  Ancestors are REBUILT too, so straw
        scalers and tree nodes re-derive.  Returns buckets changed."""
        changed = 0
        for b in list(self.crush.buckets):
            if b is None or item not in b.items:
                continue
            self._set_item_weight_in(b.id, item, weight)
            changed += 1
            # the recursion's count is NOT accumulated (reference
            # counts direct containments only) and an unlinked item
            # is -ENOENT, not a silent no-op
            self.adjust_item_weight(b.id,
                                    self.crush.bucket(b.id).weight)
        return changed if changed else -2

    def reweight(self) -> None:
        """Recalculate every bucket weight bottom-up from the leaf
        item weights (CrushWrapper::reweight -> crush_reweight_bucket
        recursion), rebuilding straw scalers along the way."""
        def rw(bid: int) -> int:
            b = self.crush.bucket(bid)
            ws = [rw(it) if it < 0 else b.item_weights[i]
                  for i, it in enumerate(b.items)]
            self.rebuild_bucket(bid, list(b.items), ws)
            return self.crush.bucket(bid).weight
        for b in list(self.crush.buckets):
            if b is not None and self._parent_of(b.id) is None:
                rw(b.id)

    def remove_item(self, item: int) -> None:
        """Detach a device from every bucket (+ ancestor reweight) and
        drop its name (CrushWrapper::remove_item)."""
        while self._parent_of(item) is not None:
            self._bucket_unlink(item)
        self.name_map.pop(item, None)
        if self.item_class:
            self.item_class.pop(item, None)
            self.rebuild_roots_with_classes()

    def rebuild_roots_with_classes(self, pins=None) -> None:
        """(Re)build the per-class SHADOW trees (CrushWrapper::
        rebuild_roots_with_classes): for every non-shadow root and
        every device class, clone the tree keeping only that class's
        devices.  Shadow buckets are named '<orig>~<class>' (invalid
        crush names, so decompile hides them as 'id N class C'
        comments) and recorded in class_bucket[orig][class] — the take
        target for class-scoped rules."""
        # destroy existing shadows first (idempotent rebuild)
        for b in list(self.crush.buckets):
            if b is None:
                continue
            if "~" in self.name_map.get(b.id, ""):
                self.crush.buckets[-1 - b.id] = None
                self.name_map.pop(b.id, None)
        self.class_bucket = {}
        if not self.item_class or not self.class_map:
            return
        roots = sorted(
            b.id for b in self.crush.buckets if b is not None
            and self._parent_of(b.id) is None)
        self._shadow_pins = pins or {}
        for r in roots:                      # set<int> ascending
            for c in sorted(self.class_map):  # class id ascending
                self._device_class_clone(r, c)
        self._shadow_pins = {}

    def _device_class_clone(self, oid: int, c: int) -> int:
        """DFS child-first clone (CrushWrapper::device_class_clone):
        devices of other classes are dropped; child BUCKET clones are
        kept even when empty; ids take the lowest free slots in
        creation order (which the recorded goldens pin)."""
        name = f"{self.name_map[oid]}~{self.class_map[c]}"
        if self.name_exists(name):
            return self.get_item_id(name)
        b = self.crush.bucket(oid)
        items: list = []
        weights: list = []
        for i, it in enumerate(b.items):
            w = self._bucket_item_weight(b, i)
            if it >= 0:
                if self.item_class.get(it) == c:
                    items.append(it)
                    weights.append(w)
            else:
                cid = self._device_class_clone(it, c)
                items.append(cid)
                weights.append(self.crush.bucket(cid).weight)
        # a decompiled text map pins shadow ids in its 'id N class C'
        # lines: honor them so class-bearing maps round-trip with
        # stable ids (the reference parses those lines the same way)
        pin = getattr(self, "_shadow_pins", {}).get(
            (self.name_map[oid], self.class_map[c]), 0)
        nid = self.add_bucket(b.alg, b.type, name, items, weights,
                              id=pin)
        self.class_bucket.setdefault(oid, {})[c] = nid
        return nid

    def split_id_class(self, bid: int):
        """Shadow id -> (original id, class id); (bid, None) when not
        a shadow (CrushWrapper::split_id_class)."""
        for orig, per_class in self.class_bucket.items():
            for c, shadow in per_class.items():
                if shadow == bid:
                    return orig, c
        return bid, None

    def get_loc(self, item: int) -> list:
        """[(type_name, bucket_name), ...] from the item up to its root
        (CrushLocation lookup)."""
        out = []
        p = self._parent_of(item)
        while p is not None:
            out.append((self.get_type_name(p.type),
                        self.get_item_name(p.id)))
            p = self._parent_of(p.id)
        return out

    def add_bucket(self, alg: int, type: int, name: str,
                   items: Sequence[int] = (), weights: Sequence[int] = (),
                   id: int = 0) -> int:
        b = builder.make_bucket(alg, type, items, weights, id,
                                self.crush.straw_calc_version)
        bid = self.crush.add_bucket(b, None if id == 0 else id)
        self.set_item_name(bid, name)
        return bid

    def get_bucket(self, id: int) -> Bucket:
        b = self.crush.bucket(id)
        if b is None:
            raise KeyError(f"no bucket {id}")
        return b

    def rebuild_bucket(self, id: int, items: Sequence[int],
                       weights: Sequence[int]) -> None:
        """Replace a bucket's items/weights in place (reweight/add/remove)."""
        old = self.get_bucket(id)
        b = builder.make_bucket(old.alg, old.type, items, weights, id,
                                self.crush.straw_calc_version)
        self.crush.buckets[-1 - id] = b

    def get_max_devices(self) -> int:
        return self.crush.max_devices

    def set_max_devices(self, n: int) -> None:
        self.crush.max_devices = n

    # ---- rules ------------------------------------------------------------
    def add_rule(self, rule: Rule, name: str, ruleno: int = -1) -> int:
        rno = self.crush.add_rule(rule, ruleno)
        self.rule_name_map[rno] = name
        return rno

    def remove_rule(self, ruleno: int) -> int:
        """CrushWrapper::remove_rule: drop the rule slot + its name."""
        if ruleno < 0 or ruleno >= len(self.crush.rules) \
                or self.crush.rules[ruleno] is None:
            return -2
        self.crush.rules[ruleno] = None
        self.rule_name_map.pop(ruleno, None)
        return 0

    def add_simple_rule(self, name: str, root_name: str,
                        failure_domain_name: str = "",
                        device_class: str = "",
                        mode: str = "firstn",
                        rule_type: int = PG_POOL_TYPE_REPLICATED,
                        ruleno: int = -1) -> int:
        if self.rule_exists(name):
            return -17  # EEXIST
        if not self.name_exists(root_name):
            return -2   # ENOENT
        root = self.get_item_id(root_name)
        ftype = 0
        if failure_domain_name:
            ftype = self.get_type_id(failure_domain_name)
            if ftype < 0:
                return -22  # EINVAL
        if device_class:
            if not self.class_exists(device_class):
                return -22
            c = self.get_or_create_class_id(device_class)
            shadow = self.class_bucket.get(root, {}).get(c)
            if shadow is None:
                return -22
            root = shadow
        if mode not in ("firstn", "indep"):
            return -22
        if ruleno < 0:
            ruleno = next(
                (i for i in range(self.crush.max_rules + 1)
                 if not self.rule_exists(i) and not self.ruleset_exists(i)))
        steps: List[RuleStep] = []
        if mode == "indep":
            steps.append(RuleStep(CRUSH_RULE_SET_CHOOSELEAF_TRIES, 5, 0))
            steps.append(RuleStep(CRUSH_RULE_SET_CHOOSE_TRIES, 100, 0))
        steps.append(RuleStep(CRUSH_RULE_TAKE, root, 0))
        if ftype:
            steps.append(RuleStep(
                CRUSH_RULE_CHOOSELEAF_FIRSTN if mode == "firstn"
                else CRUSH_RULE_CHOOSELEAF_INDEP, 0, ftype))
        else:
            steps.append(RuleStep(
                CRUSH_RULE_CHOOSE_FIRSTN if mode == "firstn"
                else CRUSH_RULE_CHOOSE_INDEP, 0, 0))
        steps.append(RuleStep(CRUSH_RULE_EMIT, 0, 0))
        rule = Rule(steps=steps, ruleset=ruleno, type=rule_type,
                    min_size=1 if mode == "firstn" else 3,
                    max_size=10 if mode == "firstn" else 20)
        return self.add_rule(rule, name, ruleno)

    def set_rule_mask_max_size(self, ruleno: int, max_size: int) -> None:
        self.crush.rules[ruleno].max_size = max_size

    def find_rule(self, ruleset: int, type: int, size: int) -> int:
        return crush_find_rule(self.crush, ruleset, type, size)

    # ---- tunables ---------------------------------------------------------
    def set_tunables_profile(self, profile: str) -> None:
        self.crush.set_tunables_profile(profile)

    # ---- choose args ------------------------------------------------------
    def choose_args_create(self, key: int = 0) -> List[ChooseArg]:
        args = [ChooseArg() for _ in range(self.crush.max_buckets)]
        self.crush.choose_args[key] = args
        return args

    def choose_args_get(self, key: int = 0) -> Optional[List[ChooseArg]]:
        return self.crush.choose_args.get(key)

    # ---- mapping ----------------------------------------------------------
    def do_rule(self, ruleno: int, x: int, maxout: int,
                weight: Sequence[int],
                choose_args_index: Optional[int] = None) -> List[int]:
        ca = None
        if choose_args_index is not None:
            ca = self.crush.choose_args.get(choose_args_index)
        return crush_do_rule(self.crush, ruleno, x, maxout, weight, ca)

    # ---- introspection ----------------------------------------------------
    def get_children(self, id: int) -> List[int]:
        b = self.crush.bucket(id)
        return list(b.items) if b else []

    def get_full_location(self, item: int) -> Dict[str, str]:
        """Walk up the tree: type name -> bucket name for each ancestor."""
        loc = {}
        cur = item
        found = True
        while found:
            found = False
            for b in self.crush.buckets:
                if b is not None and cur in b.items:
                    loc[self.get_type_name(b.type)] = self.get_item_name(b.id)
                    cur = b.id
                    found = True
                    break
        return loc
