"""CRUSH constants — opcodes, bucket algorithms, tunable profiles.

Values match the reference data model (src/crush/crush.h) because crush maps
and their evaluation semantics are defined in terms of them.
"""

# rule opcodes (crush.h:52-70)
CRUSH_RULE_NOOP = 0
CRUSH_RULE_TAKE = 1
CRUSH_RULE_CHOOSE_FIRSTN = 2
CRUSH_RULE_CHOOSE_INDEP = 3
CRUSH_RULE_EMIT = 4
CRUSH_RULE_CHOOSELEAF_FIRSTN = 6
CRUSH_RULE_CHOOSELEAF_INDEP = 7
CRUSH_RULE_SET_CHOOSE_TRIES = 8
CRUSH_RULE_SET_CHOOSELEAF_TRIES = 9
CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES = 10
CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
CRUSH_RULE_SET_CHOOSELEAF_VARY_R = 12
CRUSH_RULE_SET_CHOOSELEAF_STABLE = 13

# bucket algorithms (crush.h crush_algorithm)
CRUSH_BUCKET_UNIFORM = 1
CRUSH_BUCKET_LIST = 2
CRUSH_BUCKET_TREE = 3
CRUSH_BUCKET_STRAW = 4
CRUSH_BUCKET_STRAW2 = 5

CRUSH_HASH_RJENKINS1 = 0

# sentinel outputs (crush.h)
CRUSH_ITEM_UNDEF = 0x7FFFFFFE  # choose_indep: placeholder pre-assignment
CRUSH_ITEM_NONE = 0x7FFFFFFF   # no result

CRUSH_MAX_DEPTH = 10
CRUSH_MAX_RULESET = 256

# pool/rule types (osd_types pg_pool_t)
PG_POOL_TYPE_REPLICATED = 1
PG_POOL_TYPE_ERASURE = 3

ALL_BUCKET_ALGS = ((1 << CRUSH_BUCKET_UNIFORM) | (1 << CRUSH_BUCKET_LIST) |
                   (1 << CRUSH_BUCKET_TREE) | (1 << CRUSH_BUCKET_STRAW) |
                   (1 << CRUSH_BUCKET_STRAW2))
LEGACY_ALLOWED_BUCKET_ALGS = ((1 << CRUSH_BUCKET_UNIFORM) |
                              (1 << CRUSH_BUCKET_LIST) |
                              (1 << CRUSH_BUCKET_STRAW))
HAMMER_ALLOWED_BUCKET_ALGS = ((1 << CRUSH_BUCKET_UNIFORM) |
                              (1 << CRUSH_BUCKET_LIST) |
                              (1 << CRUSH_BUCKET_STRAW) |
                              (1 << CRUSH_BUCKET_STRAW2))

# tunable profiles (CrushWrapper.h:140-212)
TUNABLE_PROFILES = {
    "argonaut": dict(choose_local_tries=2, choose_local_fallback_tries=5,
                     choose_total_tries=19, chooseleaf_descend_once=0,
                     chooseleaf_vary_r=0, chooseleaf_stable=0,
                     allowed_bucket_algs=LEGACY_ALLOWED_BUCKET_ALGS),
    "bobtail": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                    choose_total_tries=50, chooseleaf_descend_once=1,
                    chooseleaf_vary_r=0, chooseleaf_stable=0,
                    allowed_bucket_algs=LEGACY_ALLOWED_BUCKET_ALGS),
    "firefly": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                    choose_total_tries=50, chooseleaf_descend_once=1,
                    chooseleaf_vary_r=1, chooseleaf_stable=0,
                    allowed_bucket_algs=LEGACY_ALLOWED_BUCKET_ALGS),
    "hammer": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                   choose_total_tries=50, chooseleaf_descend_once=1,
                   chooseleaf_vary_r=1, chooseleaf_stable=0,
                   allowed_bucket_algs=HAMMER_ALLOWED_BUCKET_ALGS),
    "jewel": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                  choose_total_tries=50, chooseleaf_descend_once=1,
                  chooseleaf_vary_r=1, chooseleaf_stable=1,
                  allowed_bucket_algs=HAMMER_ALLOWED_BUCKET_ALGS),
}
TUNABLE_PROFILES["optimal"] = TUNABLE_PROFILES["jewel"]
TUNABLE_PROFILES["default"] = TUNABLE_PROFILES["jewel"]
TUNABLE_PROFILES["legacy"] = TUNABLE_PROFILES["argonaut"]

S64_MIN = -(1 << 63)
