"""crush_ln: fixed-point 2^44*log2(x+1) via lookup tables.

Semantics identical to the reference straw2 draw's log (src/crush/mapper.c
crush_ln, :243-290): normalize x+1 into [2^15, 2^17), split into a
table-indexed high part and an interpolated low part, both via the LUTs in
_ln_table_data.  Exactness here decides straw2 argmax winners, so the whole
path is integer.
"""
from __future__ import annotations

import numpy as np

from ._ln_table_data import RH_LH_TBL, LL_TBL

# numpy copies for the vectorized host mapper / device upload
RH_LH_NP = np.array(RH_LH_TBL, dtype=np.uint64)
LL_NP = np.array(LL_TBL, dtype=np.uint64)


def crush_ln(xin: int) -> int:
    x = (xin + 1) & 0xFFFFFFFF

    # normalize into [2^15, 2^17): find shift so bit 15 or 16 set
    iexpon = 15
    if not (x & 0x18000):
        bits = 16 - (x & 0x1FFFF).bit_length()
        x <<= bits
        iexpon = 15 - bits

    index1 = (x >> 8) << 1
    rh = RH_LH_TBL[index1 - 256]          # ~ 2^56/index1
    lh = RH_LH_TBL[index1 + 1 - 256]      # ~ 2^48*log2(index1/256)

    xl64 = (x * rh) >> 48                 # ~ 2^48*(2^15 + x%2^8) scaled
    index2 = xl64 & 0xFF
    ll = LL_TBL[index2]                   # ~ 2^48*log2(1+index2/2^15)

    result = iexpon << (12 + 32)
    result += (lh + ll) >> (48 - 12 - 32)
    return result


def crush_ln_np(xin: np.ndarray) -> np.ndarray:
    """Vectorized crush_ln over uint32 inputs (0..0xffff expected)."""
    x = (xin.astype(np.uint64) + 1) & np.uint64(0xFFFFFFFF)
    # bit-length based normalization: values are <= 0x10000 here
    iexpon = np.full(x.shape, 15, dtype=np.int64)
    need = (x & np.uint64(0x18000)) == 0
    # compute number of leading shifts for values below 2^15
    xs = x.copy()
    for _ in range(15):  # bounded: x >= 1
        m = need & ((xs & np.uint64(0x18000)) == 0)
        if not m.any():
            break
        xs = np.where(m, xs << np.uint64(1), xs)
        iexpon = np.where(m, iexpon - 1, iexpon)
    x = xs
    index1 = ((x >> np.uint64(8)) << np.uint64(1)).astype(np.int64)
    rh = RH_LH_NP[index1 - 256]
    lh = RH_LH_NP[index1 + 1 - 256]
    xl64 = (x * rh) >> np.uint64(48)
    index2 = (xl64 & np.uint64(0xFF)).astype(np.int64)
    ll = LL_NP[index2]
    result = (iexpon.astype(np.uint64) << np.uint64(44)) + \
        ((lh + ll) >> np.uint64(4))
    return result
