"""OSDMap: the epoched cluster map and the pg→OSD mapping pipeline.

State and pipeline semantics mirror the reference (src/osd/OSDMap.{h,cc}):
osd up/exists flags, 16.16 in/out weights (OSDMap.h:512), primary affinity
(:516), pg_upmap / pg_upmap_items overrides (:519-520), pg_temp /
primary_temp, pools, and the embedded crush map.  The full per-PG pipeline
(_pg_to_raw_osds → _apply_upmap → _raw_to_up_osds → _pick_primary →
_apply_primary_affinity → _get_temp_osds, OSDMap.cc:1936-2185) is
implemented exactly; batch mapping lives in mapping.py where the crush
evaluation runs as one device call and the post-passes vectorize.

Incremental diffs (OSDMap.h:393) carry new/changed state between epochs the
way the mon publishes them.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..crush import CrushWrapper
from ..crush.constants import CRUSH_ITEM_NONE
from ..crush.hash import crush_hash32_2
from .types import pg_pool_t, pg_t

CEPH_OSD_IN = 0x10000
CEPH_OSD_OUT = 0
CEPH_OSD_MAX_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_DEFAULT_PRIMARY_AFFINITY = 0x10000

# osd_state bits (include/rados.h)
CEPH_OSD_EXISTS = 1
CEPH_OSD_UP = 2


@dataclass
class Incremental:
    """Delta between epoch-1 and epoch (OSDMap.h:393-395 analog)."""
    epoch: int = 0
    new_max_osd: int = -1
    new_flags: int = -1        # cluster CEPH_OSDMAP_* flags; -1 = keep
    new_pools: Dict[int, pg_pool_t] = field(default_factory=dict)
    new_pool_names: Dict[int, str] = field(default_factory=dict)
    old_pools: List[int] = field(default_factory=list)
    new_up: Dict[int, bool] = field(default_factory=dict)       # osd -> up?
    new_weight: Dict[int, int] = field(default_factory=dict)
    # weight to restore if the osd boots after an AUTO out (replicated
    # like osd_xinfo_t::old_weight, osd/OSDMap.h; 0 = clear the memo)
    new_old_weight: Dict[int, int] = field(default_factory=dict)
    new_primary_affinity: Dict[int, int] = field(default_factory=dict)
    new_pg_upmap: Dict[pg_t, List[int]] = field(default_factory=dict)
    old_pg_upmap: List[pg_t] = field(default_factory=list)
    new_pg_upmap_items: Dict[pg_t, List[Tuple[int, int]]] = \
        field(default_factory=dict)
    old_pg_upmap_items: List[pg_t] = field(default_factory=list)
    new_pg_temp: Dict[pg_t, List[int]] = field(default_factory=dict)
    new_primary_temp: Dict[pg_t, int] = field(default_factory=dict)
    new_erasure_code_profiles: Dict[str, Dict[str, str]] = \
        field(default_factory=dict)
    crush: Optional[CrushWrapper] = None
    # mon-service payloads committed with the epoch (PaxosService
    # siblings sharing the one Paxos, src/mon/PaxosService.h): cluster
    # log entries (LogMonitor) and config-key mutations
    # (ConfigKeyService; value None = delete).  OSDMap consumers ignore
    # them — they are replicated mon state riding the same consensus.
    service_log: List[Tuple[float, str, str, str]] = \
        field(default_factory=list)          # (stamp, who, level, text)
    service_config_kv: Dict[str, Optional[str]] = \
        field(default_factory=dict)


# cluster-wide osdmap flags (include/rados.h:139-142)
CEPH_OSDMAP_NEARFULL = 1 << 0
CEPH_OSDMAP_FULL = 1 << 1
CEPH_OSDMAP_PAUSEWR = 1 << 3


class OSDMap:
    def __init__(self):
        self.epoch = 0
        self.flags = 0
        # map identity/stamps (OSDMap::print header fields); tools
        # built maps (osdmaptool --createsimple) keep the zero fsid
        # like the reference's zeroed uuid_d
        self.fsid = "00000000-0000-0000-0000-000000000000"
        self.created = 0.0
        self.modified = 0.0
        self.crush_version = 1
        self.max_osd = 0
        self.osd_state: List[int] = []
        self.osd_weight: List[int] = []
        self.osd_primary_affinity: Optional[List[int]] = None
        self.pools: Dict[int, pg_pool_t] = {}
        self.pool_name: Dict[int, str] = {}
        self.pool_max = -1
        self.pg_upmap: Dict[pg_t, List[int]] = {}
        self.pg_upmap_items: Dict[pg_t, List[Tuple[int, int]]] = {}
        self.pg_temp: Dict[pg_t, List[int]] = {}
        self.primary_temp: Dict[pg_t, int] = {}
        # osd -> weight before an automatic out (osd_xinfo_t::old_weight):
        # lives in the map so every mon agrees across failovers
        self.osd_old_weight: Dict[int, int] = {}
        self.erasure_code_profiles: Dict[str, Dict[str, str]] = {}
        self.crush = CrushWrapper()

    # ---- osd state --------------------------------------------------------
    def set_max_osd(self, n: int) -> None:
        while len(self.osd_state) < n:
            self.osd_state.append(0)
            self.osd_weight.append(CEPH_OSD_OUT)
            if self.osd_primary_affinity is not None:
                self.osd_primary_affinity.append(
                    CEPH_OSD_DEFAULT_PRIMARY_AFFINITY)
        del self.osd_state[n:]
        del self.osd_weight[n:]
        self.max_osd = n
        if self.crush.get_max_devices() < n:
            self.crush.set_max_devices(n)

    def exists(self, osd: int) -> bool:
        return (0 <= osd < self.max_osd
                and bool(self.osd_state[osd] & CEPH_OSD_EXISTS))

    def is_up(self, osd: int) -> bool:
        return (0 <= osd < self.max_osd
                and bool(self.osd_state[osd] & CEPH_OSD_UP))

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def is_in(self, osd: int) -> bool:
        return self.exists(osd) and self.get_weight(osd) > 0

    def is_out(self, osd: int) -> bool:
        return not self.is_in(osd)

    def get_weight(self, osd: int) -> int:
        return self.osd_weight[osd]

    def set_osd(self, osd: int, up: bool = True,
                weight: int = CEPH_OSD_IN) -> None:
        """Create/refresh an osd entry (test/mini-cluster convenience)."""
        if osd >= self.max_osd:
            self.set_max_osd(osd + 1)
        self.osd_state[osd] = CEPH_OSD_EXISTS | (CEPH_OSD_UP if up else 0)
        self.osd_weight[osd] = weight

    def set_primary_affinity(self, osd: int, aff: int) -> None:
        if self.osd_primary_affinity is None:
            self.osd_primary_affinity = \
                [CEPH_OSD_DEFAULT_PRIMARY_AFFINITY] * self.max_osd
        self.osd_primary_affinity[osd] = aff

    # ---- pools ------------------------------------------------------------
    def add_pool(self, name: str, pool: pg_pool_t,
                 pool_id: int = -1) -> int:
        if pool_id < 0:
            self.pool_max += 1
            pool_id = self.pool_max
        else:
            self.pool_max = max(self.pool_max, pool_id)
        self.pools[pool_id] = pool
        self.pool_name[pool_id] = name
        return pool_id

    def get_pg_pool(self, pool_id: int) -> Optional[pg_pool_t]:
        return self.pools.get(pool_id)

    def lookup_pg_pool_name(self, name: str) -> int:
        for pid, n in self.pool_name.items():
            if n == name:
                return pid
        return -2  # -ENOENT

    # ---- object → pg ------------------------------------------------------
    def map_to_pg(self, pool_id: int, name: str, key: str = "",
                  nspace: str = "") -> pg_t:
        pool = self.pools[pool_id]
        ps = pool.hash_key(key if key else name, nspace)
        return pg_t(pool_id, ps)

    object_locator_to_pg = map_to_pg

    # ---- pg → osds pipeline (OSDMap.cc:1936-2185) -------------------------
    def _pg_to_raw_osds(self, pool: pg_pool_t, pg: pg_t
                        ) -> Tuple[List[int], int]:
        pps = pool.raw_pg_to_pps(pg)
        size = pool.size
        ruleno = self.crush.find_rule(pool.crush_rule, pool.type, size)
        osds: List[int] = []
        if ruleno >= 0:
            osds = self.crush.do_rule(
                ruleno, pps, size, self.osd_weight,
                choose_args_index=pg.pool
                if pg.pool in self.crush.crush.choose_args else None)
        self._remove_nonexistent_osds(pool, osds)
        return osds, pps

    def _remove_nonexistent_osds(self, pool: pg_pool_t,
                                 osds: List[int]) -> None:
        if pool.can_shift_osds():
            osds[:] = [o for o in osds if self.exists(o)]
        else:
            for i, o in enumerate(osds):
                if o != CRUSH_ITEM_NONE and not self.exists(o):
                    osds[i] = CRUSH_ITEM_NONE

    def _apply_upmap(self, pool: pg_pool_t, raw_pg: pg_t,
                     raw: List[int]) -> List[int]:
        pg = pool.raw_pg_to_pg(raw_pg)
        p = self.pg_upmap.get(pg)
        if p is not None:
            if any(o != CRUSH_ITEM_NONE and 0 <= o < self.max_osd
                   and self.osd_weight[o] == 0 for o in p):
                # an explicit target is marked out: ignore the whole
                # override, including any pg_upmap_items (OSDMap.cc:1971)
                return raw
            raw = list(p)
        q = self.pg_upmap_items.get(pg)
        if q is not None:
            for frm, to in q:
                exists = False
                pos = -1
                for i, o in enumerate(raw):
                    if o == to:
                        exists = True
                        break
                    if (o == frm and pos < 0
                            and not (to != CRUSH_ITEM_NONE
                                     and 0 <= to < self.max_osd
                                     and self.osd_weight[to] == 0)):
                        pos = i
                if not exists and pos >= 0:
                    raw[pos] = to
        return raw

    def _raw_to_up_osds(self, pool: pg_pool_t,
                        raw: List[int]) -> List[int]:
        if pool.can_shift_osds():
            return [o for o in raw if self.exists(o) and self.is_up(o)]
        return [o if (o != CRUSH_ITEM_NONE and self.exists(o)
                      and self.is_up(o)) else CRUSH_ITEM_NONE
                for o in raw]

    @staticmethod
    def _pick_primary(osds: List[int]) -> int:
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def _apply_primary_affinity(self, seed: int, pool: pg_pool_t,
                                osds: List[int], primary: int
                                ) -> Tuple[List[int], int]:
        aff = self.osd_primary_affinity
        if aff is None:
            return osds, primary
        if not any(o != CRUSH_ITEM_NONE
                   and aff[o] != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
                   for o in osds):
            return osds, primary
        pos = -1
        for i, o in enumerate(osds):
            if o == CRUSH_ITEM_NONE:
                continue
            a = aff[o]
            if (a < CEPH_OSD_MAX_PRIMARY_AFFINITY
                    and (crush_hash32_2(seed, o) >> 16) >= a):
                # rejected as primary; remember as fallback
                if pos < 0:
                    pos = i
            else:
                pos = i
                break
        if pos < 0:
            return osds, primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            osds = [primary] + osds[:pos] + osds[pos + 1:]
        return osds, primary

    def _get_temp_osds(self, pool: pg_pool_t, raw_pg: pg_t
                       ) -> Tuple[List[int], int]:
        pg = pool.raw_pg_to_pg(raw_pg)
        temp_pg: List[int] = []
        p = self.pg_temp.get(pg)
        if p is not None:
            for o in p:
                if not self.exists(o) or self.is_down(o):
                    if not pool.can_shift_osds():
                        temp_pg.append(CRUSH_ITEM_NONE)
                else:
                    temp_pg.append(o)
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1 and temp_pg:
            temp_primary = self._pick_primary(temp_pg)
        return temp_pg, temp_primary

    def pg_to_raw_osds(self, pg: pg_t) -> Tuple[List[int], int]:
        pool = self.get_pg_pool(pg.pool)
        if pool is None:
            return [], -1
        raw, _ = self._pg_to_raw_osds(pool, pg)
        return raw, self._pick_primary(raw)

    def pg_to_raw_up(self, pg: pg_t) -> Tuple[List[int], int]:
        pool = self.get_pg_pool(pg.pool)
        if pool is None:
            return [], -1
        raw, pps = self._pg_to_raw_osds(pool, pg)
        raw = self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up_osds(pool, raw)
        primary = self._pick_primary(raw)
        up, primary = self._apply_primary_affinity(pps, pool, up, primary)
        return up, primary

    def pg_to_up_acting_osds(self, pg: pg_t
                             ) -> Tuple[List[int], int, List[int], int]:
        """Returns (up, up_primary, acting, acting_primary)
        (OSDMap.cc:2154-2185)."""
        pool = self.get_pg_pool(pg.pool)
        if pool is None or pg.ps >= pool.pg_num:
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, pg)
        raw, pps = self._pg_to_raw_osds(pool, pg)
        raw = self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._pick_primary(up)
        up, up_primary = self._apply_primary_affinity(
            pps, pool, up, up_primary)
        if not acting:
            acting = up
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    # ---- epochs -----------------------------------------------------------
    def apply_incremental(self, inc: Incremental) -> None:
        assert inc.epoch == self.epoch + 1, (inc.epoch, self.epoch)
        self.epoch = inc.epoch
        if inc.new_flags >= 0:
            self.flags = inc.new_flags
        if inc.new_max_osd >= 0:
            self.set_max_osd(inc.new_max_osd)
        for pid in inc.old_pools:
            self.pools.pop(pid, None)
            self.pool_name.pop(pid, None)
        for pid, pool in inc.new_pools.items():
            self.pools[pid] = pool
            self.pool_max = max(self.pool_max, pid)
        for pid, name in inc.new_pool_names.items():
            self.pool_name[pid] = name
        for osd, up in inc.new_up.items():
            st = self.osd_state[osd] | CEPH_OSD_EXISTS
            self.osd_state[osd] = (st | CEPH_OSD_UP) if up \
                else (st & ~CEPH_OSD_UP)
        for osd, w in inc.new_weight.items():
            if osd >= self.max_osd:
                self.set_max_osd(osd + 1)
            self.osd_state[osd] |= CEPH_OSD_EXISTS
            self.osd_weight[osd] = w
        for osd, w in inc.new_old_weight.items():
            if w:
                self.osd_old_weight[osd] = w
            else:
                self.osd_old_weight.pop(osd, None)
        for osd, a in inc.new_primary_affinity.items():
            self.set_primary_affinity(osd, a)
        for pg in inc.old_pg_upmap:
            self.pg_upmap.pop(pg, None)
        self.pg_upmap.update(inc.new_pg_upmap)
        for pg in inc.old_pg_upmap_items:
            self.pg_upmap_items.pop(pg, None)
        self.pg_upmap_items.update(inc.new_pg_upmap_items)
        for pg, osds in inc.new_pg_temp.items():
            if osds:
                self.pg_temp[pg] = list(osds)
            else:
                self.pg_temp.pop(pg, None)
        for pg, p in inc.new_primary_temp.items():
            if p >= 0:
                self.primary_temp[pg] = p
            else:
                self.primary_temp.pop(pg, None)
        self.erasure_code_profiles.update(inc.new_erasure_code_profiles)
        if inc.crush is not None:
            self.crush = inc.crush
