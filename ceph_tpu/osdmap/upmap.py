"""pg-upmap optimizer — semantics-exact port of OSDMap::calc_pg_upmaps.

The reference balancer's upmap mode (src/osd/OSDMap.cc:3926, driven by
`osdmaptool --upmap` and mgr/balancer) iteratively finds the fullest
OSD whose deviation ratio exceeds the threshold and retargets ONE of
its PGs onto underfull OSDs via the constrained rule re-mapper
(crush/remap.py), restarting until nothing exceeds the threshold or
``max`` changes were made.

Decision-identical with the reference, which requires care beyond the
algorithm's shape:
  - float32 arithmetic for weights/targets/deviations (the reference
    uses C ``float``; threshold comparisons sit exactly on boundaries);
  - map/set orderings: pgs ascend (pool, seed); osds ascend;
    deviation ties break by ascending osd, and the fullest-first scan
    visits equal deviations in DESCENDING osd order (C++ multimap
    rbegin reverses insertion order within equal keys);
  - ``orig`` comes from the RAW mapping (no upmaps applied), while the
    per-iteration PG counts come from the upmap-applied ``up`` sets.

Byte-exact agreement with the reference's recorded `osdmaptool
--upmap` output is pinned by tests/test_osdmaptool_golden.py.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..crush.remap import get_rule_weight_osd_map, try_remap_rule
from .osdmap import OSDMap
from .types import pg_t

NONE = 0x7FFFFFFF
F = np.float32


class PendingInc:
    """The slice of OSDMap::Incremental calc_pg_upmaps fills."""

    def __init__(self):
        self.new_pg_upmap_items: Dict[pg_t, List[Tuple[int, int]]] = {}
        self.old_pg_upmap_items: Set[pg_t] = set()


def _raw_all(m: OSDMap, pool_id: int, pool) -> List[List[int]]:
    """RAW mapping (no upmaps) for every pg of the pool, batched via
    the native evaluator when available (the per-iteration loop only
    overlays upmap items on top of this, so it is computed once)."""
    size = pool.size
    ruleno = m.crush.find_rule(pool.crush_rule, pool.type, size)
    if ruleno < 0:
        return [[] for _ in range(pool.pg_num)]
    pps = [pool.raw_pg_to_pps(pg_t(pool_id, ps))
           for ps in range(pool.pg_num)]
    choose_args = m.crush.crush.choose_args.get(pool_id)
    rows: Optional[List[List[int]]] = None
    try:
        from ..native import NativeCrushMapper, native_available
        if native_available():
            nm = NativeCrushMapper(m.crush.crush, choose_args)
            out, lens = nm.do_rule_batch(ruleno, pps, size, m.osd_weight)
            rows = [[int(v) for v in out[i][:lens[i]]]
                    for i in range(len(pps))]
    except Exception:
        rows = None
    if rows is None:
        rows = [m.crush.do_rule(ruleno, x, size, m.osd_weight,
                                choose_args_index=pool_id
                                if choose_args is not None else None)
                for x in pps]
    for row in rows:
        m._remove_nonexistent_osds(pool, row)
    return rows


def try_pg_upmap(m: OSDMap, pg: pg_t, overfull: Set[int],
                 underfull: Sequence[int],
                 raw: Sequence[int]
                 ) -> Optional[Tuple[List[int], List[int]]]:
    """(OSDMap::try_pg_upmap)  ``raw`` is the pg's raw mapping
    (caller-cached _pg_to_raw_osds result).  Returns (orig, out) or
    None when no useful remap exists."""
    pool = m.get_pg_pool(pg.pool)
    if pool is None:
        return None
    rule = m.crush.find_rule(pool.crush_rule, pool.type, pool.size)
    if rule < 0:
        return None
    orig = list(raw)
    if not any(o in overfull for o in orig):
        return None
    out = try_remap_rule(m.crush, rule, pool.size, overfull, underfull,
                         orig)
    if out is None or out == orig:
        return None
    return orig, out


def calc_pg_upmaps(m: OSDMap, max_deviation_ratio: float, max: int,
                   only_pools: Optional[Set[int]] = None,
                   pending_inc: Optional[PendingInc] = None) -> int:
    """(OSDMap::calc_pg_upmaps)  Mutates ``m``'s pg_upmap_items like
    the reference mutates its tmp copy; returns changes made."""
    if pending_inc is None:
        pending_inc = PendingInc()
    if not only_pools:
        only_pools = set(m.pools.keys())
    max_dev = F(max_deviation_ratio)

    raw_cache: Dict[int, List[List[int]]] = {}
    for pool_id in sorted(only_pools):
        pool = m.pools.get(pool_id)
        if pool is not None:
            raw_cache[pool_id] = _raw_all(m, pool_id, pool)

    num_changed = 0
    while True:
        pgs_by_osd: Dict[int, List[pg_t]] = {}
        total_pgs = 0
        osd_weight_total = F(0.0)
        osd_weight: Dict[int, F] = {}
        for pool_id in sorted(m.pools.keys()):
            if pool_id not in only_pools:
                continue
            pool = m.pools[pool_id]
            raws = raw_cache[pool_id]
            for ps in range(pool.pg_num):
                pg = pg_t(pool_id, ps)
                row = raws[ps]
                if pg in m.pg_upmap or pg in m.pg_upmap_items:
                    row = m._apply_upmap(pool, pg, list(row))
                # the reference counts UP sets (pg_to_up_acting_osds):
                # down/nonexistent osds must not accumulate pgs
                for o in m._raw_to_up_osds(pool, list(row)):
                    if o != NONE:
                        pgs_by_osd.setdefault(o, []).append(pg)
            total_pgs += pool.size * pool.pg_num

            ruleno = m.crush.find_rule(pool.crush_rule, pool.type,
                                       pool.size)
            # no matching rule -> empty weight map (the reference's
            # unsigned-index ENOENT), while total_pgs still counted
            pmap = get_rule_weight_osd_map(m.crush, ruleno) \
                if ruleno >= 0 else {}
            for osd in sorted(pmap):
                # get_weightf: 16.16 in/out weight as C float
                wf = F(F(m.osd_weight[osd]) / F(0x10000)) \
                    if 0 <= osd < m.max_osd else F(0.0)
                adjusted = F(wf * F(pmap[osd]))
                osd_weight[osd] = F(osd_weight.get(osd, F(0.0))
                                    + adjusted)
                osd_weight_total = F(osd_weight_total + adjusted)
        for osd in sorted(osd_weight):
            pgs_by_osd.setdefault(osd, [])

        if osd_weight_total == 0:
            break
        pgs_per_weight = F(F(total_pgs) / osd_weight_total)

        # deviation per osd; multimap<float,int> == stable sort by
        # deviation over ascending-osd insertion order
        osd_deviation: Dict[int, F] = {}
        deviation_osd: List[Tuple[F, int]] = []
        overfull: Set[int] = set()
        for osd in sorted(pgs_by_osd):
            target = F(F(osd_weight.get(osd, F(0.0))) * pgs_per_weight)
            deviation = F(F(len(pgs_by_osd[osd])) - target)
            osd_deviation[osd] = deviation
            deviation_osd.append((deviation, osd))
            if float(deviation) >= 1.0:
                overfull.add(osd)
        deviation_osd.sort(key=lambda t: float(t[0]))  # stable

        underfull: List[int] = []
        for dev, osd in deviation_osd:
            if float(dev) >= -0.999:
                break
            underfull.append(osd)
        if not overfull or not underfull:
            break

        # fullest first; reversed(stable sort) == multimap rbegin
        # (equal deviations visited in descending osd order)
        restart = False
        for dev, osd in reversed(deviation_osd):
            target = F(F(osd_weight.get(osd, F(0.0))) * pgs_per_weight)
            assert target > 0
            if F(dev / target) < max_dev:
                break
            num_to_move = int(dev)       # trunc toward zero
            if num_to_move < 1:
                break

            pgs = pgs_by_osd[osd]        # ascending (pool, seed)

            # drop an existing remap that lands on this overfull osd
            for pg in pgs:
                items = m.pg_upmap_items.get(pg)
                if items is not None:
                    for _frm, to in items:
                        if to == osd:
                            del m.pg_upmap_items[pg]
                            pending_inc.old_pg_upmap_items.add(pg)
                            num_changed += 1
                            restart = True
                            break   # entry gone; scanning on would
                            #         re-delete (the reference erases
                            #         mid-iteration, which is UB there)
                if restart:
                    break
            if restart:
                break

            for pg in pgs:
                if pg in m.pg_upmap or pg in m.pg_upmap_items:
                    continue
                r = try_pg_upmap(m, pg, overfull, underfull,
                                 raw_cache[pg.pool][pg.ps])
                if r is None:
                    continue
                orig, out = r
                if len(orig) != len(out):
                    continue
                assert orig != out
                rmi = [(orig[i], out[i]) for i in range(len(out))
                       if orig[i] != out[i]]
                m.pg_upmap_items[pg] = rmi
                pending_inc.new_pg_upmap_items[pg] = list(rmi)
                restart = True
                num_changed += 1
                break
            if restart:
                break

        if not restart:
            break
        max -= 1
        if max == 0:
            break
    return num_changed
