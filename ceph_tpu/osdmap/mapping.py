"""Whole-map batch PG mapping — the OSDMapMapping / ParallelPGMapper twin.

The reference computes pg→up/acting for every PG of every pool by sharding
the python-identical per-PG pipeline over a thread pool
(src/osd/OSDMapMapping.h:17-165).  Here the crush evaluation for a whole
pool runs as one device call (ops/crush_fast.py candidate-table kernel,
falling back to the native C++ evaluator and then the host interpreter),
and the post-passes — nonexistent/down filtering, primary pick, primary
affinity (OSDMap.cc:1966-2117) — are vectorized numpy over (PGs, size)
arrays.  Sparse per-PG overrides (pg_upmap, pg_upmap_items, pg_temp,
primary_temp) re-run the exact scalar pipeline for just those PGs, so the
batch result is identical to pg_to_up_acting_osds on every input.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..crush.constants import CRUSH_ITEM_NONE
from ..crush.hash import crush_hash32_2_np
from .osdmap import (
    CEPH_OSD_DEFAULT_PRIMARY_AFFINITY, CEPH_OSD_EXISTS, CEPH_OSD_UP, OSDMap,
)
from .types import FLAG_HASHPSPOOL, pg_pool_t, pg_t

NONE = CRUSH_ITEM_NONE


def pool_pps(pool: pg_pool_t, pool_id: int, ps: np.ndarray) -> np.ndarray:
    """Vectorized raw_pg_to_pps (osd_types.cc:1412-1427)."""
    ps = ps.astype(np.uint32)
    mask = np.uint32(pool.pgp_num_mask)
    low = ps & mask
    stable = np.where(low < pool.pgp_num, low, ps & (mask >> np.uint32(1)))
    if pool.flags & FLAG_HASHPSPOOL:
        return crush_hash32_2_np(stable, np.uint32(pool_id))
    return stable + np.uint32(pool_id)


class PoolMapping:
    """Dense per-pool result arrays, one row per PG."""

    def __init__(self, up: np.ndarray, up_primary: np.ndarray,
                 acting: np.ndarray, acting_primary: np.ndarray,
                 shift: bool):
        self.up = up
        self.up_primary = up_primary
        self.acting = acting
        self.acting_primary = acting_primary
        self.shift = shift  # replicated pools compact; EC keeps NONE holes
        X, size = up.shape
        if shift:
            self.up_len = (up != NONE).sum(axis=1).astype(np.int32)
            self.acting_len = self.up_len.copy()
        else:
            self.up_len = np.full(X, size, dtype=np.int32)
            self.acting_len = self.up_len.copy()


class OSDMapMapping:
    """Caches up/acting for every PG in the map (OSDMapMapping.h analog).

    ``update()`` recomputes all pools; ``get()`` answers from the cache.
    """

    def __init__(self, use_device: bool = True, use_native: bool = True):
        self.use_device = use_device
        self.use_native = use_native
        self.pools: Dict[int, PoolMapping] = {}
        self.epoch = -1
        self.last_backend: Dict[int, str] = {}
        # compiled-rule cache: jit cost is paid once per crush-map change,
        # not per epoch (up/out flips are runtime args to the kernel)
        self._rule_cache: Dict[Tuple[int, int, int], Tuple[bytes, object]] = {}

    @staticmethod
    def _crush_fingerprint(osdmap: OSDMap) -> bytes:
        import hashlib
        h = hashlib.sha1()
        m = osdmap.crush.crush
        for b in m.buckets:
            if b is None:
                h.update(b"-")
                continue
            h.update(np.asarray([b.id, b.alg, b.type], np.int64).tobytes())
            h.update(np.asarray(b.items, np.int64).tobytes())
            h.update(np.asarray(getattr(b, "item_weights", []),
                                np.int64).tobytes())
        for r in m.rules:
            if r is not None:
                for s in r.steps:
                    h.update(np.asarray([s.op, s.arg1, s.arg2],
                                        np.int64).tobytes())
        h.update(np.asarray([m.choose_total_tries, m.chooseleaf_vary_r,
                             m.chooseleaf_stable, m.chooseleaf_descend_once],
                            np.int64).tobytes())
        for key in sorted(m.choose_args):
            h.update(str(key).encode())
            for arg in m.choose_args[key]:
                if arg is None:
                    h.update(b"-")
                    continue
                h.update(np.asarray(arg.ids or [], np.int64).tobytes())
                for ws in arg.weight_set or []:
                    h.update(np.asarray(ws.weights, np.int64).tobytes())
        return h.digest()

    # ---- raw crush batch --------------------------------------------------
    def _raw_batch(self, osdmap: OSDMap, pool_id: int, pool: pg_pool_t,
                   pps: np.ndarray,
                   crush_fp: Optional[bytes] = None) -> np.ndarray:
        size = pool.size
        ruleno = osdmap.crush.find_rule(pool.crush_rule, pool.type, size)
        X = pps.shape[0]
        if ruleno < 0:
            return np.full((X, size), NONE, dtype=np.int32)
        weight = osdmap.osd_weight
        choose_args = osdmap.crush.crush.choose_args.get(pool_id)
        if self.use_device:
            try:
                from ..ops.crush_fast import compile_fast_rule
                key = (pool_id, ruleno, size)
                fp = crush_fp if crush_fp is not None \
                    else self._crush_fingerprint(osdmap)
                cached = self._rule_cache.get(key)
                if cached is not None and cached[0] == fp:
                    fr = cached[1]
                else:
                    fr = compile_fast_rule(osdmap.crush.crush, ruleno, size,
                                           choose_args)
                    self._rule_cache[key] = (fp, fr)
                res, cnt = fr.map_batch(pps, weight)
                self.last_backend[pool_id] = "device"
                return self._trim(res, cnt, pool, size)
            except (ValueError, ImportError):
                pass
        if self.use_native:
            try:
                from ..native import NativeCrushMapper, native_available
                if native_available():
                    nm = NativeCrushMapper(osdmap.crush.crush, choose_args)
                    res, cnt = nm.do_rule_batch(ruleno, pps.tolist(), size,
                                                weight)
                    self.last_backend[pool_id] = "native"
                    return self._trim(np.asarray(res, dtype=np.int32),
                                      np.asarray(cnt), pool, size)
            except Exception:
                pass
        from ..crush.mapper import crush_do_rule
        out = np.full((X, size), NONE, dtype=np.int32)
        for i, x in enumerate(pps):
            res = crush_do_rule(osdmap.crush.crush, ruleno, int(x), size,
                                weight, choose_args)
            out[i, :len(res)] = res
        self.last_backend[pool_id] = "host"
        return out

    @staticmethod
    def _trim(res: np.ndarray, cnt: np.ndarray, pool: pg_pool_t,
              size: int) -> np.ndarray:
        out = res[:, :size].copy()
        # mask slots beyond the per-row count
        cols = np.arange(size)[None, :]
        out[cols >= np.asarray(cnt)[:, None]] = NONE
        return out

    # ---- vectorized post-passes ------------------------------------------
    def _postprocess(self, osdmap: OSDMap, pool_id: int, pool: pg_pool_t,
                     raw: np.ndarray, pps: np.ndarray) -> PoolMapping:
        X, size = raw.shape
        state = np.asarray(osdmap.osd_state, dtype=np.int32)
        exists = (state & CEPH_OSD_EXISTS) != 0
        up_osd = (state & CEPH_OSD_UP) != 0

        def osd_flag(arr, flags):
            ok = (arr >= 0) & (arr < osdmap.max_osd)
            out = np.zeros(arr.shape, dtype=bool)
            out[ok] = flags[arr[ok]]
            return out

        valid = raw != NONE
        keep = valid & osd_flag(raw, exists)
        if pool.can_shift_osds():
            raw_f = _compact_rows(np.where(keep, raw, NONE))
        else:
            raw_f = np.where(keep, raw, NONE)
        # up filter
        upkeep = (raw_f != NONE) & osd_flag(raw_f, exists & up_osd)
        if pool.can_shift_osds():
            up = _compact_rows(np.where(upkeep, raw_f, NONE))
        else:
            up = np.where(upkeep, raw_f, NONE)
        up_primary = _first_valid(up)
        up, up_primary = self._affinity(osdmap, pool, up, up_primary, pps)
        pm = PoolMapping(up, up_primary, up.copy(), up_primary.copy(),
                         pool.can_shift_osds())

        # sparse exact overrides
        special = set()
        for d in (osdmap.pg_upmap, osdmap.pg_upmap_items, osdmap.pg_temp,
                  osdmap.primary_temp):
            for pg in d:
                if pg.pool == pool_id and pg.ps < X:
                    special.add(pg.ps)
        for ps in special:
            u, upri, act, apri = osdmap.pg_to_up_acting_osds(
                pg_t(pool_id, ps))
            pm.up[ps, :] = NONE
            pm.up[ps, :len(u)] = u
            pm.up_len[ps] = len(u)
            pm.up_primary[ps] = upri
            pm.acting[ps, :] = NONE
            pm.acting[ps, :len(act)] = act
            pm.acting_len[ps] = len(act)
            pm.acting_primary[ps] = apri
        return pm

    def _affinity(self, osdmap: OSDMap, pool: pg_pool_t, osds: np.ndarray,
                  primary: np.ndarray, pps: np.ndarray):
        """Vectorized _apply_primary_affinity (OSDMap.cc:2037-2090)."""
        aff_list = osdmap.osd_primary_affinity
        if aff_list is None:
            return osds, primary
        aff = np.asarray(aff_list, dtype=np.uint32)
        X, size = osds.shape
        valid = osds != NONE
        a = np.full(osds.shape, CEPH_OSD_DEFAULT_PRIMARY_AFFINITY,
                    dtype=np.uint32)
        ok = valid & (osds >= 0) & (osds < osdmap.max_osd)
        a[ok] = aff[osds[ok]]
        rows = np.any(ok & (a != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY), axis=1)
        if not rows.any():
            return osds, primary
        h = crush_hash32_2_np(pps[:, None].astype(np.uint32),
                              osds.astype(np.uint32)) >> np.uint32(16)
        rejected = valid & (a < CEPH_OSD_DEFAULT_PRIMARY_AFFINITY) & (h >= a)
        accepted = valid & ~rejected
        first_acc = _first_index(accepted)
        first_val = _first_index(valid)
        pos = np.where(first_acc >= 0, first_acc, first_val)
        use = rows & (pos >= 0)
        new_primary = primary.copy()
        new_primary[use] = osds[np.nonzero(use)[0], pos[use]]
        if pool.can_shift_osds():
            out = osds.copy()
            for i in np.nonzero(use & (pos > 0))[0]:
                p = pos[i]
                out[i, 1:p + 1] = osds[i, 0:p]
                out[i, 0] = osds[i, p]
            osds = out
        return osds, new_primary

    # ---- public -----------------------------------------------------------
    def update(self, osdmap: OSDMap) -> None:
        """Recompute all pools; latency lands in the per-epoch batched
        mapping histogram (the whole-map remap is the device-batched
        hot path the balancer and every epoch apply lean on)."""
        import time
        from ..trace import g_perf_histograms, g_tracer, latency_axes
        t0 = time.perf_counter()
        with g_tracer.span("crush_map_update"):
            self.pools.clear()
            crush_fp = self._crush_fingerprint(osdmap) if self.use_device \
                else None
            for pool_id, pool in osdmap.pools.items():
                ps = np.arange(pool.pg_num, dtype=np.uint32)
                pps = pool_pps(pool, pool_id, ps)
                raw = self._raw_batch(osdmap, pool_id, pool, pps, crush_fp)
                self.pools[pool_id] = self._postprocess(
                    osdmap, pool_id, pool, raw, pps)
            self.epoch = osdmap.epoch
        g_perf_histograms.get(
            "osdmap", "crush_map_latency_histogram", latency_axes).inc(
            (time.perf_counter() - t0) * 1e6)

    def get(self, pg: pg_t) -> Tuple[List[int], int, List[int], int]:
        pm = self.pools[pg.pool]
        up = [int(o) for o in pm.up[pg.ps, :pm.up_len[pg.ps]]]
        acting = [int(o) for o in pm.acting[pg.ps, :pm.acting_len[pg.ps]]]
        return (up, int(pm.up_primary[pg.ps]),
                acting, int(pm.acting_primary[pg.ps]))

    def get_acting_row(self, pg: pg_t) -> List[int]:
        """Positional acting set (EC pools keep NONE holes)."""
        pm = self.pools[pg.pool]
        return [int(o) for o in pm.acting[pg.ps]]


def _compact_rows(arr: np.ndarray) -> np.ndarray:
    """Shift non-NONE entries left, preserving order (replicated pools)."""
    X, size = arr.shape
    out = np.full_like(arr, NONE)
    valid = arr != NONE
    pos = np.cumsum(valid, axis=1) - 1
    rows = np.broadcast_to(np.arange(X)[:, None], arr.shape)
    out[rows[valid], pos[valid]] = arr[valid]
    return out


def _first_valid(arr: np.ndarray) -> np.ndarray:
    """Primary pick: first non-NONE per row, else -1 (OSDMap.cc:1956)."""
    idx = _first_index(arr != NONE)
    out = np.full(arr.shape[0], -1, dtype=np.int32)
    ok = idx >= 0
    out[ok] = arr[np.nonzero(ok)[0], idx[ok]]
    return out


def _first_index(mask: np.ndarray) -> np.ndarray:
    """First True per row, -1 when none."""
    any_ = mask.any(axis=1)
    idx = mask.argmax(axis=1).astype(np.int64)
    idx[~any_] = -1
    return idx
