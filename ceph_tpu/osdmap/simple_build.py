"""Simple-map builders — OSDMap::build_simple_with_pool equivalents.

Reproduces the reference's bootstrap-map construction byte-for-byte so
the osdmaptool golden tests replay its recorded outputs:

- ``build_from_conf``: parse a ceph.conf, create one osd per [osd.N]
  section at its host/rack location (OSDMap::build_simple_crush_map_
  from_conf, src/osd/OSDMap.cc:3587).  Sections iterate in
  LEXICOGRAPHIC order (the reference's ConfFile stores sections in a
  std::map<string,...>), which fixes the bucket-id allocation order —
  and bucket ids feed the straw2 hashes, so this is mapping-critical.
- ``insert_item``: CrushWrapper::insert_item's exact creation order —
  walk types ASCENDING from the device up, creating each missing
  ancestor as a straw2 bucket CONTAINING the current cursor (so a
  host gets a lower bucket id than its rack), stopping at the first
  existing ancestor; then propagate the device weight up the chain.
- the default pool: 'rbd', replicated size 3, pg_num = max_osd <<
  pg_bits, hashpspool, crush rule 0 = [take default, chooseleaf_firstn
  0 host, emit] (add_simple_rule_at), jewel tunables.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from ..crush import CrushWrapper
from ..crush.constants import CRUSH_BUCKET_STRAW2
from .osdmap import OSDMap
from .types import FLAG_HASHPSPOOL, TYPE_REPLICATED, pg_pool_t

# OSDMap::_build_crush_types
CRUSH_TYPES = [(0, "osd"), (1, "host"), (2, "chassis"), (3, "rack"),
               (4, "row"), (5, "pdu"), (6, "pod"), (7, "room"),
               (8, "datacenter"), (9, "region"), (10, "root")]


def parse_conf_sections(text: str) -> Dict[str, Dict[str, str]]:
    """Minimal ceph.conf parser: section -> {key: value} with the
    reference's key normalization (spaces == underscores).  Returned
    dict preserves insertion order, but callers must iterate sections
    LEXICOGRAPHICALLY to match ConfFile's std::map."""
    sections: Dict[str, Dict[str, str]] = {}
    cur: Optional[Dict[str, str]] = None
    for line in text.splitlines():
        line = line.split("#", 1)[0].split(";", 1)[0].strip()
        if not line:
            continue
        msec = re.match(r"\[(.+)\]$", line)
        if msec:
            cur = sections.setdefault(msec.group(1).strip(), {})
            continue
        if cur is None or "=" not in line:
            continue
        k, _, v = line.partition("=")
        cur[k.strip().replace(" ", "_")] = v.strip()
    return sections


def _subtree_contains(cw: CrushWrapper, root: int, item: int) -> bool:
    if root == item:
        return True
    if root >= 0:
        return False
    b = cw.crush.bucket(root)
    return b is not None and any(_subtree_contains(cw, it, item)
                                 for it in b.items)


def insert_item(cw: CrushWrapper, item: int, weight: int, name: str,
                loc: Dict[str, str]) -> None:
    """CrushWrapper::insert_item at 16.16 fixed weight.  Walks the
    map's OWN type table ascending (the reference iterates type_map);
    missing ancestors are created with the map's default bucket alg
    (straw2 under jewel+, straw on legacy maps)."""
    alg = cw.get_default_bucket_alg()
    if not cw.name_exists(name):
        cw.set_item_name(item, name)
    cur = item
    device_parent = None
    for t in sorted(cw.type_map):
        tname = cw.type_map[t]
        if t == 0:
            continue
        bname = loc.get(tname)
        if bname is None:
            continue
        if not cw.name_exists(bname):
            # create the ancestor CONTAINING the cursor, weight 0
            newid = cw.add_bucket(alg, t, bname,
                                  [cur], [0])
            if cur == item:
                device_parent = newid
            cur = newid
            continue
        bid = cw.get_item_id(bname)
        b = cw.crush.bucket(bid)
        if b is None or b.type != t:
            raise ValueError(f"bucket {bname!r} type mismatch")
        if _subtree_contains(cw, bid, cur):
            # CrushWrapper.cc:901-905: re-inserting an item already
            # beneath the target location is -EINVAL, not a dup link
            raise ValueError(
                f"insert_item item {cur} already exists beneath {bid}")
        cw._bucket_link(bid, cur, 0)
        if cur == item:
            device_parent = bid
        break
    else:
        raise ValueError(f"nowhere to add item {item} in {loc}")
    # adjust_item_weightf_in_loc: set the device's weight in THE
    # LOCATION JUST PLACED (a device may live in several locations —
    # the first parent found is not necessarily this one), rebuilding
    # derived arrays and rippling the actual delta upward
    delta = cw._set_item_weight_in(device_parent, item, weight)
    cw._propagate_above(device_parent, delta)
    if item >= cw.crush.max_devices:
        cw.crush.max_devices = item + 1


def _add_default_pool(m: OSDMap, pg_bits: int, pgp_bits: int,
                      rule: int) -> None:
    if pgp_bits > pg_bits:
        pgp_bits = pg_bits
    poolbase = m.max_osd if m.max_osd else 1
    pool = pg_pool_t(type=TYPE_REPLICATED, size=3, min_size=2,
                     crush_rule=rule, pg_num=poolbase << pg_bits,
                     pgp_num=poolbase << pgp_bits,
                     flags=FLAG_HASHPSPOOL, application="rbd")
    m.add_pool("rbd", pool, pool_id=1)


def _finish_crush(cw: CrushWrapper) -> int:
    """build_simple_crush_rules: replicated_rule at id 0, chooseleaf
    over osd_crush_chooseleaf_type (host)."""
    rno = cw.add_simple_rule("replicated_rule", root_name="default",
                             failure_domain_name="host", mode="firstn",
                             ruleno=0)
    return rno


def build_from_conf(conf_text: str, with_default_pool: bool = True,
                    pg_bits: int = 6, pgp_bits: int = 6) -> OSDMap:
    """OSDMap::build_simple_with_pool(nosd=-1) + build_simple_crush_
    map_from_conf.  OSDs are NOT marked up/in (osdmaptool does that
    with --mark-up-in)."""
    sections = parse_conf_sections(conf_text)
    osd_ids: List[Tuple[str, int]] = []
    for sec in sections:
        msec = re.match(r"osd\.(\d+)$", sec)
        if msec:
            osd_ids.append((sec, int(msec.group(1))))

    m = OSDMap()
    maxosd = max((o for _, o in osd_ids), default=-1)
    m.set_max_osd(maxosd + 1)

    cw = m.crush
    for t, name in CRUSH_TYPES:
        cw.set_type_name(t, name)
    cw.set_tunables_profile("jewel")
    root = cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", [], [])
    assert root == -1

    # ConfFile sections iterate lexicographically (std::map<string,..>)
    for sec in sorted(s for s, _ in osd_ids):
        o = int(sec.split(".", 1)[1])
        kv = sections[sec]
        host = kv.get("host", "") or "unknownhost"
        rack = kv.get("rack", "") or "unknownrack"
        loc = {"host": host, "rack": rack, "root": "default"}
        for extra in ("row", "room", "datacenter"):
            if kv.get(extra):
                loc[extra] = kv[extra]
        insert_item(cw, o, 0x10000, sec, loc)

    rule = _finish_crush(cw)
    if with_default_pool:
        _add_default_pool(m, pg_bits, pgp_bits, rule)
    m.epoch = 1
    import time as _time
    m.created = m.modified = _time.time()
    return m


def build_simple(n_osds: int, with_default_pool: bool = True,
                 pg_bits: int = 6, pgp_bits: int = 6) -> OSDMap:
    """OSDMap::build_simple_with_pool(nosd=N): every osd at the fixed
    localhost/localrack location under the default root
    (build_simple_crush_map, OSDMap.cc:3556-3580 — localhost id -2,
    localrack -3, pinned by create-print.t's recorded decompile)."""
    import time as _time
    m = OSDMap()
    m.set_max_osd(n_osds)
    m.created = m.modified = _time.time()
    cw = m.crush
    for t, name in CRUSH_TYPES:
        cw.set_type_name(t, name)
    cw.set_tunables_profile("jewel")
    root = cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", [], [])
    assert root == -1
    for o in range(n_osds):
        insert_item(cw, o, 0x10000, f"osd.{o}",
                    {"host": "localhost", "rack": "localrack",
                     "root": "default"})
    rule = _finish_crush(cw)
    if with_default_pool:
        _add_default_pool(m, pg_bits, pgp_bits, rule)
    m.epoch = 1
    return m


def mark_up_in(m: OSDMap) -> None:
    """osdmaptool --mark-up-in."""
    for i in range(m.max_osd):
        m.set_osd(i, up=True)
        m.osd_weight[i] = 0x10000


def mark_out(m: OSDMap, osd: int) -> None:
    """osdmaptool --mark-out N: up but OUT (weight 0); crush weight
    stays, so placement rejects it via the is_out draw."""
    if 0 <= osd < m.max_osd:
        m.set_osd(osd, up=True)
        m.osd_weight[osd] = 0
