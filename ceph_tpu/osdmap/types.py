"""Cluster-map value types: pg_t and pg_pool_t.

Semantics mirror the reference types (src/osd/osd_types.{h,cc}): a PG is
(pool, ps); a pool carries the placement parameters — pg_num/pgp_num with
their power-of-two masks for ceph_stable_mod splitting (osd_types.cc:1250),
the crush rule, replica/EC sizing, and the hashpspool seed-mixing flag
(osd_types.cc:1412-1427).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from ..crush.hash import crush_hash32_2
from ..crush.constants import (
    CRUSH_HASH_RJENKINS1, PG_POOL_TYPE_ERASURE, PG_POOL_TYPE_REPLICATED,
)
from ..utils.str_hash import CEPH_STR_HASH_RJENKINS, ceph_str_hash

TYPE_REPLICATED = PG_POOL_TYPE_REPLICATED
TYPE_ERASURE = PG_POOL_TYPE_ERASURE

FLAG_HASHPSPOOL = 1 << 0
FLAG_FULL = 1 << 1            # pool is full (osd_types.h:1148)
FLAG_FULL_QUOTA = 1 << 10     # full because quota exceeded (:1157)
FLAG_NEARFULL = 1 << 11       # pool is nearfull (:1158)
FLAG_EC_OVERWRITES = 1 << 17


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Stable modulo under pg_num growth (include/rados.h:84-90)."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


@dataclass(frozen=True, order=True)
class pg_t:
    pool: int
    ps: int

    def __str__(self) -> str:
        return f"{self.pool}.{self.ps:x}"


@dataclass
class pg_pool_t:
    type: int = TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    crush_rule: int = 0
    object_hash: int = CEPH_STR_HASH_RJENKINS
    pg_num: int = 8
    pgp_num: int = 8
    flags: int = FLAG_HASHPSPOOL
    last_change: int = 0
    erasure_code_profile: str = ""
    stripe_width: int = 0
    # enabled application (pg_pool_t application_metadata keys; the
    # default pool carries "rbd")
    application: str = ""
    # pool snapshots (pg_pool_t snaps/snap_seq, osd_types.h): snap id ->
    # name; removed ids accumulate so PGs can trim clones
    snap_seq: int = 0
    snaps: Dict[int, str] = field(default_factory=dict)
    removed_snaps: List[int] = field(default_factory=list)
    # self-managed (unmanaged) snap mode: ids are allocated by the mon but
    # snapshots exist only in client-supplied SnapContexts (librbd-style;
    # pg_pool_t::is_unmanaged_snaps_mode, osd_types.h).  A pool commits to
    # one mode on first use; mixing is refused like the reference does.
    selfmanaged: bool = False
    # pool quotas (pg_pool_t quota_max_*, "osd pool set-quota"): 0 =
    # unlimited; the mgr sets FLAG_FULL_QUOTA|FLAG_FULL when exceeded
    quota_max_objects: int = 0
    quota_max_bytes: int = 0

    def live_snaps(self) -> set:
        """Snap ids that may still be referenced — the trim liveness
        set.  Pool mode: the named snaps.  Selfmanaged mode: every
        allocated-and-not-removed id (any client's snapc may cite it,
        so only removal makes a clone garbage); cached because clone
        writes consult this per mutation and snap_seq only grows."""
        if not self.selfmanaged:
            return set(self.snaps)
        key = (self.snap_seq, len(self.removed_snaps))
        cached = getattr(self, "_live_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        live = set(range(1, self.snap_seq + 1)) - set(self.removed_snaps)
        object.__setattr__(self, "_live_cache", (key, live))
        return live
    # cache tiering (pg_pool_t tier fields, osd_types.h): a BASE pool
    # gains read_tier/write_tier redirects; the CACHE pool records
    # tier_of + agent/hit-set knobs (HitSet.h; OSDMonitor "osd tier")
    tier_of: int = -1            # base pool id (set on the cache pool)
    read_tier: int = -1          # cache pool id (set on the base pool)
    write_tier: int = -1
    cache_mode: str = ""         # "writeback" (the implemented mode)
    hit_set_period: float = 60.0
    hit_set_count: int = 4
    target_max_objects: int = 0  # 0 = no eviction pressure
    pg_num_mask: int = field(default=0, repr=False)
    pgp_num_mask: int = field(default=0, repr=False)

    def __post_init__(self):
        self.calc_pg_masks()

    def calc_pg_masks(self) -> None:
        self.pg_num_mask = (1 << (self.pg_num - 1).bit_length()) - 1
        self.pgp_num_mask = (1 << (self.pgp_num - 1).bit_length()) - 1

    def set_pg_num(self, n: int) -> None:
        self.pg_num = n
        self.calc_pg_masks()

    def set_pgp_num(self, n: int) -> None:
        self.pgp_num = n
        self.calc_pg_masks()

    def is_replicated(self) -> bool:
        return self.type == TYPE_REPLICATED

    def is_erasure(self) -> bool:
        return self.type == TYPE_ERASURE

    def can_shift_osds(self) -> bool:
        """Replicated pools compact holes; EC pools keep positional NONEs
        (osd_types.h:1506-1515)."""
        return self.type == TYPE_REPLICATED

    def has_flag(self, f: int) -> bool:
        return bool(self.flags & f)

    def allows_ecoverwrites(self) -> bool:
        return self.has_flag(FLAG_EC_OVERWRITES)

    # ---- placement math ---------------------------------------------------
    def hash_key(self, key: str, ns: str = "") -> int:
        if not ns:
            return ceph_str_hash(self.object_hash, key)
        buf = ns.encode() + b"\x1f" + key.encode()
        return ceph_str_hash(self.object_hash, buf)

    def raw_pg_to_pg(self, pg: pg_t) -> pg_t:
        return pg_t(pg.pool, ceph_stable_mod(pg.ps, self.pg_num,
                                             self.pg_num_mask))

    def raw_pg_to_pps(self, pg: pg_t) -> int:
        """Placement seed: pool-salted when hashpspool (osd_types.cc:1412)."""
        if self.flags & FLAG_HASHPSPOOL:
            return crush_hash32_2(
                ceph_stable_mod(pg.ps, self.pgp_num, self.pgp_num_mask),
                pg.pool)
        return ceph_stable_mod(pg.ps, self.pgp_num, self.pgp_num_mask) \
            + pg.pool
