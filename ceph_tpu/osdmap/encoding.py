"""Structured encode/decode for the cluster-map types.

The reference gives every map type a versioned encode/decode pair
(include/encoding.h; OSDMap::encode, CrushWrapper::encode) so maps can be
persisted in the mon store and shipped on the wire.  Here the same role is
filled by explicit dict codecs (JSON-serializable, debuggable like
`osdmaptool --dump json`) for CrushWrapper, pg_pool_t, OSDMap and
Incremental — used by the durability layer (mon store files, OSD
superblocks) and the cross-process messenger's wire format.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..crush.constants import (
    CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM, TUNABLE_PROFILES,
)
from ..crush.types import (
    Bucket, ChooseArg, CrushMap, ListBucket, Rule, RuleStep, StrawBucket,
    Straw2Bucket, TreeBucket, UniformBucket, WeightSet,
)
from ..crush.wrapper import CrushWrapper
from .types import pg_pool_t, pg_t

_TUNABLE_KEYS = sorted(TUNABLE_PROFILES["default"])

_BUCKET_CLS = {
    CRUSH_BUCKET_UNIFORM: UniformBucket,
    CRUSH_BUCKET_LIST: ListBucket,
    CRUSH_BUCKET_TREE: TreeBucket,
    CRUSH_BUCKET_STRAW: StrawBucket,
    CRUSH_BUCKET_STRAW2: Straw2Bucket,
}


# ---- crush -----------------------------------------------------------------

def bucket_to_dict(b: Optional[Bucket]) -> Optional[Dict[str, Any]]:
    if b is None:
        return None
    d: Dict[str, Any] = {"id": b.id, "type": b.type, "alg": b.alg,
                         "items": list(b.items), "weight": b.weight,
                         "hash": b.hash}
    if isinstance(b, UniformBucket):
        d["item_weight"] = b.item_weight
    elif isinstance(b, ListBucket):
        d["item_weights"] = list(b.item_weights)
        d["sum_weights"] = list(b.sum_weights)
    elif isinstance(b, TreeBucket):
        d["num_nodes"] = b.num_nodes
        d["node_weights"] = list(b.node_weights)
    elif isinstance(b, StrawBucket):
        d["item_weights"] = list(b.item_weights)
        d["straws"] = list(b.straws)
    elif isinstance(b, Straw2Bucket):
        d["item_weights"] = list(b.item_weights)
    return d


def bucket_from_dict(d: Optional[Dict[str, Any]]) -> Optional[Bucket]:
    if d is None:
        return None
    cls = _BUCKET_CLS[d["alg"]]
    b = cls(id=d["id"], type=d["type"], alg=d["alg"],
            items=list(d["items"]), weight=d["weight"], hash=d["hash"])
    for k in ("item_weight", "num_nodes"):
        if k in d:
            setattr(b, k, d[k])
    for k in ("item_weights", "sum_weights", "node_weights", "straws"):
        if k in d:
            setattr(b, k, list(d[k]))
    return b


def crushmap_to_dict(m: CrushMap) -> Dict[str, Any]:
    return {
        "buckets": [bucket_to_dict(b) for b in m.buckets],
        "rules": [None if r is None else {
            "ruleset": r.ruleset, "type": r.type, "min_size": r.min_size,
            "max_size": r.max_size,
            "steps": [[s.op, s.arg1, s.arg2] for s in r.steps],
        } for r in m.rules],
        "max_devices": m.max_devices,
        "tunables": {k: getattr(m, k) for k in _TUNABLE_KEYS},
        "straw_calc_version": m.straw_calc_version,
        "choose_args": {
            str(key): [None if a is None else {
                "ids": list(a.ids) if a.ids else None,
                "weight_set": None if a.weight_set is None else
                [list(ws.weights) for ws in a.weight_set],
            } for a in args]
            for key, args in m.choose_args.items()},
    }


def crushmap_from_dict(d: Dict[str, Any]) -> CrushMap:
    m = CrushMap()
    m.buckets = [bucket_from_dict(b) for b in d["buckets"]]
    m.rules = [None if r is None else Rule(
        steps=[RuleStep(*s) for s in r["steps"]], ruleset=r["ruleset"],
        type=r["type"], min_size=r["min_size"], max_size=r["max_size"])
        for r in d["rules"]]
    m.max_devices = d["max_devices"]
    for k, v in d["tunables"].items():
        setattr(m, k, v)
    m.straw_calc_version = d["straw_calc_version"]
    m.choose_args = {
        int(key): [None if a is None else ChooseArg(
            ids=list(a["ids"]) if a["ids"] else None,
            weight_set=None if a["weight_set"] is None else
            [WeightSet(weights=list(w)) for w in a["weight_set"]])
            for a in args]
        for key, args in d["choose_args"].items()}
    return m


def crush_to_dict(cw: CrushWrapper) -> Dict[str, Any]:
    return {
        "map": crushmap_to_dict(cw.crush),
        "type_map": {str(k): v for k, v in cw.type_map.items()},
        "name_map": {str(k): v for k, v in cw.name_map.items()},
        "rule_name_map": {str(k): v for k, v in cw.rule_name_map.items()},
        "class_map": {str(k): v for k, v in cw.class_map.items()},
        "item_class": {str(k): v for k, v in cw.item_class.items()},
        "class_bucket": {str(r): {str(c): b for c, b in cb.items()}
                         for r, cb in cw.class_bucket.items()},
    }


def crush_from_dict(d: Dict[str, Any]) -> CrushWrapper:
    cw = CrushWrapper()
    cw.crush = crushmap_from_dict(d["map"])
    cw.type_map = {int(k): v for k, v in d["type_map"].items()}
    cw.name_map = {int(k): v for k, v in d["name_map"].items()}
    cw.rule_name_map = {int(k): v for k, v in d["rule_name_map"].items()}
    cw.class_map = {int(k): v for k, v in d["class_map"].items()}
    cw.item_class = {int(k): v for k, v in d["item_class"].items()}
    cw.class_bucket = {int(r): {int(c): b for c, b in cb.items()}
                       for r, cb in d["class_bucket"].items()}
    return cw


# ---- pools / osdmap --------------------------------------------------------

_POOL_FIELDS = ("type", "size", "min_size", "crush_rule", "object_hash",
                "pg_num", "pgp_num", "flags", "last_change",
                "erasure_code_profile", "stripe_width")
# tiering fields ride with defaults so old checkpoints keep decoding
_POOL_TIER_FIELDS = ("tier_of", "read_tier", "write_tier", "cache_mode",
                     "hit_set_period", "hit_set_count",
                     "target_max_objects")


def pool_to_dict(p: pg_pool_t) -> Dict[str, Any]:
    d = {k: getattr(p, k) for k in _POOL_FIELDS}
    for k in _POOL_TIER_FIELDS:
        d[k] = getattr(p, k)
    d["snap_seq"] = p.snap_seq
    d["snaps"] = {str(k): v for k, v in p.snaps.items()}
    d["removed_snaps"] = list(p.removed_snaps)
    if p.selfmanaged:
        d["selfmanaged"] = True
    if p.quota_max_objects or p.quota_max_bytes:
        d["quota_max_objects"] = p.quota_max_objects
        d["quota_max_bytes"] = p.quota_max_bytes
    d["flags_versioned"] = True   # marks flags as post-ec_overwrites-gate
    return d


def pool_from_dict(d: Dict[str, Any]) -> pg_pool_t:
    p = pg_pool_t(**{k: d[k] for k in _POOL_FIELDS})
    for k in _POOL_TIER_FIELDS:
        if k in d:
            setattr(p, k, d[k])
    p.snap_seq = int(d.get("snap_seq", 0))
    p.snaps = {int(k): v for k, v in d.get("snaps", {}).items()}
    p.removed_snaps = [int(x) for x in d.get("removed_snaps", [])]
    p.selfmanaged = bool(d.get("selfmanaged", False))
    p.quota_max_objects = int(d.get("quota_max_objects", 0))
    p.quota_max_bytes = int(d.get("quota_max_bytes", 0))
    if p.is_erasure() and not d.get("flags_versioned"):
        # checkpoints written before the overwrites gate existed always
        # allowed rmw; restoring them must not break their workloads
        from .types import FLAG_EC_OVERWRITES
        p.flags |= FLAG_EC_OVERWRITES
    return p


def _pgid_key(pg: pg_t) -> str:
    return f"{pg.pool}.{pg.ps}"


def _pgid_from_key(s: str) -> pg_t:
    pool, ps = s.split(".")
    return pg_t(int(pool), int(ps))


def osdmap_to_dict(m) -> Dict[str, Any]:
    return {
        "epoch": m.epoch,
        "flags": m.flags,
        "max_osd": m.max_osd,
        "osd_state": list(m.osd_state),
        "osd_weight": list(m.osd_weight),
        "osd_primary_affinity": None if m.osd_primary_affinity is None
        else list(m.osd_primary_affinity),
        "pools": {str(k): pool_to_dict(p) for k, p in m.pools.items()},
        "pool_name": {str(k): v for k, v in m.pool_name.items()},
        "pool_max": m.pool_max,
        "pg_upmap": {_pgid_key(k): list(v) for k, v in m.pg_upmap.items()},
        "pg_upmap_items": {_pgid_key(k): [list(t) for t in v]
                           for k, v in m.pg_upmap_items.items()},
        "pg_temp": {_pgid_key(k): list(v) for k, v in m.pg_temp.items()},
        "primary_temp": {_pgid_key(k): v
                         for k, v in m.primary_temp.items()},
        "osd_old_weight": {str(k): v
                           for k, v in m.osd_old_weight.items()},
        "erasure_code_profiles": {k: dict(v) for k, v in
                                  m.erasure_code_profiles.items()},
        "crush": crush_to_dict(m.crush),
    }


def osdmap_from_dict(d: Dict[str, Any]):
    from .osdmap import OSDMap
    m = OSDMap()
    m.epoch = d["epoch"]
    m.flags = int(d.get("flags", 0))
    m.max_osd = d["max_osd"]
    m.osd_state = list(d["osd_state"])
    m.osd_weight = list(d["osd_weight"])
    m.osd_primary_affinity = None if d["osd_primary_affinity"] is None \
        else list(d["osd_primary_affinity"])
    m.pools = {int(k): pool_from_dict(p) for k, p in d["pools"].items()}
    m.pool_name = {int(k): v for k, v in d["pool_name"].items()}
    m.pool_max = d["pool_max"]
    m.pg_upmap = {_pgid_from_key(k): list(v)
                  for k, v in d["pg_upmap"].items()}
    m.pg_upmap_items = {_pgid_from_key(k): [tuple(t) for t in v]
                        for k, v in d["pg_upmap_items"].items()}
    m.pg_temp = {_pgid_from_key(k): list(v)
                 for k, v in d["pg_temp"].items()}
    m.primary_temp = {_pgid_from_key(k): v
                      for k, v in d["primary_temp"].items()}
    m.osd_old_weight = {int(k): v for k, v in
                        d.get("osd_old_weight", {}).items()}
    m.erasure_code_profiles = {k: dict(v) for k, v in
                               d["erasure_code_profiles"].items()}
    m.crush = crush_from_dict(d["crush"])
    return m


def incremental_to_dict(inc) -> Dict[str, Any]:
    return {
        "epoch": inc.epoch,
        "new_max_osd": inc.new_max_osd,
        "new_pools": {str(k): pool_to_dict(p)
                      for k, p in inc.new_pools.items()},
        "new_pool_names": {str(k): v
                           for k, v in inc.new_pool_names.items()},
        "old_pools": list(inc.old_pools),
        "new_up": {str(k): v for k, v in inc.new_up.items()},
        "new_weight": {str(k): v for k, v in inc.new_weight.items()},
        "new_old_weight": {str(k): v
                           for k, v in inc.new_old_weight.items()},
        "new_primary_affinity": {str(k): v for k, v in
                                 inc.new_primary_affinity.items()},
        "new_pg_upmap": {_pgid_key(k): list(v)
                         for k, v in inc.new_pg_upmap.items()},
        "old_pg_upmap": [_pgid_key(k) for k in inc.old_pg_upmap],
        "new_pg_upmap_items": {_pgid_key(k): [list(t) for t in v]
                               for k, v in inc.new_pg_upmap_items.items()},
        "old_pg_upmap_items": [_pgid_key(k)
                               for k in inc.old_pg_upmap_items],
        "new_pg_temp": {_pgid_key(k): list(v)
                        for k, v in inc.new_pg_temp.items()},
        "new_primary_temp": {_pgid_key(k): v
                             for k, v in inc.new_primary_temp.items()},
        "new_erasure_code_profiles": {
            k: dict(v) for k, v in inc.new_erasure_code_profiles.items()},
        "crush": None if inc.crush is None else crush_to_dict(inc.crush),
        "service_log": [list(e) for e in inc.service_log],
        "service_config_kv": dict(inc.service_config_kv),
    }


def incremental_from_dict(d: Dict[str, Any]):
    from .osdmap import Incremental
    inc = Incremental()
    inc.epoch = d["epoch"]
    inc.new_max_osd = d["new_max_osd"]
    inc.new_pools = {int(k): pool_from_dict(p)
                     for k, p in d["new_pools"].items()}
    inc.new_pool_names = {int(k): v
                          for k, v in d["new_pool_names"].items()}
    inc.old_pools = list(d["old_pools"])
    inc.new_up = {int(k): v for k, v in d["new_up"].items()}
    inc.new_weight = {int(k): v for k, v in d["new_weight"].items()}
    inc.new_old_weight = {int(k): v for k, v in
                          d.get("new_old_weight", {}).items()}
    inc.new_primary_affinity = {int(k): v for k, v in
                                d["new_primary_affinity"].items()}
    inc.new_pg_upmap = {_pgid_from_key(k): list(v)
                        for k, v in d["new_pg_upmap"].items()}
    inc.old_pg_upmap = [_pgid_from_key(k) for k in d["old_pg_upmap"]]
    inc.new_pg_upmap_items = {
        _pgid_from_key(k): [tuple(t) for t in v]
        for k, v in d["new_pg_upmap_items"].items()}
    inc.old_pg_upmap_items = [_pgid_from_key(k)
                              for k in d["old_pg_upmap_items"]]
    inc.new_pg_temp = {_pgid_from_key(k): list(v)
                       for k, v in d["new_pg_temp"].items()}
    inc.new_primary_temp = {_pgid_from_key(k): v
                            for k, v in d["new_primary_temp"].items()}
    inc.new_erasure_code_profiles = {
        k: dict(v) for k, v in d["new_erasure_code_profiles"].items()}
    inc.crush = None if d["crush"] is None \
        else crush_from_dict(d["crush"])
    inc.service_log = [tuple(e) for e in d.get("service_log", [])]
    inc.service_config_kv = dict(d.get("service_config_kv", {}))
    return inc
