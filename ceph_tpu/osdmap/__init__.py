from .types import (
    FLAG_EC_OVERWRITES, FLAG_HASHPSPOOL, TYPE_ERASURE, TYPE_REPLICATED,
    ceph_stable_mod, pg_pool_t, pg_t,
)
from .osdmap import (
    CEPH_OSD_DEFAULT_PRIMARY_AFFINITY, CEPH_OSD_IN, CEPH_OSD_OUT,
    Incremental, OSDMap,
)
from .mapping import OSDMapMapping, PoolMapping, pool_pps

__all__ = [
    "FLAG_EC_OVERWRITES", "FLAG_HASHPSPOOL", "TYPE_ERASURE",
    "TYPE_REPLICATED", "ceph_stable_mod", "pg_pool_t", "pg_t",
    "CEPH_OSD_DEFAULT_PRIMARY_AFFINITY", "CEPH_OSD_IN", "CEPH_OSD_OUT",
    "Incremental", "OSDMap", "OSDMapMapping", "PoolMapping", "pool_pps",
]
