"""calc_pg_upmaps — the upmap balancer.

Functional equivalent of OSDMap::calc_pg_upmaps (reference osd/OSDMap.h:1305,
used by `osdmaptool --upmap` and the mgr balancer module): iteratively move
PG replicas off the most-overfull OSD onto the most-underfull candidate via
pg_upmap_items entries, while respecting the rule's failure-domain
separation (the reference walks the rule via try_remap_rule,
CrushWrapper.h:1503; here candidates are constrained to unused failure
domains and every move is validated by actually re-running the mapping).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..crush.constants import (
    CRUSH_ITEM_NONE, CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
)
from ..crush.types import ChooseArg, WeightSet
from .osdmap import Incremental, OSDMap
from .types import pg_t


def _failure_domain_type(osdmap: OSDMap, ruleno: int) -> int:
    rule = osdmap.crush.crush.rules[ruleno]
    for step in rule.steps:
        if step.op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                       CRUSH_RULE_CHOOSELEAF_INDEP,
                       CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP):
            return step.arg2
    return 0


def _domain_of(osdmap: OSDMap, osd: int, dtype: int) -> int:
    """The ancestor bucket of type *dtype* containing *osd* (osd itself
    when the domain is the device)."""
    if dtype == 0:
        return osd
    cw = osdmap.crush
    item = osd
    seen = 0
    while seen < 16:
        seen += 1
        parent = None
        for b in cw.crush.buckets:
            if b is not None and item in b.items:
                parent = b
                break
        if parent is None:
            return item
        if parent.type == dtype:
            return parent.id
        item = parent.id
    return item


def calc_pg_upmaps(osdmap: OSDMap, max_deviation: float = 0.01,
                   max_iterations: int = 100,
                   pools: Optional[List[int]] = None,
                   inc: Optional[Incremental] = None) -> int:
    """Compute pg_upmap_items flattening the distribution.

    Delegates to the decision-identical port of the reference's
    OSDMap::calc_pg_upmaps (osdmap/upmap.py, pinned byte-for-byte to
    the recorded osdmaptool cram outputs by
    tests/test_osdmaptool_golden.py).  Results land in *inc* (and the
    map's pg_upmap_items for chained evaluation); returns the number
    of changes, like the reference.
    """
    from .upmap import PendingInc
    from .upmap import calc_pg_upmaps as _exact
    pi = PendingInc()
    n = _exact(osdmap, max_deviation, max_iterations,
               set(pools) if pools else None, pi)
    if inc is not None:
        inc.new_pg_upmap_items.update(pi.new_pg_upmap_items)
        inc.old_pg_upmap_items.extend(sorted(pi.old_pg_upmap_items))
    return n


# ---- crush-compat mode (per-position weight_set optimization) --------------

def _bucket_depths(cw) -> List[Tuple[int, object]]:
    """Buckets ordered leaf-most first: (depth-from-devices, bucket)."""
    m = cw.crush

    def depth(b) -> int:
        d = 1
        for it in b.items:
            if it < 0:
                sub = m.bucket(it)
                if sub is not None:
                    d = max(d, 1 + depth(sub))
        return d

    out = [(depth(b), b) for b in m.buckets if b is not None]
    out.sort(key=lambda t: t[0])
    return out


def calc_weight_set(osdmap: OSDMap, pool_id: int,
                    max_iterations: int = 30,
                    step: float = 0.4) -> Tuple[float, float]:
    """crush-compat balancer (pybind/mgr/balancer/module.py
    do_crush_compat): optimize a per-position ``weight_set``
    (crush.h:273 crush_choose_arg) so the pool's PG distribution
    flattens WITHOUT any pg_upmap entries — the mode for clients too
    old to decode upmaps.

    Leaf (device) weights in each position's set are nudged toward
    each osd's per-position placement target; interior buckets' entries
    re-aggregate their children.  The weight_set with the best overall
    stddev wins and is stored under the map's choose_args[pool_id].
    Returns (stddev_before, stddev_after) in PG-copy units.
    """
    pool = osdmap.pools[pool_id]
    cw = osdmap.crush
    m = cw.crush
    npos = pool.size
    # working weight sets: bucket id -> per-position weight lists,
    # seeded from the topology weights
    wsets: Dict[int, List[List[int]]] = {}
    for b in m.buckets:
        if b is not None:
            wsets[b.id] = [list(b.item_weights) for _ in range(npos)]

    def install(ws) -> None:
        args = [ChooseArg() for _ in range(len(m.buckets))]
        for bid, per_pos in ws.items():
            args[-1 - bid] = ChooseArg(
                weight_set=[WeightSet(weights=list(p))
                            for p in per_pos])
        m.choose_args[pool_id] = args

    def measure():
        counts = [dict() for _ in range(npos)]
        for ps in range(pool.pg_num):
            up, _ = osdmap.pg_to_raw_up(pg_t(pool_id, ps))
            for pos, o in enumerate(up):
                if o != CRUSH_ITEM_NONE and pos < npos:
                    counts[pos][o] = counts[pos].get(o, 0) + 1
        return counts

    def stddev(counts) -> float:
        total: Dict[int, int] = {}
        for c in counts:
            for o, n in c.items():
                total[o] = total.get(o, 0) + n
        osds = [o for o in range(osdmap.max_osd)
                if osdmap.exists(o) and osdmap.osd_weight[o] > 0]
        if not osds:
            return 0.0
        mean = sum(total.get(o, 0) for o in osds) / len(osds)
        return (sum((total.get(o, 0) - mean) ** 2
                    for o in osds) / len(osds)) ** 0.5

    # weight-proportional per-position targets from the TOPOLOGY
    leaf_w: Dict[int, int] = {}
    for b in m.buckets:
        if b is None:
            continue
        for it, w in zip(b.items, b.item_weights):
            if it >= 0:
                leaf_w[it] = w
    wsum = sum(leaf_w.values()) or 1

    baseline = measure()
    best_dev = before = stddev(baseline)
    best_ws = {bid: [list(p) for p in per]
               for bid, per in wsets.items()}
    counts = baseline
    depth_order = _bucket_depths(cw)    # topology-invariant
    for _ in range(max_iterations):
        copies = [sum(c.values()) for c in counts]
        for pos in range(npos):
            tgt = {o: copies[pos] * w / wsum for o, w in leaf_w.items()}
            for b in m.buckets:
                if b is None:
                    continue
                pp = wsets[b.id][pos]
                for i, it in enumerate(b.items):
                    if it < 0:
                        continue
                    actual = counts[pos].get(it, 0)
                    want = tgt.get(it, 0.0)
                    if want <= 0:
                        continue
                    factor = 1.0 + step * (want - actual) / max(want, 1.0)
                    pp[i] = max(1, int(pp[i] * factor))
        # interior buckets re-aggregate their children per position
        for _d, b in depth_order:
            for pos in range(npos):
                for i, it in enumerate(b.items):
                    if it < 0:
                        sub = m.bucket(it)
                        if sub is not None:
                            wsets[b.id][pos][i] = max(
                                1, sum(wsets[it][pos]))
        install(wsets)
        counts = measure()
        dev = stddev(counts)
        if dev < best_dev:
            best_dev = dev
            best_ws = {bid: [list(p) for p in per]
                       for bid, per in wsets.items()}
    install(best_ws)
    return before, best_dev
