"""calc_pg_upmaps — the upmap balancer.

Functional equivalent of OSDMap::calc_pg_upmaps (reference osd/OSDMap.h:1305,
used by `osdmaptool --upmap` and the mgr balancer module): iteratively move
PG replicas off the most-overfull OSD onto the most-underfull candidate via
pg_upmap_items entries, while respecting the rule's failure-domain
separation (the reference walks the rule via try_remap_rule,
CrushWrapper.h:1503; here candidates are constrained to unused failure
domains and every move is validated by actually re-running the mapping).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..crush.constants import (
    CRUSH_ITEM_NONE, CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
)
from .osdmap import Incremental, OSDMap
from .types import pg_t


def _failure_domain_type(osdmap: OSDMap, ruleno: int) -> int:
    rule = osdmap.crush.crush.rules[ruleno]
    for step in rule.steps:
        if step.op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                       CRUSH_RULE_CHOOSELEAF_INDEP,
                       CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP):
            return step.arg2
    return 0


def _domain_of(osdmap: OSDMap, osd: int, dtype: int) -> int:
    """The ancestor bucket of type *dtype* containing *osd* (osd itself
    when the domain is the device)."""
    if dtype == 0:
        return osd
    cw = osdmap.crush
    item = osd
    seen = 0
    while seen < 16:
        seen += 1
        parent = None
        for b in cw.crush.buckets:
            if b is not None and item in b.items:
                parent = b
                break
        if parent is None:
            return item
        if parent.type == dtype:
            return parent.id
        item = parent.id
    return item


def calc_pg_upmaps(osdmap: OSDMap, max_deviation: float = 0.01,
                   max_iterations: int = 100,
                   pools: Optional[List[int]] = None,
                   inc: Optional[Incremental] = None) -> int:
    """Compute pg_upmap_items flattening the distribution.

    Stores results into *inc* (and the map's pg_upmap_items for chained
    evaluation); returns the number of changes, like the reference.
    """
    pools = pools if pools is not None else sorted(osdmap.pools)
    changes = 0

    for _ in range(max_iterations):
        # current distribution over raw-up mappings
        pgs_by_osd: Dict[int, List[pg_t]] = {}
        total_copies = 0
        dom_cache: Dict[Tuple[int, int], int] = {}
        pg_map: Dict[pg_t, List[int]] = {}
        for pid in pools:
            pool = osdmap.pools[pid]
            for ps in range(pool.pg_num):
                pg = pg_t(pid, ps)
                up, _ = osdmap.pg_to_raw_up(pg)
                up = [o for o in up if o != CRUSH_ITEM_NONE]
                pg_map[pg] = up
                for o in up:
                    pgs_by_osd.setdefault(o, []).append(pg)
                    total_copies += 1
        weights = {o: osdmap.osd_weight[o]
                   for o in range(osdmap.max_osd)
                   if osdmap.exists(o) and osdmap.osd_weight[o] > 0}
        if not weights or not total_copies:
            return changes
        wsum = sum(weights.values())
        target = {o: total_copies * w / wsum for o, w in weights.items()}
        deviation = {o: len(pgs_by_osd.get(o, [])) - target[o]
                     for o in weights}
        over = max(deviation, key=lambda o: deviation[o])
        under = sorted((o for o in weights if deviation[o] < 0),
                       key=lambda o: deviation[o])
        if deviation[over] <= max(1.0, max_deviation * total_copies /
                                  max(1, len(weights))):
            break
        moved = False
        for pg in sorted(pgs_by_osd.get(over, []), key=str):
            pool = osdmap.pools[pg.pool]
            ruleno = osdmap.crush.find_rule(pool.crush_rule, pool.type,
                                            pool.size)
            dtype = _failure_domain_type(osdmap, ruleno)
            cur = pg_map[pg]
            used_domains = set()
            for o in cur:
                if o == over:
                    continue
                key = (o, dtype)
                if key not in dom_cache:
                    dom_cache[key] = _domain_of(osdmap, o, dtype)
                used_domains.add(dom_cache[key])
            for cand in under:
                if cand in cur:
                    continue
                key = (cand, dtype)
                if key not in dom_cache:
                    dom_cache[key] = _domain_of(osdmap, cand, dtype)
                if dom_cache[key] in used_domains:
                    continue
                # validate by applying the remap for real
                items = osdmap.pg_upmap_items.get(pg, [])
                trial = [it for it in items if it[0] != over] \
                    + [(over, cand)]
                osdmap.pg_upmap_items[pg] = trial
                new_up, _ = osdmap.pg_to_raw_up(pg)
                if over in new_up or cand not in new_up:
                    if items:
                        osdmap.pg_upmap_items[pg] = items
                    else:
                        del osdmap.pg_upmap_items[pg]
                    continue
                if inc is not None:
                    inc.new_pg_upmap_items[pg] = trial
                changes += 1
                moved = True
                break
            if moved:
                break
        if not moved:
            break
    return changes
