"""Sharded op queue + mClock QoS scheduling — the OSD's op intake.

Two reference mechanisms reproduced with honest semantics:

- ``ShardedOpWQ`` (common/WorkQueue.h:618, osd/OSD.cc:2008): client ops
  land in one of N shards keyed by PG id, so one PG's ops stay strictly
  FIFO while different PGs interleave fairly.  The reference drains
  shards with a thread pool; this single-threaded runtime drains them
  explicitly (``drain``), preserving the ordering/fairness contract the
  threads would give.
- ``MClockQueue`` (osd/mClockOpClassQueue.h over src/dmclock): QoS
  arbitration between op classes (client / recovery / scrub / snaptrim)
  by (reservation, weight, limit) tags.  Classes below their
  reservation are served first (most-behind first); the rest share by
  weight (lowest virtual finish tag wins); classes at their limit wait.

The scheduler decides ORDER whenever more ops are queued than drained
in one step — exactly the burst case QoS exists for.

Per-client dmClock (docs/QOS.md): inside each class queue, ops that
carry a client entity are arbitrated by a second dmClock tier keyed by
that entity — (reservation, weight, limit) per client, defaults from
the ``osd_mclock_client_*`` options, overrides from
``osd_mclock_client_overrides``.  The class tier stays the OUTER
arbiter (recovery/scrub arbitration is unchanged); the client tier
only decides WHICH client's op goes when the class tier picks that
class.  The client tier always runs the deterministic virtual clock
(one tick per pop): its reservation/limit are shares of the class's
dequeues (ops per 1000 client-tier pops), not wall rates — wall-rate
enforcement stays a class-tier (WallMClockQueue) property.
"""
from __future__ import annotations

import threading

from .lockdep import DebugLock
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..trace.oplat import mark_item

# op classes (mClockOpClassQueue's osd_op_queue_mclock_* option groups)
CLASS_CLIENT = "client"
CLASS_RECOVERY = "recovery"
CLASS_SCRUB = "scrub"
CLASS_SNAPTRIM = "snaptrim"

# (reservation, weight, limit) per class, in ops per virtual second;
# defaults shaped like the reference's mclock option defaults: clients
# get most of the weight, background work is reservation-guaranteed but
# limited so it cannot starve clients
DEFAULT_TAGS: Dict[str, Tuple[float, float, float]] = {
    CLASS_CLIENT: (100.0, 500.0, 0.0),      # limit 0 = unlimited
    CLASS_RECOVERY: (50.0, 100.0, 200.0),
    CLASS_SCRUB: (10.0, 50.0, 100.0),
    CLASS_SNAPTRIM: (10.0, 50.0, 100.0),
}


# ---- qos perf counters (perf dump / Prometheus) ----------------------------
QOS_FIRST = 95000
l_qos_dequeue_client = 95001     # client-class ops dequeued
l_qos_dequeue_recovery = 95002
l_qos_dequeue_scrub = 95003
l_qos_dequeue_snaptrim = 95004
l_qos_admission_rejections = 95005  # ops refused at intake (EAGAIN)
l_qos_throttle_events = 95006    # clients newly entering throttle
l_qos_queue_depth = 95007        # gauge: op-queue depth at last intake
QOS_LAST = 95010

_CLASS_DEQ_IDX = {
    CLASS_CLIENT: l_qos_dequeue_client,
    CLASS_RECOVERY: l_qos_dequeue_recovery,
    CLASS_SCRUB: l_qos_dequeue_scrub,
    CLASS_SNAPTRIM: l_qos_dequeue_snaptrim,
}

_qos_pc = None
_qos_pc_lock = DebugLock("qos_pc::init")


def qos_perf_counters():
    """The op-queue QoS counter logger (perf dump / Prometheus)."""
    global _qos_pc
    if _qos_pc is not None:
        return _qos_pc
    with _qos_pc_lock:
        if _qos_pc is None:
            from .perf_counters import PerfCountersBuilder
            b = PerfCountersBuilder("qos", QOS_FIRST, QOS_LAST)
            b.add_u64_counter(l_qos_dequeue_client, "dequeues_client",
                              "client-class ops dequeued")
            b.add_u64_counter(l_qos_dequeue_recovery, "dequeues_recovery",
                              "recovery-class ops dequeued")
            b.add_u64_counter(l_qos_dequeue_scrub, "dequeues_scrub",
                              "scrub-class ops dequeued")
            b.add_u64_counter(l_qos_dequeue_snaptrim, "dequeues_snaptrim",
                              "snaptrim-class ops dequeued")
            b.add_u64_counter(l_qos_admission_rejections,
                              "admission_rejections",
                              "client ops shed at intake "
                              "(osd_op_queue_admission_max)")
            b.add_u64_counter(l_qos_throttle_events, "throttle_events",
                              "clients newly entering the admission "
                              "throttle window")
            b.add_u64(l_qos_queue_depth, "queue_depth",
                      "op-queue depth observed at the last intake")
            _qos_pc = b.create_perf_counters()
    return _qos_pc


def _note_class_dequeue(op_class: str) -> None:
    idx = _CLASS_DEQ_IDX.get(op_class)
    if idx is not None:
        qos_perf_counters().inc(idx)


class DmClockArbiter:
    """The dmclock-lite three-phase arbitration core, generalized over
    an abstract ENTITY key with pluggable tag lookup — one
    implementation shared by both virtual-clock tiers (the class tier
    arbitrates op classes in :class:`MClockQueue`, the client tier
    arbitrates client entities in :class:`ClientDmClock`) so the tiers
    cannot drift (the ROADMAP residual-debt item; the self-tuning
    control plane will turn tags through this one core).

    Tags are (reservation, weight, limit) shares per 1000 dequeues of
    the owning queue's virtual clock — one tick per pop, deterministic,
    no wall time in the decision path (wall-rate enforcement is
    :class:`WallMClockQueue`'s separate job).  The three phases:

    - **reservation**: entities behind their guaranteed share go
      first, most-behind-its-floor first;
    - **weight**: among entities under their limit, the lowest
      NORMALIZED virtual finish tag (``w_tag / weight``) wins;
    - **limit**: ``w_tag >= now * limit / 1000`` stands an entity
      aside in the weight phase — unless every candidate is at its
      limit (work conservation: an idle server never refuses work).

    Idle->active re-clamping (``activate``) keeps the dmclock
    invariants: no hoarded reservation credit, and the weight tag
    starts at the most-behind ACTIVE entity's normalized finish.  The
    tiers differ only in the clamp's default when NOTHING else is
    active: ``track_floor=True`` (client tier) remembers the last
    served finish tag so a newcomer to an empty lane set cannot starve
    entities with history; ``False`` (class tier) leaves the tag
    untouched, the class tier's historical rule.
    """

    __slots__ = ("r_tags", "w_tags", "now", "w_floor", "track_floor")

    def __init__(self, track_floor: bool = False):
        self.r_tags: Dict[str, float] = {}
        self.w_tags: Dict[str, float] = {}
        self.now = 0.0
        self.w_floor = 0.0          # last served normalized finish tag
        self.track_floor = track_floor

    def tick(self) -> None:
        """Advance the virtual clock: one unit per dequeue attempt."""
        self.now += 1.0

    def activate(self, entity: str, res: float, weight: float,
                 active: List[str],
                 weight_of: Callable[[str], float]) -> None:
        """Idle->active tag re-clamp for *entity*; *active* is the set
        of entities with queued work (the activating entity's queue is
        still empty when this runs)."""
        if res > 0:
            self.r_tags[entity] = max(self.r_tags.get(entity, 0.0),
                                      self.now * res / 1000.0)
        floors = [self.w_tags.get(c, 0.0) / max(weight_of(c), 1e-9)
                  for c in active]
        if floors:
            floor = min(floors)
        elif self.track_floor:
            floor = self.w_floor
        else:
            return
        self.w_tags[entity] = max(self.w_tags.get(entity, 0.0),
                                  floor * max(weight, 1e-9))

    def pick(self, candidates: List[str],
             tags: Dict[str, Tuple[float, float, float]]) -> str:
        """The three-phase choice among non-empty *candidates*."""
        if len(candidates) == 1:
            return candidates[0]
        # phase 1: reservations — most-behind-its-floor first
        best, best_deficit = None, 0.0
        for c in candidates:
            res = tags[c][0]
            if res <= 0:
                continue
            deficit = self.now * res / 1000.0 - self.r_tags.get(c, 0.0)
            if deficit > best_deficit:
                best, best_deficit = c, deficit
        if best is not None:
            return best

        # phase 2: weight shares — lowest normalized finish tag wins;
        # entities at their limit stand aside unless all are (phase 3)
        def finish(c: str) -> float:
            return self.w_tags.get(c, 0.0) / max(tags[c][1], 1e-9)

        under = [c for c in candidates
                 if not self.at_limit(c, tags[c][2])]
        return min(under or candidates, key=finish)

    def at_limit(self, entity: str, lim: float) -> bool:
        if lim <= 0:
            return False
        return self.w_tags.get(entity, 0.0) >= self.now * lim / 1000.0

    def serve(self, entity: str, weight: float) -> None:
        """Account one dequeue against *entity*'s tags."""
        self.r_tags[entity] = self.r_tags.get(entity, 0.0) + 1.0
        self.w_tags[entity] = self.w_tags.get(entity, 0.0) + 1.0
        if self.track_floor:
            self.w_floor = max(
                self.w_floor,
                self.w_tags[entity] / max(weight, 1e-9))

    def forget(self, entity: str) -> None:
        """Drop an evicted entity's tag state (bounded memory under
        entity churn; a returner is re-clamped like any newcomer)."""
        self.r_tags.pop(entity, None)
        self.w_tags.pop(entity, None)


class ClientDmClock:
    """The per-client dmClock lane INSIDE one op class's queue.

    Deque-compatible container (``push``/``pop``/``__len__``) so the
    class-tier arbiters need not know clients exist: when the class
    tier picks this class, ``pop`` runs a second (reservation, weight,
    limit) arbitration across the client entities queued here.  Ops
    enqueued with no client share the ``""`` lane (pure FIFO among
    themselves — exactly the pre-client behavior).

    Virtual clock: one tick per pop, so reservation/limit read as ops
    per 1000 client-tier dequeues — deterministic, like MClockQueue.
    The arbitration itself is :class:`DmClockArbiter` — the SAME core
    the class tier runs, parameterized only by this tier's tag lookup
    and floor policy.  Per-client tags resolve override ->
    ``osd_mclock_client_*`` defaults; ``osd_mclock_client_overrides``
    is parsed lazily ("entity:res:weight:limit[,entity:...]") and
    re-parsed whenever the option string changes, so injectargs takes
    effect immediately.
    """

    __slots__ = ("_queues", "_arb", "_size",
                 "_dequeues", "_override_src", "_overrides",
                 "_local_tags", "_defaults", "_resolved")

    def __init__(self):
        self._queues: Dict[str, Deque] = {}
        self._arb = DmClockArbiter(track_floor=True)
        self._size = 0
        self._dequeues: Dict[str, int] = {}
        self._override_src: Optional[str] = None
        self._overrides: Dict[str, Tuple[float, float, float]] = {}
        self._local_tags: Dict[str, Tuple[float, float, float]] = {}
        self._defaults: Optional[Tuple[float, float, float]] = None
        self._resolved: Dict[str, Tuple[float, float, float]] = {}

    # ---- tags --------------------------------------------------------------
    def set_client_tags(self, client: str, res: float, weight: float,
                        limit: float) -> None:
        self._local_tags[client] = (float(res), float(weight),
                                    float(limit))

    def _refresh_tag_sources(self) -> None:
        """Re-read the osd_mclock_client_* options ONCE per arbitration
        (pop / idle->active push), not once per candidate: any change
        to the overrides string or the three defaults drops the
        per-client resolved cache, so injectargs stays live while a
        steady-state pop costs one dict lookup per candidate."""
        from .config import g_conf
        src = str(g_conf.get_val("osd_mclock_client_overrides") or "")
        defaults = (
            float(g_conf.get_val("osd_mclock_client_reservation")),
            float(g_conf.get_val("osd_mclock_client_weight")),
            float(g_conf.get_val("osd_mclock_client_limit")))
        if src == self._override_src and defaults == self._defaults:
            return
        self._override_src = src
        self._defaults = defaults
        self._resolved = {}
        self._overrides = {}
        for part in src.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.rsplit(":", 3)
            if len(bits) != 4:
                continue     # malformed entry: fall to defaults
            try:
                self._overrides[bits[0]] = (float(bits[1]),
                                            float(bits[2]),
                                            float(bits[3]))
            except ValueError:
                continue

    def _tags_for(self, client: str) -> Tuple[float, float, float]:
        t = self._local_tags.get(client)
        if t is not None:
            return t
        t = self._resolved.get(client)
        if t is None:
            if self._defaults is None:
                self._refresh_tag_sources()
            t = self._resolved[client] = self._overrides.get(
                client, self._defaults)
        return t

    # ---- deque-compatible container API ------------------------------------
    def __len__(self) -> int:
        return self._size

    def push(self, client: str, item) -> None:
        q = self._queues.get(client)
        if q is None:
            q = self._queues[client] = deque()
        if not q:
            # idle -> active re-clamp (DmClockArbiter.activate): no
            # hoarded reservation credit, weight tag floored at the
            # most-behind ACTIVE client's normalized finish (or the
            # last served finish when alone — track_floor)
            self._refresh_tag_sources()
            res, weight, _lim = self._tags_for(client)
            active = [c for c, aq in self._queues.items() if aq]
            self._arb.activate(client, res, weight, active,
                               lambda c: self._tags_for(c)[1])
        q.append(item)
        self._size += 1

    def pop(self):
        """QoS-chosen item; None when empty."""
        candidates = [c for c, q in self._queues.items() if q]
        if not candidates:
            return None
        self._arb.tick()
        # one option-change check per pop; per-candidate resolution is
        # then a cached dict lookup (nothing can change mid-decision)
        self._refresh_tag_sources()
        tags = {c: self._tags_for(c) for c in candidates}
        best = self._arb.pick(candidates, tags)
        item = self._queues[best].popleft()
        self._size -= 1
        self._arb.serve(best, tags[best][1])
        self._dequeues[best] = self._dequeues.get(best, 0) + 1
        if not self._queues[best] and len(self._queues) > 64:
            # bound per-client memory under churn ("millions of
            # users"): evict the drained lane AND its tag/accounting
            # state — a returning client is re-clamped by push() like
            # any newcomer, so dropped history is safe by construction
            del self._queues[best]
            self._arb.forget(best)
            self._dequeues.pop(best, None)
            self._resolved.pop(best, None)
        return item

    def dump(self) -> Dict:
        return {
            "queued": {c: len(q) for c, q in self._queues.items() if q},
            "dequeues": dict(self._dequeues),
            "w_tags": {c: round(v, 3)
                       for c, v in self._arb.w_tags.items()},
        }


class _LiveClassTags:
    """Live ``osd_mclock_class_overrides`` overlay for the class-tier
    queues: the constructor tags are kept as a base, and every
    arbitration entry point re-checks the option string (cached-source
    idiom, like ``ClientDmClock._refresh_tag_sources``) so injectargs
    re-weights a RUNNING queue without daemon restart — the controller
    plane's recovery-vs-client actuator depends on this.  Classes not
    present in the base tag set are ignored (an override cannot invent
    an op class), malformed entries fall through to the base tags."""

    def _init_live_tags(self, tags: Optional[Dict[str, Tuple[
            float, float, float]]]) -> None:
        self._base_tags = dict(tags or DEFAULT_TAGS)
        self.tags = dict(self._base_tags)
        self._class_src: Optional[str] = None
        # apply the overlay NOW so an unchanged option string never
        # rebuilds self.tags later — direct pokes at a live queue's
        # tags (the test idiom) survive until the option changes
        self._refresh_class_tags()

    def _refresh_class_tags(self) -> None:
        from .config import g_conf
        src = str(g_conf.get_val("osd_mclock_class_overrides") or "")
        if src == self._class_src:
            return
        self._class_src = src
        merged = dict(self._base_tags)
        for part in src.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.rsplit(":", 3)
            if len(bits) != 4 or bits[0] not in merged:
                continue     # malformed / unknown class: base tags
            try:
                merged[bits[0]] = (float(bits[1]), float(bits[2]),
                                   float(bits[3]))
            except ValueError:
                continue
        self.tags = merged


class MClockQueue(_LiveClassTags):
    """dmclock-lite over a virtual clock that advances one unit per
    dequeue (deterministic; no wall time in the decision path).

    The arbitration is :class:`DmClockArbiter` over op-class entity
    keys with ``self.tags`` as the tag lookup — the SAME core the
    per-client lanes inside each class run, so the two tiers cannot
    drift apart.  ``self.tags`` is the constructor base overlaid live
    with ``osd_mclock_class_overrides`` (:class:`_LiveClassTags`)."""

    def __init__(self, tags: Optional[Dict[str, Tuple[float, float,
                                                      float]]] = None):
        self._init_live_tags(tags)
        self._queues: Dict[str, ClientDmClock] = {}
        self._arb = DmClockArbiter(track_floor=False)
        self._size = 0

    def enqueue(self, op_class: str, item, client: str = "") -> None:
        self._refresh_class_tags()
        if op_class not in self.tags:
            op_class = CLASS_CLIENT
        q = self._queues.get(op_class)
        if q is None:
            q = self._queues[op_class] = ClientDmClock()
        if not q:
            # idle -> active re-clamp (DmClockArbiter.activate): a
            # long-idle class cannot cash in an unbounded reservation
            # deficit or dodge its limit
            res, weight, _lim = self.tags[op_class]
            active = [c for c, aq in self._queues.items() if aq]
            self._arb.activate(op_class, res, weight, active,
                               lambda c: self.tags[c][1])
        q.push(client, item)
        self._size += 1

    def __len__(self) -> int:
        return self._size

    def dequeue(self):
        """Pop the QoS-chosen item; None when empty."""
        self._arb.tick()
        self._refresh_class_tags()
        candidates = [c for c, q in self._queues.items() if q]
        if not candidates:
            return None
        best = self._arb.pick(candidates, self.tags)
        # stage ledger: the class tier picked this class NOW; the lane
        # pop below is the client tier's own arbitration (oplat stages
        # class_queue / client_lane — host-side stamps only)
        t_pick = time.perf_counter()
        item = self._queues[best].pop()
        self._size -= 1
        self._arb.serve(best, self.tags[best][1])
        _note_class_dequeue(best)
        mark_item(item, "class_queue", t_pick)
        mark_item(item, "client_lane")
        return item

    def dump(self) -> Dict:
        return {
            "queued": {c: len(q) for c, q in self._queues.items() if q},
            "vclock": self._arb.now,
            "r_tags": dict(self._arb.r_tags),
            "w_tags": dict(self._arb.w_tags),
            # client-tier accounting survives a drained queue: the
            # dequeue history is exactly what an operator inspects
            # AFTER a burst
            "clients": {c: q.dump() for c, q in self._queues.items()},
        }

    def _at_limit(self, c: str) -> bool:
        return self._arb.at_limit(c, self.tags[c][2])


class WallMClockQueue(_LiveClassTags):
    """dmclock against WALL time — a real rate enforcer, not just an
    ordering arbiter (src/dmclock dmc::PriorityQueue semantics).

    Tags are per-class times in seconds: the reservation tag is when
    the class's next guaranteed-credit falls due (1/res apart), the
    limit tag is when it is next allowed a weight-phase dequeue (1/lim
    apart).  ``dequeue(now)``:

    - reservation phase: any class whose reservation tag <= now is owed
      service; most-overdue first.  Floors are therefore honored in
      real ops/sec, and an idle class cannot hoard credit (tags clamp
      to now on idle->active, dmclock's tag re-clamping).
    - weight phase: among classes under their limit (limit tag <= now),
      lowest virtual finish tag wins; serving pushes the limit tag
      forward by 1/lim, so a class can NEVER exceed limit ops/sec over
      any window, even on an otherwise idle OSD.
    - neither ready: returns (None, next_due) so the caller can sleep
      until credit accrues instead of spinning.

    (res, weight, limit) keep the DEFAULT_TAGS shapes but are read as
    ops per REAL second here.
    """

    def __init__(self, tags: Optional[Dict[str, Tuple[float, float,
                                                      float]]] = None,
                 clock: Optional[Callable[[], float]] = None):
        import time as _time
        self._init_live_tags(tags)
        self.clock = clock or _time.monotonic
        self._queues: Dict[str, ClientDmClock] = {}
        self._r_next: Dict[str, float] = {}   # next reservation due
        self._l_next: Dict[str, float] = {}   # next limit-allowed slot
        self._w_tags: Dict[str, float] = {}   # virtual weight finish
        self._w_floor = 0.0                   # last served finish tag
        self._size = 0

    def enqueue(self, op_class: str, item, client: str = "") -> None:
        self._refresh_class_tags()
        if op_class not in self.tags:
            op_class = CLASS_CLIENT
        q = self._queues.get(op_class)
        if q is None:
            q = self._queues[op_class] = ClientDmClock()
        if not q:
            now = self.clock()
            # idle -> active: no hoarded reservation credit, no limit
            # debt from the idle past
            self._r_next[op_class] = max(
                self._r_next.get(op_class, 0.0), now)
            self._l_next[op_class] = max(
                self._l_next.get(op_class, 0.0), now)
            # clamp the weight tag to the virtual present: a fresh
            # class entering an EMPTY queue starts at the last served
            # finish tag (not 0, which would starve any class with
            # history), and a returning class starts no better than
            # the most-behind active class
            active = [c for c, aq in self._queues.items() if aq]
            floor = min((self._w_tags.get(c, 0.0) for c in active),
                        default=self._w_floor)
            self._w_tags[op_class] = max(
                self._w_tags.get(op_class, 0.0), floor)
        q.push(client, item)
        self._size += 1

    def __len__(self) -> int:
        return self._size

    def dequeue(self, now: Optional[float] = None):
        """-> (item, 0.0) or (None, next_due_time); next_due is 0.0
        when the queue is empty."""
        now = self.clock() if now is None else now
        self._refresh_class_tags()
        candidates = [c for c, q in self._queues.items() if q]
        if not candidates:
            return None, 0.0
        # ---- reservation phase (floors) --------------------------------
        best, best_overdue = None, 0.0
        for c in candidates:
            res = self.tags[c][0]
            if res <= 0:
                continue
            overdue = now - self._r_next.get(c, 0.0)
            if overdue >= 0 and (best is None or overdue > best_overdue):
                best, best_overdue = c, overdue
        if best is not None:
            return self._serve(best, now, reserved=True), 0.0
        # ---- weight phase (shares under ceilings) ----------------------
        under = [c for c in candidates
                 if self.tags[c][2] <= 0
                 or self._l_next.get(c, 0.0) <= now]
        if under:
            best = min(under, key=lambda c: self._w_tags.get(c, 0.0))
            return self._serve(best, now, reserved=False), 0.0
        # everyone is rate-blocked: report when the earliest credit
        # (reservation or limit slot) falls due
        nxt = min(min((self._r_next.get(c, now) for c in candidates
                       if self.tags[c][0] > 0), default=float("inf")),
                  min(self._l_next.get(c, now) for c in candidates))
        return None, nxt

    def _serve(self, c: str, now: float, reserved: bool):
        t_pick = time.perf_counter()
        item = self._queues[c].pop()
        self._size -= 1
        _note_class_dequeue(c)
        mark_item(item, "class_queue", t_pick)
        mark_item(item, "client_lane")
        res, weight, lim = self.tags[c]
        if res > 0:
            # served work counts toward the floor whatever phase it
            # used (dmclock advances the reservation tag on any serve)
            self._r_next[c] = max(self._r_next.get(c, 0.0), now) \
                + (1.0 / res)
        if lim > 0:
            self._l_next[c] = max(self._l_next.get(c, 0.0), now) \
                + (1.0 / lim)
        self._w_tags[c] = self._w_tags.get(c, 0.0) \
            + 1.0 / max(weight, 1e-9)
        self._w_floor = self._w_tags[c]
        return item

    def has_ready(self, now: Optional[float] = None) -> bool:
        """True when some queued op is dispatchable right now (not
        rate-blocked) — the drain/flush boundary must not wait out the
        rate limiter itself."""
        now = self.clock() if now is None else now
        for c, q in self._queues.items():
            if not q:
                continue
            res, _w, lim = self.tags[c]
            if res > 0 and self._r_next.get(c, 0.0) <= now:
                return True
            if lim <= 0 or self._l_next.get(c, 0.0) <= now:
                return True
        return False

    def dump(self) -> Dict:
        return {
            "queued": {c: len(q) for c, q in self._queues.items() if q},
            "mode": "wall",
            "r_next": dict(self._r_next),
            "l_next": dict(self._l_next),
            "w_tags": dict(self._w_tags),
            "clients": {c: q.dump() for c, q in self._queues.items()},
        }


class ShardedOpWQ:
    """PG-sharded front queues feeding per-shard mClock arbiters."""

    def __init__(self, n_shards: int = 5,
                 tags: Optional[Dict] = None, wall: bool = False):
        self.n_shards = n_shards
        self.wall = wall
        cls = WallMClockQueue if wall else MClockQueue
        self.shards: List = [cls(tags) for _ in range(n_shards)]
        # one PG's ops must stay FIFO: the shard index is a pure
        # function of the pgid (OSD.cc shard = pgid.hash % num_shards)
        self._rr = 0

    def _deq(self, shard):
        """Uniform dequeue across clock modes; wall mode records when
        the next rate credit falls due so drainers can sleep exactly
        that long instead of a fixed poll interval."""
        if not self.wall:
            return shard.dequeue()
        item, nxt = shard.dequeue()
        if item is None and nxt:
            cur = getattr(self, "next_due", 0.0)
            self.next_due = nxt if not cur else min(cur, nxt)
        return item

    def take_next_due(self) -> float:
        """Earliest rate-credit time seen since the last call (0 =
        none); wall mode only."""
        nd = getattr(self, "next_due", 0.0)
        self.next_due = 0.0
        return nd

    def ready(self) -> bool:
        """Is there work dispatchable NOW?  In wall mode rate-blocked
        ops don't count: flush()/drain boundaries must not block on the
        rate limiter's schedule."""
        if not self.wall:
            return len(self) > 0
        return any(sh.has_ready() for sh in self.shards)

    def shard_of(self, pgid: Tuple[int, int]) -> int:
        return hash(pgid) % self.n_shards

    def enqueue(self, pgid: Tuple[int, int], op_class: str, item,
                client: str = "") -> None:
        pool = getattr(self, "_pool", None)
        if pool is not None:
            # threaded mode: the per-shard queues are shared with the
            # workers; serialize on the pool's condition lock and wake
            with pool._cv:
                self.shards[self.shard_of(pgid)].enqueue(op_class, item,
                                                         client)
                pool._cv.notify_all()
        else:
            self.shards[self.shard_of(pgid)].enqueue(op_class, item,
                                                     client)

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    def dump(self) -> Dict:
        """Introspection for the admin socket (dump_op_pq_state role)."""
        return {f"shard_{i}": sh.dump()
                for i, sh in enumerate(self.shards)}

    def drain(self, handler: Callable, max_ops: int = 0) -> int:
        """Round-robin the shards, QoS-dequeue within each; returns the
        number of ops handled."""
        done = 0
        idle_rounds = 0
        while idle_rounds < self.n_shards:
            if max_ops and done >= max_ops:
                break
            shard = self.shards[self._rr]
            self._rr = (self._rr + 1) % self.n_shards
            item = self._deq(shard)
            if item is None:
                idle_rounds += 1
                continue
            idle_rounds = 0
            handler(item)
            done += 1
        return done


class ShardedThreadPool:
    """Real worker threads draining the sharded queues — the reference's
    ShardedThreadPool (common/WorkQueue.h:618, started by the OSD at
    OSD.cc:2008 as osd_op_tp).

    Each worker owns a subset of shards (shard i -> worker i % n) so a
    PG's ops stay FIFO within their shard while different shards run
    GENUINELY concurrently; the handler is responsible for taking the
    locks its shared state needs (the reference's dequeue_op takes the
    PG lock the same way), which is exactly what puts lockdep and the
    mClock arbiters under real contention.
    """

    def __init__(self, wq: "ShardedOpWQ", handler: Callable,
                 n_threads: int = 2):
        import threading
        self.wq = wq
        self.handler = handler
        self.n_threads = max(1, n_threads)
        self._lock = DebugLock("ThreadPool::lock")
        self._cv = threading.Condition(self._lock)
        self._stopping = False
        self._active = 0
        wq._pool = self
        self._threads = [
            threading.Thread(target=self._worker, args=(i,),
                             name=f"osd-op-tp-{i}", daemon=True)
            for i in range(self.n_threads)]
        for t in self._threads:
            t.start()

    def _my_shards(self, i: int) -> List[int]:
        return [s for s in range(self.wq.n_shards)
                if s % self.n_threads == i]

    def _worker(self, i: int) -> None:
        shards = self._my_shards(i)
        while True:
            item = None
            with self._cv:
                while True:
                    if self._stopping:
                        return
                    for s in shards:
                        item = self.wq._deq(self.wq.shards[s])
                        if item is not None:
                            break
                    if item is not None:
                        self._active += 1
                        break
                    timeout = 0.05
                    if self.wq.wall:
                        nd = self.wq.take_next_due()
                        if nd:
                            import time as _time
                            timeout = max(0.001,
                                          min(0.05,
                                              nd - _time.monotonic()))
                    self._cv.wait(timeout=timeout)
            try:
                self.handler(item)
            except Exception:
                # a poisoned op must not kill the worker: its shards
                # are statically partitioned with no takeover, so a
                # dead thread would strand every future op hashed to
                # them (and hang flush callers)
                import traceback
                traceback.print_exc()
            finally:
                with self._cv:
                    self._active -= 1
                    self._cv.notify_all()

    def kick(self) -> None:
        """Wake workers after an enqueue."""
        with self._cv:
            self._cv.notify_all()

    def flush(self, timeout: float = 60.0) -> None:
        """Block until every queued op has been HANDLED (drain + join
        in-flight handlers) — the synchronous boundary the in-process
        fabric's pump loops rely on."""
        import time as _time
        end = _time.monotonic() + timeout
        with self._cv:
            self._cv.notify_all()
            while (self.wq.ready() or self._active) and \
                    _time.monotonic() < end:
                self._cv.wait(timeout=0.05)
                self._cv.notify_all()
        if self.wq.ready() or self._active:
            raise TimeoutError("op thread pool failed to drain")

    def stop(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
