from .config import Option, ConfigProxy, OPT_INT, OPT_STR, OPT_FLOAT, \
    OPT_BOOL, OPT_DOUBLE
from .perf_counters import (
    PerfCounters, PerfCountersBuilder, PerfCountersCollection,
)
from .admin_socket import AdminSocket
from .tracked_op import OpTracker, TrackedOp
from .lockdep import (DebugLock, DebugRLock, LockOrderError,
                      lockdep_enable, lockdep_reset)
from .dout import Dout, Log, dlog, get_log, register_config_observers
from .kernel_trace import (
    KernelTimer, annotate, g_kernel_timer, start_profiler_trace,
    stop_profiler_trace,
)

__all__ = [
    "Option", "ConfigProxy", "OPT_INT", "OPT_STR", "OPT_FLOAT", "OPT_BOOL",
    "OPT_DOUBLE", "PerfCounters", "PerfCountersBuilder",
    "PerfCountersCollection", "AdminSocket", "OpTracker", "TrackedOp",
    "DebugLock", "DebugRLock", "LockOrderError", "lockdep_enable",
    "lockdep_reset",
    "Dout", "Log", "dlog", "get_log", "register_config_observers",
    "KernelTimer", "annotate", "g_kernel_timer", "start_profiler_trace",
    "stop_profiler_trace",
]
