from .config import Option, ConfigProxy, OPT_INT, OPT_STR, OPT_FLOAT, \
    OPT_BOOL, OPT_DOUBLE
from .perf_counters import (
    PerfCounters, PerfCountersBuilder, PerfCountersCollection,
)
from .admin_socket import AdminSocket
from .tracked_op import OpTracker, TrackedOp
from .lockdep import DebugLock, LockOrderError, lockdep_enable, lockdep_reset

__all__ = [
    "Option", "ConfigProxy", "OPT_INT", "OPT_STR", "OPT_FLOAT", "OPT_BOOL",
    "OPT_DOUBLE", "PerfCounters", "PerfCountersBuilder",
    "PerfCountersCollection", "AdminSocket", "OpTracker", "TrackedOp",
    "DebugLock", "LockOrderError", "lockdep_enable", "lockdep_reset",
]
