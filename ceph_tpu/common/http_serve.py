"""Shared threaded HTTP wrapper for the framework's pure
request->response frontends (rgw's S3/Swift handlers, the mgr
prometheus/restful surface).

``handle(method, path, headers, body, query) -> (status, headers,
body)`` frontends plug in unchanged; the in-process fabric is not
thread-safe, so concurrent connections serialize on one lock (the
reference runs real thread pools over thread-safe cores).
"""
from __future__ import annotations

import threading

from .lockdep import DebugLock
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Tuple
from urllib.parse import parse_qsl, urlparse

HandleFn = Callable[[str, str, Dict[str, str], bytes, Dict[str, str]],
                    Tuple[int, Dict[str, str], bytes]]


def serve_frontend(handle: HandleFn, port: int = 0):
    """Returns (server, port); ``server.shutdown()`` +
    ``server.server_close()`` when done (shutdown alone leaves the
    listening fd open)."""
    lock = DebugLock("http_frontend::serial")

    class Handler(BaseHTTPRequestHandler):
        def _run(self, method: str) -> None:
            u = urlparse(self.path)
            ln = int(self.headers.get("Content-Length", "0") or 0)
            body = self.rfile.read(ln) if ln else b""
            with lock:
                # keep_blank_values: bare subresource markers
                # (?versioning, ?uploads, ?acl ...) must survive
                status, hdrs, out = handle(
                    method, u.path, dict(self.headers), body,
                    dict(parse_qsl(u.query, keep_blank_values=True)))
            self.send_response(status)
            sent_len = False
            for k, v in hdrs.items():
                self.send_header(k, v)
                if k.lower() == "content-length":
                    sent_len = True
            if not sent_len:
                self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            if method != "HEAD":
                self.wfile.write(out)

        def do_GET(self):
            self._run("GET")

        def do_PUT(self):
            self._run("PUT")

        def do_POST(self):
            self._run("POST")

        def do_DELETE(self):
            self._run("DELETE")

        def do_HEAD(self):
            self._run("HEAD")

        def log_message(self, *a):  # pragma: no cover - quiet server
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]
