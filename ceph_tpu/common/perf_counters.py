"""PerfCounters — per-subsystem metric registry.

Mirrors the reference's counters (src/common/perf_counters.{h,cc}): a
builder declares u64 counters / time sums / long-run averages in a
contiguous index range, instances update lock-free-cheap, and a collection
dumps every logger as JSON for the admin socket's `perf dump`.
"""
from __future__ import annotations

import threading

from .lockdep import DebugLock
import time
from typing import Dict, List, Optional, Tuple

PERFCOUNTER_U64 = 1
PERFCOUNTER_TIME = 2
PERFCOUNTER_LONGRUNAVG = 4
PERFCOUNTER_COUNTER = 8


class _Counter:
    __slots__ = ("name", "type", "description", "value", "sum", "count")

    def __init__(self, name: str, type: int, description: str):
        self.name = name
        self.type = type
        self.description = description
        self.value = 0
        self.sum = 0.0
        self.count = 0


class PerfCounters:
    def __init__(self, name: str, lower: int, upper: int):
        self.name = name
        self.lower = lower
        self.upper = upper
        self._by_idx: Dict[int, _Counter] = {}
        self._lock = DebugLock("PerfCounters::lock")

    def _add(self, idx: int, c: _Counter) -> None:
        assert self.lower < idx < self.upper, "index out of declared range"
        self._by_idx[idx] = c

    # ---- updates ----------------------------------------------------------
    def inc(self, idx: int, amount: int = 1) -> None:
        """Bump a counter; ``count`` (the avgcount denominator) only
        moves for LONGRUNAVG counters, matching the reference's inc()
        (perf_counters.cc) — plain u64 counters must keep count == 0 so
        any future average over them isn't skewed by an inc-only,
        dec-never denominator."""
        c = self._by_idx[idx]
        with self._lock:
            c.value += amount
            if c.type & PERFCOUNTER_LONGRUNAVG:
                c.count += 1

    def dec(self, idx: int, amount: int = 1) -> None:
        """Reference semantics: dec() asserts on LONGRUNAVG counters
        and never touches avgcount — symmetric with inc() above."""
        c = self._by_idx[idx]
        assert not (c.type & PERFCOUNTER_LONGRUNAVG), \
            "dec() on a LONGRUNAVG counter (perf_counters.cc asserts)"
        with self._lock:
            c.value -= amount

    def set(self, idx: int, v: int) -> None:
        with self._lock:
            self._by_idx[idx].value = v

    def tinc(self, idx: int, seconds: float) -> None:
        c = self._by_idx[idx]
        with self._lock:
            c.sum += seconds
            c.count += 1

    def hinc(self, idx: int, v: float) -> None:
        """long-run average sample"""
        c = self._by_idx[idx]
        with self._lock:
            c.sum += v
            c.count += 1

    # ---- introspection ----------------------------------------------------
    def get(self, idx: int) -> int:
        return self._by_idx[idx].value

    def dump(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        with self._lock:
            for c in self._by_idx.values():
                if c.type & PERFCOUNTER_LONGRUNAVG:
                    out[c.name] = {"avgcount": c.count, "sum": c.sum}
                elif c.type & PERFCOUNTER_TIME:
                    out[c.name] = {"sum": c.sum, "avgcount": c.count}
                else:
                    out[c.name] = c.value
        return out


class PerfCountersBuilder:
    def __init__(self, name: str, lower: int, upper: int):
        self._pc = PerfCounters(name, lower, upper)

    def add_u64_counter(self, idx: int, name: str,
                        description: str = "") -> "PerfCountersBuilder":
        self._pc._add(idx, _Counter(name, PERFCOUNTER_U64
                                    | PERFCOUNTER_COUNTER, description))
        return self

    def add_u64(self, idx: int, name: str,
                description: str = "") -> "PerfCountersBuilder":
        self._pc._add(idx, _Counter(name, PERFCOUNTER_U64, description))
        return self

    def add_time_avg(self, idx: int, name: str,
                     description: str = "") -> "PerfCountersBuilder":
        self._pc._add(idx, _Counter(name, PERFCOUNTER_TIME
                                    | PERFCOUNTER_LONGRUNAVG, description))
        return self

    def create_perf_counters(self) -> PerfCounters:
        return self._pc


class PerfCountersCollection:
    """Process-wide registry dumped by `perf dump`."""

    def __init__(self):
        self._loggers: Dict[str, PerfCounters] = {}
        self._lock = DebugLock("PerfCountersCollection::lock")

    def add(self, pc: PerfCounters) -> None:
        with self._lock:
            self._loggers[pc.name] = pc

    def remove(self, pc: PerfCounters) -> None:
        with self._lock:
            self._loggers.pop(pc.name, None)

    def dump(self, logger: str = "", counter: str = ""
             ) -> Dict[str, Dict[str, object]]:
        with self._lock:
            out = {}
            for name, pc in self._loggers.items():
                if logger and name != logger:
                    continue
                d = pc.dump()
                if counter:
                    d = {k: v for k, v in d.items() if k == counter}
                out[name] = d
            return out
