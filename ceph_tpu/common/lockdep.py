"""lockdep — runtime lock-ordering checker (src/common/lockdep.cc role).

The reference registers every named mutex and records, per acquisition,
which locks the thread already holds; observing A-before-B and later
B-before-A is a potential deadlock and aborts with both backtraces.
This is the same design over ``threading``: ``DebugLock`` wraps a lock
with a name, a global order graph accumulates (holder -> acquired)
edges, and an inversion raises ``LockOrderError`` with the two orders'
stacks.  Enabled via ``lockdep_enable()`` (tests / vstart-style debug
runs — the reference gates it behind the ``lockdep`` option too,
src/vstart.sh); disabled it costs one attribute check per acquire.
"""
from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

_enabled = False
_registry_lock = threading.Lock()
# (before, after) -> formatted stack that first established the order
_orders: Dict[Tuple[str, str], str] = {}
_held = threading.local()


class LockOrderError(RuntimeError):
    pass


def lockdep_enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def lockdep_reset() -> None:
    with _registry_lock:
        _orders.clear()


def _held_stack() -> List[str]:
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


def _reaches(src: str, dst: str) -> Optional[str]:
    """First recorded stack on a path src ->* dst in the order graph
    (the reference lockdep's recursive ``does_follow`` check)."""
    seen = {src}
    frontier = [src]
    while frontier:
        cur = frontier.pop()
        for (a, b), stack in _orders.items():
            if a != cur or b in seen:
                continue
            if b == dst:
                return stack
            seen.add(b)
            frontier.append(b)
    return None


def _will_lock(name: str) -> None:
    held = _held_stack()
    if not held:
        return
    # the stack string only matters the FIRST time an edge is
    # recorded; format it lazily so steady-state nested acquires
    # (every edge already known) skip the traceback walk — this runs
    # on the per-op hot path of every witness-armed daemon
    stack = None
    with _registry_lock:
        for h in held:
            if h == name:
                raise LockOrderError(f"recursive acquire of {name!r}")
            # transitive check: any existing name ->* h path plus the
            # new h -> name edge closes a cycle
            prior = _reaches(name, h)
            if prior is not None:
                raise LockOrderError(
                    f"lock order inversion: acquiring {name!r} while "
                    f"holding {h!r}, but an order {name!r} ->* {h!r} "
                    f"was established here:\n{prior}")
            if (h, name) not in _orders:
                if stack is None:
                    stack = "".join(traceback.format_stack(limit=8)[:-2])
                _orders[(h, name)] = stack


class DebugLock:
    """Named lock participating in ordering checks when lockdep is on.

    Also implements the ``threading.Condition`` owner protocol
    (``_release_save`` / ``_acquire_restore`` / ``_is_owned``) so a
    ``Condition(DebugLock(...))`` wait/notify round keeps the held
    stack honest instead of tripping a false recursive-acquire via
    Condition's default ``acquire(False)`` ownership probe.
    """

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()  # lint: allow[no-bare-lock]

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _enabled:
            _will_lock(self.name)
        got = self._lock.acquire(blocking, timeout)
        if got and _enabled:
            _held_stack().append(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        # pop the held stack even when the witness is off: a lock
        # acquired while enabled and released after lockdep_enable(False)
        # must not strand its name (a later re-enable would see a
        # phantom hold and report a false recursive acquire)
        st = _held_stack()
        if self.name in st:
            st.remove(self.name)

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    # ---- threading.Condition owner protocol ---------------------------
    def _release_save(self):
        self.release()

    def _acquire_restore(self, _state) -> None:
        self.acquire()

    def _is_owned(self) -> bool:
        if _enabled and self.name in _held_stack():
            return True
        # Condition's stock probe, against the RAW lock so lockdep
        # never sees it as an ordering event
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True


class DebugRLock:
    """Named reentrant lock under the same witness.

    Same-instance re-acquisition by the owning thread is legal RLock
    semantics and records no ordering event; only the OUTERMOST
    acquire/release participates in the order graph, exactly like the
    reference's recursive ``ceph::make_recursive_mutex`` registration.
    """

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.RLock()  # lint: allow[no-bare-lock]
        self._owner: int = 0
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        outermost = self._owner != me
        if _enabled and outermost:
            _will_lock(self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            # safe unlocked writes: we hold the lock
            self._owner = me
            self._count += 1
            if _enabled and outermost:
                _held_stack().append(self.name)
        return got

    def release(self) -> None:
        outermost = self._count == 1
        self._count -= 1
        if self._count == 0:
            self._owner = 0
        self._lock.release()
        # see DebugLock.release: unconditional so toggling the witness
        # mid-hold can never strand a held-stack entry
        if outermost:
            st = _held_stack()
            if self.name in st:
                st.remove(self.name)

    def __enter__(self) -> "DebugRLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()
