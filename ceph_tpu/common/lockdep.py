"""lockdep — runtime lock-ordering checker (src/common/lockdep.cc role).

The reference registers every named mutex and records, per acquisition,
which locks the thread already holds; observing A-before-B and later
B-before-A is a potential deadlock and aborts with both backtraces.
This is the same design over ``threading``: ``DebugLock`` wraps a lock
with a name, a global order graph accumulates (holder -> acquired)
edges, and an inversion raises ``LockOrderError`` with the two orders'
stacks.  Enabled via ``lockdep_enable()`` (tests / vstart-style debug
runs — the reference gates it behind the ``lockdep`` option too,
src/vstart.sh); disabled it costs one attribute check per acquire.
"""
from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

_enabled = False
_registry_lock = threading.Lock()
# (before, after) -> formatted stack that first established the order
_orders: Dict[Tuple[str, str], str] = {}
_held = threading.local()


class LockOrderError(RuntimeError):
    pass


def lockdep_enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def lockdep_reset() -> None:
    with _registry_lock:
        _orders.clear()


def _held_stack() -> List[str]:
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


def _reaches(src: str, dst: str) -> Optional[str]:
    """First recorded stack on a path src ->* dst in the order graph
    (the reference lockdep's recursive ``does_follow`` check)."""
    seen = {src}
    frontier = [src]
    while frontier:
        cur = frontier.pop()
        for (a, b), stack in _orders.items():
            if a != cur or b in seen:
                continue
            if b == dst:
                return stack
            seen.add(b)
            frontier.append(b)
    return None


def _will_lock(name: str) -> None:
    held = _held_stack()
    if not held:
        return
    stack = "".join(traceback.format_stack(limit=8)[:-2])
    with _registry_lock:
        for h in held:
            if h == name:
                raise LockOrderError(f"recursive acquire of {name!r}")
            # transitive check: any existing name ->* h path plus the
            # new h -> name edge closes a cycle
            prior = _reaches(name, h)
            if prior is not None:
                raise LockOrderError(
                    f"lock order inversion: acquiring {name!r} while "
                    f"holding {h!r}, but an order {name!r} ->* {h!r} "
                    f"was established here:\n{prior}")
            _orders.setdefault((h, name), stack)


class DebugLock:
    """Named lock participating in ordering checks when lockdep is on."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _enabled:
            _will_lock(self.name)
        got = self._lock.acquire(blocking, timeout)
        if got and _enabled:
            _held_stack().append(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        if _enabled:
            st = _held_stack()
            if self.name in st:
                st.remove(self.name)

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()
