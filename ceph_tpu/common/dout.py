"""Leveled per-subsystem debug logging — the dout/ldout analog.

Mirrors the reference's logging split (common/dout.h macros +
log/Log.cc collector):

- every subsystem has a pair of levels ``log/gather`` (common/subsys.h):
  entries at or below *gather* are collected into a bounded in-memory
  ring (log/Log.cc m_recent, default 10000), and the subset at or below
  *log* goes to the sink (file/stderr).  The ring makes recent low-level
  detail available after the fact ("log dump" on the admin socket —
  the reference dumps it on crash) without paying the IO for it.
- levels are runtime-tunable per subsystem via config options
  ``debug_<subsys> = "log/gather"`` with observer-driven updates
  (md_config_t observers, common/config_obs.h).

Python-idiomatic surface: module-level ``dlog(subsys, level, msg)``
plus per-owner ``Dout`` handles that carry the ``who`` prefix.  The
disabled path is one dict lookup and an int compare.
"""
from __future__ import annotations

import sys
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

# default log/gather per subsystem (subset of common/subsys.h with the
# reference's "1/5"-style defaults)
SUBSYS_DEFAULTS: Dict[str, Tuple[int, int]] = {
    "osd": (1, 5),
    "mon": (1, 5),
    "pg": (1, 5),
    "crush": (1, 1),
    "ec": (1, 5),       # the reference's "osd" covers ECBackend; split out
    "msg": (0, 5),
    "client": (0, 5),
    "recovery": (1, 5),
    "scrub": (1, 5),
    "config": (0, 5),
}

MAX_RECENT = 10000      # log/Log.cc m_max_recent default


class LogEntry:
    __slots__ = ("stamp", "subsys", "level", "who", "msg")

    def __init__(self, stamp: float, subsys: str, level: int, who: str,
                 msg: str):
        self.stamp = stamp
        self.subsys = subsys
        self.level = level
        self.who = who
        self.msg = msg

    def format(self) -> str:
        return (f"{self.stamp:.6f} {self.who or '-'} "
                f"{self.level:2d} {self.subsys}: {self.msg}")


class Log:
    """The collector: bounded recent ring + optional sink."""

    def __init__(self):
        self.levels: Dict[str, Tuple[int, int]] = dict(SUBSYS_DEFAULTS)
        self.recent: Deque[LogEntry] = deque(maxlen=MAX_RECENT)
        self.sink = None                 # file object or None
        self.stderr_level = -1           # also mirror <= this to stderr

    # ---- levels -----------------------------------------------------------
    def set_level(self, subsys: str, log_level: int,
                  gather_level: Optional[int] = None) -> None:
        if gather_level is None:
            gather_level = max(log_level,
                               self.levels.get(subsys, (0, 5))[1])
        self.levels[subsys] = (log_level, gather_level)

    def parse_level(self, subsys: str, spec: str) -> None:
        """"3" or "3/10" like the reference's debug_<subsys> values."""
        parts = str(spec).split("/")
        lg = int(parts[0])
        gt = int(parts[1]) if len(parts) > 1 else lg
        self.levels[subsys] = (lg, max(lg, gt))

    def gather_level(self, subsys: str) -> int:
        return self.levels.get(subsys, (0, 0))[1]

    # ---- submission -------------------------------------------------------
    def submit(self, subsys: str, level: int, who: str, msg: str) -> None:
        lg, gt = self.levels.get(subsys, (0, 0))
        if level > gt:
            return
        e = LogEntry(time.time(), subsys, level, who, msg)
        self.recent.append(e)
        if level <= lg and self.sink is not None:
            self.sink.write(e.format() + "\n")
        if level <= self.stderr_level:
            sys.stderr.write(e.format() + "\n")

    # ---- draining ---------------------------------------------------------
    def dump_recent(self, n: int = 0, subsys: str = "") -> List[str]:
        entries = [e for e in self.recent
                   if not subsys or e.subsys == subsys]
        if n:
            entries = entries[-n:]
        return [e.format() for e in entries]

    def open_file(self, path: str) -> None:
        self.sink = open(path, "a")

    def flush(self) -> None:
        if self.sink is not None:
            self.sink.flush()

    def clear(self) -> None:
        self.recent.clear()


_log = Log()


def get_log() -> Log:
    return _log


def dlog(subsys: str, level: int, msg: str, who: str = "") -> None:
    """The dout(level) << ... analog; cheap when gathered off."""
    lv = _log.levels.get(subsys)
    if lv is None or level > lv[1]:
        return
    _log.submit(subsys, level, who, msg)


class Dout:
    """Per-owner handle carrying the ``who`` prefix (each daemon's
    dout context)."""

    def __init__(self, subsys: str, who: str):
        self.subsys = subsys
        self.who = who

    def __call__(self, level: int, msg: str) -> None:
        dlog(self.subsys, level, msg, self.who)

    def enabled(self, level: int) -> bool:
        return level <= _log.gather_level(self.subsys)


def register_config_observers(config) -> None:
    """Wire debug_<subsys> config options to live level updates
    (``ceph tell ... injectargs --debug-osd 20`` behavior)."""
    for subsys in list(_log.levels):
        config.add_observer(f"debug_{subsys}",
                            lambda _n, v, _s=subsys:
                            _log.parse_level(_s, v))
    from .kernel_trace import g_kernel_timer
    config.add_observer("tracing_kernels",
                        lambda _n, v: g_kernel_timer.enable(bool(v)))
    from ..trace import g_tracer
    config.add_observer("tracing_spans",
                        lambda _n, v: g_tracer.enable(bool(v)))
