"""OpTracker — in-flight op tracking and historic-op tracing.

Mirrors the reference's op latency surface (src/common/TrackedOp.cc +
the blkin trace slot on every Message, msg/Message.h:254): each op carries
one trace id end to end, records named events with timestamps, and
completed ops land in a bounded history ring dumped via the admin socket
(`dump_historic_ops`, `dump_ops_in_flight`).
"""
from __future__ import annotations

import threading

from .lockdep import DebugLock
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple


class TrackedOp:
    def __init__(self, tracker: "OpTracker", trace_id: int,
                 description: str):
        self.tracker = tracker
        self.trace_id = trace_id
        self.description = description
        self.initiated_at = tracker.now()
        self.events: List[Tuple[float, str]] = []
        self.completed_at: Optional[float] = None
        # observability hooks: the daemon's span for this op (set by the
        # dispatch path when the tracer is on), the flight-recorder
        # entry pinning its span tree once the op proves slow, and the
        # always-on stage-latency ledger (trace/oplat.py) — pinned by
        # reference like the span objects, so a slow op's per-stage
        # breakdown survives without re-running anything
        self.span = None
        self.flight = None
        self.oplat = None

    def mark_event(self, event: str) -> None:
        self.events.append((self.tracker.now(), event))

    def finish(self) -> None:
        self.completed_at = self.tracker.now()
        self.tracker._complete(self)

    @property
    def duration(self) -> float:
        end = self.completed_at if self.completed_at is not None \
            else self.tracker.now()
        return end - self.initiated_at

    def dump(self) -> dict:
        return {
            "description": self.description,
            "trace_id": self.trace_id,
            "initiated_at": self.initiated_at,
            "age": self.duration,
            "type_data": {
                "events": [{"time": t, "event": e}
                           for t, e in self.events],
            },
        }


class OpTracker:
    def __init__(self, history_size: int = 20,
                 history_duration: float = 600.0,
                 clock=time.monotonic, name: str = ""):
        self.history_size = history_size
        self.history_duration = history_duration
        # daemon name for the event journal; empty = generic "osd"
        self.name = name
        self.now = clock
        self._inflight: Dict[int, TrackedOp] = {}
        self._history: Deque[TrackedOp] = deque(maxlen=history_size)
        self._slow: Deque[TrackedOp] = deque(maxlen=history_size)
        self._lock = DebugLock("OpTracker::lock")
        self._complaint_override: Optional[float] = None

    @property
    def complaint_time(self) -> float:
        """Live view of op_complaint_time: a runtime `config set` (or
        injectargs) takes effect on the next completion — no observer
        plumbing per tracker instance needed.  Direct assignment (tests,
        embedders) pins an explicit override."""
        if self._complaint_override is not None:
            return self._complaint_override
        from .config import g_conf
        return float(g_conf.get_val("op_complaint_time"))

    @complaint_time.setter
    def complaint_time(self, v: float) -> None:
        self._complaint_override = float(v)

    def create_request(self, trace_id: int, description: str) -> TrackedOp:
        op = TrackedOp(self, trace_id, description)
        with self._lock:
            self._inflight[trace_id] = op
        op.mark_event("initiated")
        return op

    def _complete(self, op: TrackedOp) -> None:
        with self._lock:
            self._inflight.pop(op.trace_id, None)
            self._history.append(op)
            slow = op.duration > self.complaint_time
            if slow:
                self._slow.append(op)
        if slow:
            # flight-record the span tree NOW: ring eviction in the
            # collector must not be able to dismember a slow op's trace
            # before anyone dumps it.  Span objects are pinned by
            # reference, so spans still open here (the client's root)
            # close in place before a later dump reads them.
            from ..trace import g_flight_recorder, g_tracer
            if g_tracer.enabled and op.trace_id:
                spans = g_tracer.collector.spans_for_trace(op.trace_id)
                if spans:
                    op.flight = g_flight_recorder.record(
                        op.trace_id, op.description, op.duration, spans)
            from ..trace.journal import g_journal
            g_journal.emit(self.name or "osd", "slow_op",
                           description=op.description,
                           duration=round(op.duration, 6))

    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = [o.dump() for o in self._inflight.values()]
        return {"ops": ops, "num_ops": len(ops)}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            ops = [o.dump() for o in self._history]
        return {"size": self.history_size,
                "duration": self.history_duration, "ops": ops}

    def dump_historic_slow_ops(self) -> dict:
        """Slow ops with their flight-recorded span trees (the
        reference's dump_historic_slow_ops, grown the ZTracer view)."""
        with self._lock:
            ops = list(self._slow)
        out = []
        for o in ops:
            d = o.dump()
            if o.flight is not None:
                d["span_tree"] = o.flight.tree()
            if o.oplat is not None:
                # which stage ate the budget — the always-on ledger is
                # already complete, no re-run or tracing required
                d["stage_ledger"] = o.oplat.dump()
            # which COPIES ate the budget: the devprof per-transfer
            # ledger rides the op's pinned spans as a tag, so slow-op
            # forensics shows bytes next to time
            copies: List[dict] = []
            spans = o.flight.spans if o.flight is not None \
                else ([o.span] if o.span is not None else [])
            for s in spans:
                copies.extend(s.tags.get("copy_ledger", ()))
            if spans:
                d["copy_ledger"] = copies
            out.append(d)
        return {"ops": out}

    @property
    def num_slow_ops(self) -> int:
        with self._lock:
            return len(self._slow)
