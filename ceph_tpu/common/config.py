"""Typed option registry + config proxy.

Mirrors the reference's central option table and md_config_t semantics
(src/common/options.cc — ~7k typed options with levels/defaults/
descriptions; src/common/config.{h,cc} with change observers): options are
declared once with type/default/description, values resolve
override → default, and observers get notified on runtime changes
(`ceph tell ... injectargs` analog).  Only the options this framework
actually consumes are declared; the mechanism matches.
"""
from __future__ import annotations

import configparser
from typing import Any, Callable, Dict, List, Optional

OPT_INT = "int"
OPT_STR = "str"
OPT_FLOAT = "float"
OPT_DOUBLE = "double"
OPT_BOOL = "bool"

LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"


class Option:
    def __init__(self, name: str, type: str, level: str = LEVEL_ADVANCED):
        self.name = name
        self.type = type
        self.level = level
        self.default: Any = None
        self.description = ""
        self.long_description = ""

    def set_default(self, v) -> "Option":
        self.default = v
        return self

    def set_description(self, d: str) -> "Option":
        self.description = d
        return self

    def set_long_description(self, d: str) -> "Option":
        self.long_description = d
        return self

    def cast(self, v):
        if self.type == OPT_INT:
            return int(v)
        if self.type in (OPT_FLOAT, OPT_DOUBLE):
            return float(v)
        if self.type == OPT_BOOL:
            return v if isinstance(v, bool) \
                else str(v).lower() in ("true", "1", "yes", "on")
        return str(v)


def _subsys_defaults():
    from .dout import SUBSYS_DEFAULTS
    return sorted(SUBSYS_DEFAULTS.items())


def build_options() -> List[Option]:
    """The option table (subset of src/common/options.cc this build uses)."""
    return [
        Option("osd_pool_default_size", OPT_INT).set_default(3)
        .set_description("the number of copies of an object"),
        Option("osd_pool_default_min_size", OPT_INT).set_default(0)
        .set_description("minimum replicas before a write is acked"),
        Option("osd_pool_default_pg_num", OPT_INT).set_default(32)
        .set_description("number of PGs for new pools"),
        Option("osd_pool_erasure_code_stripe_unit", OPT_INT)
        .set_default(4096)
        .set_description("stripe unit (bytes) for EC pool chunks"),
        Option("osd_heartbeat_interval", OPT_FLOAT).set_default(6.0)
        .set_description("seconds between peer heartbeats"),
        Option("osd_heartbeat_grace", OPT_FLOAT).set_default(20.0)
        .set_description("seconds of silence before reporting a peer"),
        Option("osd_erasure_code_plugins", OPT_STR)
        .set_default("tpu isa jerasure lrc shec")
        .set_description("EC plugins to preload at start"),
        Option("erasure_code_dir", OPT_STR).set_default("")
        .set_description("plugin directory (reference options.cc:311; "
                         "python registry needs none)"),
        Option("mon_max_pg_per_osd", OPT_INT).set_default(250),
        Option("crush_device_fast_mapper", OPT_BOOL).set_default(True)
        .set_description("use the device candidate-table CRUSH mapper"),
        Option("crush_fast_tries_cap", OPT_INT).set_default(4)
        .set_description("retries materialized on device before host "
                         "residual fallback"),
        Option("ec_device_batch", OPT_INT).set_default(64)
        .set_description("stripes per batched device encode call"),
        Option("ec_dispatch_batch_max", OPT_INT).set_default(64)
        .set_description("EC dispatch scheduler: requests per codec "
                         "signature that trigger an immediate coalesced "
                         "flush (ceph_tpu/dispatch)"),
        Option("ec_dispatch_batch_window_us", OPT_INT).set_default(0)
        .set_description("EC dispatch scheduler: collection window in "
                         "microseconds before a queued request's batch "
                         "flushes; 0 = exact passthrough to the "
                         "uncoalesced per-op device call"),
        Option("ec_dispatch_queue_max", OPT_INT).set_default(1024)
        .set_description("EC dispatch scheduler: total pending requests "
                         "across all queues before a forced "
                         "backpressure flush"),
        Option("ec_mesh_chips", OPT_INT).set_default(0)
        .set_description("devices in the dispatch mesh runtime "
                         "(ceph_tpu/mesh): flushed encode batches "
                         "shard their stripe rows across a 1-D batch-"
                         "axis mesh of this many chips.  0 = mesh off "
                         "(single-device dispatch, the existing path "
                         "by construction); -1 = all addressable "
                         "devices; N > 1 = the first N (clamped to "
                         "what the process can see)"),
        Option("ec_mesh_pool_buffers", OPT_INT).set_default(4)
        .set_description("padded staging buffers the mesh runtime "
                         "retains per batch shape for reuse across "
                         "flushes (ceph_tpu/mesh/pool)"),
        Option("ec_mesh_donate", OPT_BOOL).set_default(True)
        .set_description("donate the sharded batch buffer to the mesh "
                         "encode (donate_argnums) so the device "
                         "recycles it into the output; ignored on "
                         "backends without buffer aliasing (cpu)"),
        Option("ec_mesh_rateless", OPT_BOOL).set_default(False)
        .set_description("rateless coded mesh encode "
                         "(ceph_tpu/mesh/rateless): over-decompose "
                         "each flushed encode batch into more coded "
                         "row-blocks than chips and complete the "
                         "flush from the FIRST sufficient subset of "
                         "chips — a slow or dead chip costs "
                         "bandwidth, never latency.  Off (default) = "
                         "the block-sharded SPMD mesh path"),
        Option("ec_mesh_rateless_tasks", OPT_INT).set_default(0)
        .set_description("total coded row-blocks per rateless mesh "
                         "flush (systematic blocks — one per chip — "
                         "plus GF(2^8) random-combination parity "
                         "blocks).  0 = auto (mesh size + 2 parity "
                         "blocks); values are clamped to at least "
                         "mesh size + 1 so every flush carries "
                         "redundancy"),
        Option("ec_mesh_skew_sample_every", OPT_INT).set_default(16)
        .set_description("sampled per-chip skew probes: every Nth mesh "
                         "flush drains one element per chip shard and "
                         "records per-chip completion deltas on the "
                         "chip-health scoreboard "
                         "(ceph_tpu/mesh/chipstat).  0 = probing off; "
                         "the OSD tick additionally guarantees the "
                         "next flush after quiet traffic probes "
                         "(cadence floor)"),
        Option("ec_mesh_skew_threshold", OPT_FLOAT).set_default(3.0)
        .set_description("per-chip probe service time over the mesh "
                         "median at or above this ratio on 3 "
                         "consecutive probes marks the chip SUSPECT "
                         "(clears after 3 clean probes) and raises "
                         "TPU_MESH_SKEW; <= 0 disables the "
                         "scoreboard verdicts (probes still record)"),
        Option("chaos_storyline_legs_max", OPT_INT).set_default(3)
        .set_description("composed-chaos scenario engine "
                         "(ceph_tpu/chaos): maximum primitive legs "
                         "one seeded storyline samples on top of its "
                         "always-on traffic phase; read at compose "
                         "time, so runtime changes shape the NEXT "
                         "composed scenario"),
        Option("chaos_settle_ticks_max", OPT_INT).set_default(64)
        .set_description("composed-chaos settle budget: mgr ticks "
                         "(with synthetic clean flushes in between) "
                         "the engine grants every expected health "
                         "check to clear after its fault is disarmed "
                         "before declaring the scenario WEDGED"),
        Option("ec_pipeline_depth", OPT_INT).set_default(1)
        .set_description("EC write pipeline: encodes a single PG may "
                         "keep in flight in the dispatch scheduler "
                         "before backpressure force-flushes "
                         "(osd/ec_backend).  1 = today's synchronous "
                         "submit->encode->fan-out per op; >1 converts "
                         "the write path to non-blocking dispatch "
                         "futures with continuation fan-out"),
        Option("ec_subwrite_retry_timeout", OPT_FLOAT).set_default(3.0)
        .set_description("seconds before an unacked EC sub-op write is "
                         "resent to its shard (messenger-level drops "
                         "no longer wedge the per-oid write pipeline); "
                         "0 disables the resend timer"),
        Option("ec_subwrite_retry_max", OPT_INT).set_default(6)
        .set_description("resend attempts per in-flight EC sub-op "
                         "write before giving up (peering's on_change "
                         "then owns the cleanup, as before the timer)"),
        Option("ec_device_retry_max", OPT_INT).set_default(2)
        .set_description("retries (after the first attempt) for a "
                         "transient device codec-call failure before "
                         "the call degrades to the CPU matrix path "
                         "(ceph_tpu/fault guard)"),
        Option("ec_device_retry_backoff_us", OPT_INT).set_default(200)
        .set_description("base backoff between device-call retries, "
                         "doubled per attempt (exponential)"),
        Option("ec_device_watchdog_ms", OPT_FLOAT).set_default(0.0)
        .set_description("per-call watchdog deadline for device codec "
                         "calls; a call exceeding it counts as a "
                         "failure (result discarded).  0 = disabled"),
        Option("ec_breaker_threshold", OPT_INT).set_default(3)
        .set_description("consecutive device-call failures that trip a "
                         "codec signature's circuit breaker onto the "
                         "CPU path (TPU_CODEC_DEGRADED)"),
        Option("ec_breaker_cooldown_s", OPT_FLOAT).set_default(30.0)
        .set_description("seconds an open breaker refuses the device "
                         "before half-open probing it to auto-restore"),
        Option("os_memstore_device_bytes_max", OPT_INT).set_default(0)
        .set_description("device-resident shard store byte budget "
                         "(os_store/device_shard): > 0 lets the EC "
                         "write path store encoded shard bodies as "
                         "HBM handles (zero d2h on the encode->store "
                         "path, crc fused into the encode kernel) and "
                         "LRU-demotes the coldest resident shards to "
                         "host bytes past the budget.  0 (default) = "
                         "residency off, host-bytes store by "
                         "construction"),
        Option("osd_recovery_repair_reads", OPT_BOOL).set_default(True)
        .set_description("repair a single lost shard of a "
                         "regenerating-code pool from d sub-chunk "
                         "helper contributions instead of k whole "
                         "chunks (ceph_tpu/recovery; off = always "
                         "full-stripe decode)"),
        Option("osd_recovery_max_active", OPT_INT).set_default(8)
        .set_description("sub-chunk repair rounds in flight per OSD; "
                         "excess rounds park and drain as slots free "
                         "(reference osd_recovery_max_active role)"),
        Option("ec_regen_subchunk_unit", OPT_INT).set_default(512)
        .set_description("default sub-chunk width (bytes) for "
                         "regenerating-code pools whose profile omits "
                         "subchunk=; stripe width is B x unit, stored "
                         "chunk alpha x unit per stripe "
                         "(docs/RECOVERY.md)"),
        Option("osd_scrub_min_interval", OPT_FLOAT).set_default(86400.0)
        .set_description("seconds between periodic background scrubs "
                         "of a PG (reference osd_scrub_min_interval)"),
        Option("osd_scrub_auto", OPT_BOOL).set_default(True)
        .set_description("schedule background scrubs from the OSD tick"),
        Option("osd_deep_scrub_interval", OPT_FLOAT).set_default(604800.0)
        .set_description("seconds between deep (data-checksumming) "
                         "scrubs of a PG; shallow scrubs in between "
                         "compare metadata only (reference "
                         "osd_deep_scrub_interval)"),
        Option("osd_op_num_threads", OPT_INT).set_default(0)
        .set_description("worker threads draining the sharded op queue "
                         "(reference osd_op_num_threads_per_shard x "
                         "shards; 0 = drain synchronously)"),
        Option("osd_op_queue_mclock_wall", OPT_BOOL).set_default(False)
        .set_description("enforce mclock reservation/limit in ops per "
                         "REAL second (src/dmclock role) instead of "
                         "the deterministic virtual clock"),
        Option("osd_mclock_client_reservation", OPT_FLOAT)
        .set_default(0.0)
        .set_description("per-client dmClock reservation inside the "
                         "client op class, in ops per 1000 client-tier "
                         "dequeues (docs/QOS.md); 0 = no floor"),
        Option("osd_mclock_client_weight", OPT_FLOAT).set_default(1.0)
        .set_description("per-client dmClock weight inside the client "
                         "op class: backlogged clients share dequeues "
                         "proportionally to their weights"),
        Option("osd_mclock_client_limit", OPT_FLOAT).set_default(0.0)
        .set_description("per-client dmClock limit inside the client "
                         "op class, in ops per 1000 client-tier "
                         "dequeues; 0 = uncapped"),
        Option("osd_mclock_client_overrides", OPT_STR).set_default("")
        .set_description("per-entity (res, weight, limit) overrides: "
                         "'entity:res:weight:limit[,entity:...]' — "
                         "entities not listed use the "
                         "osd_mclock_client_* defaults"),
        Option("osd_mclock_class_overrides", OPT_STR).set_default("")
        .set_description("class-tier dmClock tag overrides: "
                         "'class:res:weight:limit[,class:...]' over "
                         "the op classes (client, recovery, scrub, "
                         "snaptrim) — layered over the constructor "
                         "tags at every arbitration, so injectargs "
                         "re-weights a running queue (docs/QOS.md; "
                         "the control plane's recovery-vs-client "
                         "actuator)"),
        Option("osd_op_queue_admission_max", OPT_INT).set_default(0)
        .set_description("op-queue depth at which client-op intake "
                         "sheds load: new client ops are answered "
                         "EAGAIN with a retry_after hint instead of "
                         "growing the queue (docs/QOS.md admission "
                         "control); 0 = disabled"),
        Option("osd_op_queue_throttle_window", OPT_FLOAT)
        .set_default(0.0)
        .set_description("seconds a shed client stays throttled after "
                         "tripping admission control (on top of the "
                         "depth hysteresis: a throttled client is "
                         "re-admitted only once the queue drains below "
                         "half of osd_op_queue_admission_max); also "
                         "the retry_after hint floor sent to clients"),
        Option("osd_op_queue_batch_intake", OPT_BOOL).set_default(False)
        .set_description("do not drain the sharded op queue inline at "
                         "every intake: ops accumulate across one fabric "
                         "pump and drain at quiescence, so bursts see "
                         "real mClock arbitration (the traffic "
                         "harness's intake mode; default preserves "
                         "the synchronous drain)"),
        Option("osd_capacity_bytes", OPT_INT).set_default(0)
        .set_description("logical capacity per OSD for full-ratio "
                         "accounting (osd_stat_t kb role); 0 = "
                         "unlimited, never full"),
        Option("mon_osd_full_ratio", OPT_FLOAT).set_default(0.95)
        .set_description("OSD fill ratio at which the cluster FULL "
                         "flag blocks writes (common/options.cc "
                         "mon_osd_full_ratio)"),
        Option("mon_osd_nearfull_ratio", OPT_FLOAT).set_default(0.85)
        .set_description("OSD fill ratio raising the NEARFULL health "
                         "warning (mon_osd_nearfull_ratio)"),
        Option("mgr_telemetry_retention", OPT_INT).set_default(360)
        .set_description("samples kept in the mgr telemetry rollup's "
                         "time-series rings (one sample per mgr tick; "
                         "keep >= mgr_slo_slow_window_s / tick dt or "
                         "the slow burn window silently truncates to "
                         "the ring span; docs/OBSERVABILITY.md "
                         "cluster rollup)"),
        Option("mgr_slo_fast_window_s", OPT_FLOAT).set_default(30.0)
        .set_description("fast SLO burn-rate window (seconds of the "
                         "cluster clock) — the responsive window a "
                         "breach must sustain in before a TPU_SLO_* "
                         "health check raises"),
        Option("mgr_slo_slow_window_s", OPT_FLOAT).set_default(300.0)
        .set_description("slow SLO burn-rate window (seconds) — the "
                         "confirming window; a spike that breaches "
                         "the fast window but dilutes below the "
                         "objective here never raises"),
        Option("mgr_slo_sustain_ticks", OPT_INT).set_default(2)
        .set_description("consecutive mgr ticks the fast-window burn "
                         "must breach before a TPU_SLO_* check "
                         "raises (a single-tick spike never flaps it)"),
        Option("mgr_slo_clear_ticks", OPT_INT).set_default(2)
        .set_description("consecutive clean mgr ticks before an "
                         "active TPU_SLO_* check clears (hysteresis)"),
        Option("mgr_slo_oplat_p99_usec", OPT_STR).set_default("")
        .set_description("per-stage cluster-p99 latency objectives, "
                         "'stage:usec[,stage:usec]' over the oplat "
                         "stage catalog (e.g. 'device_call:50000,"
                         "class_queue:100000'); breaching raises "
                         "TPU_SLO_OPLAT.  Empty = disabled"),
        Option("mgr_slo_copies_per_op_max", OPT_FLOAT).set_default(0.0)
        .set_description("cluster copies-per-op ceiling (devprof "
                         "transfers + host copies over completed "
                         "ops); breaching raises TPU_SLO_COPY — the "
                         "bench copy budget as live health.  0 = "
                         "disabled"),
        Option("mgr_slo_admission_rate_max", OPT_FLOAT).set_default(0.0)
        .set_description("admission-control rejection-rate ceiling "
                         "(rejections per second of the cluster "
                         "clock); breaching raises TPU_SLO_ADMISSION."
                         "  0 = disabled"),
        Option("mgr_control_enable", OPT_BOOL).set_default(False)
        .set_description("master enable for the mgr's damped SLO "
                         "feedback controller (docs/CONTROL.md); off "
                         "= today's observe-only mgr by construction "
                         "— the controller never senses, moves, or "
                         "logs"),
        Option("mgr_control_bounds", OPT_STR).set_default("")
        .set_description("operator floors/ceilings per controlled "
                         "knob, 'knob:floor:ceiling[,knob:...]' — "
                         "layered over the built-in bounds; the "
                         "controller never steps a knob outside "
                         "[floor, ceiling] (docs/CONTROL.md)"),
        Option("mgr_control_cooldown_ticks", OPT_INT).set_default(2)
        .set_description("mgr ticks a knob rests after an actuation "
                         "before the controller may step it again — "
                         "one bounded step per cooldown window makes "
                         "oscillation structurally impossible"),
        Option("mgr_control_damping", OPT_FLOAT).set_default(0.5)
        .set_description("geometric step damping: each successive "
                         "same-direction move on a knob scales its "
                         "step by this factor (0 < d <= 1), so a "
                         "persistent breach converges instead of "
                         "slamming between bounds"),
        Option("mgr_control_ledger_size", OPT_INT).set_default(128)
        .set_description("actuation-ledger ring size ('tpu control "
                         "dump'): every move keeps knob, from/to, "
                         "reflex and reason until overwritten"),
        Option("mgr_control_actuate_retries", OPT_INT).set_default(2)
        .set_description("bounded re-attempts of one actuation within "
                         "a tick when the config injection fails "
                         "(fault site control.actuate); past the "
                         "budget the move is dropped and retried "
                         "whole next tick — the controller never "
                         "wedges"),
        Option("mgr_journal_ring_size", OPT_INT).set_default(256)
        .set_description("events kept per daemon in the cluster event "
                         "journal's bounded rings (trace/journal.py); "
                         "read live on every emit, so an injectargs "
                         "shrink evicts down on the next event"),
        Option("mgr_incident_retention", OPT_INT).set_default(16)
        .set_description("incident bundles kept in the mgr's archive "
                         "(mgr/incident.py); a runtime shrink prunes "
                         "the archive immediately via the config "
                         "observer, oldest bundles first"),
        Option("mgr_incident_timeline_tail", OPT_INT).set_default(64)
        .set_description("merged-timeline events snapshotted into an "
                         "incident bundle at capture, and again per "
                         "finalize when the triggering check clears"),
        Option("tracing_kernels", OPT_BOOL).set_default(False)
        .set_description("time every device kernel dispatch (adds a "
                         "sync per call; diagnosis only)"),
        Option("tracing_spans", OPT_BOOL).set_default(False)
        .set_description("collect parent/child op spans end to end "
                         "(trace/ package; host-side only, adds zero "
                         "device syncs — safe to leave on)"),
        Option("op_complaint_time", OPT_FLOAT).set_default(30.0)
        .set_description("ops slower than this land in the slow-op "
                         "history + flight recorder (reference "
                         "osd_op_complaint_time, options.cc)"),
        # daemon-identity path options (reference options.cc defaults,
        # with the same $cluster/$name metavariables -- ceph-conf
        # expands them per name; pinned by src/test/cli/ceph-conf)
        Option("log_file", OPT_STR, LEVEL_BASIC)
        .set_default("/var/log/ceph/$cluster-$name.log")
        .set_description("path to log file"),
        Option("admin_socket", OPT_STR)
        .set_default("/var/run/ceph/$cluster-$name.asok")
        .set_description("path for the runtime control socket"),
        Option("mon_debug_dump_location", OPT_STR)
        .set_default("/var/log/ceph/$cluster-$name.tdump")
        .set_description("file to dump paxos transactions to"),
        Option("fsid", OPT_STR, LEVEL_BASIC).set_default("")
        .set_description("cluster fsid (uuid)"),
        Option("mon_host", OPT_STR, LEVEL_BASIC).set_default("")
        .set_description("list of hosts or addresses for monitors"),
        Option("public_network", OPT_STR).set_default("")
        .set_description("network(s) for public traffic"),
        Option("pid_file", OPT_STR).set_default("")
        .set_description("path to write the daemon's pid to"),
        Option("host", OPT_STR, LEVEL_BASIC).set_default("")
        .set_description("local hostname"),
        # debug_<subsys> levels, "log" or "log/gather" — one schema entry
        # per dout subsystem (single source of truth: SUBSYS_DEFAULTS)
        *[Option(f"debug_{s}", OPT_STR).set_default(f"{lg}/{gt}")
          .set_description(f"{s} debug level (log/gather)")
          for s, (lg, gt) in _subsys_defaults()],
    ]


class ConfigProxy:
    """md_config_t analog: values + observers."""

    def __init__(self):
        self.schema: Dict[str, Option] = {o.name: o for o in build_options()}
        self.values: Dict[str, Any] = {}
        self.observers: Dict[str, List[Callable[[str, Any], None]]] = {}

    def get_val(self, name: str):
        if name in self.values:
            return self.values[name]
        return self.schema[name].default

    def set_val(self, name: str, v) -> None:
        opt = self.schema[name]
        self.values[name] = opt.cast(v)
        for cb in self.observers.get(name, []):
            cb(name, self.values[name])

    def rm_val(self, name: str) -> None:
        self.values.pop(name, None)

    # the validated set/get path shared by every admin surface (asok
    # 'config set', 'ceph tell ... injectargs', MCommand handlers):
    # one place owns the schema check, cast-error wording, and
    # observer notification
    def set_checked(self, name: str, value) -> Dict[str, Any]:
        if name not in self.schema:
            raise ValueError(f"unrecognized config option '{name}'")
        try:
            self.set_val(name, value)
        except (TypeError, ValueError):
            raise ValueError(f"invalid value '{value}' for option "
                             f"'{name}'")
        return {name: self.get_val(name)}

    def get_checked(self, name: str) -> Dict[str, Any]:
        if name not in self.schema:
            raise ValueError(f"unrecognized config option '{name}'")
        return {name: self.get_val(name)}

    def handle_config_command(self, cmd: str,
                              args: Dict[str, Any]
                              ) -> Optional[Dict[str, Any]]:
        """The config subset of the daemon-command vocabulary
        ('ceph tell <daemon> ...'), shared by every MCommand handler:
        returns the reply data, or None when *cmd* is not a config
        command (the daemon adds its own).  injectargs validates
        EVERY name and value before applying anything — an error must
        mean nothing changed."""
        if cmd == "injectargs":
            opts = dict(args.get("opts", {}))
            for name, val in opts.items():
                if name not in self.schema:
                    raise ValueError(
                        f"unrecognized config option '{name}'")
                try:
                    self.schema[name].cast(val)
                except (TypeError, ValueError):
                    raise ValueError(f"invalid value '{val}' for "
                                     f"option '{name}'")
            out: Dict[str, Any] = {}
            for name, val in opts.items():
                out.update(self.set_checked(name, val))
            return out
        if cmd == "config show":
            return self.show_config()
        if cmd == "config get":
            return self.get_checked(args.get("name", ""))
        return None

    def run_daemon_command(self, cmd: str, args: Dict[str, Any],
                           extras: Dict[str, Callable[[], Any]]
                           ) -> "tuple[int, Dict[str, Any]]":
        """The full MCommand handler body shared by every daemon:
        config vocabulary first, then the daemon's *extras* (zero-arg
        callables by command name), with the reference's -EINVAL
        error shape.  Returns (result, data)."""
        try:
            handled = self.handle_config_command(cmd, args)
            if handled is not None:
                return 0, handled
            if cmd in extras:
                return 0, extras[cmd]()
            return -22, {"error": f"unknown command '{cmd}'"}
        except (TypeError, ValueError) as e:
            return -22, {"error": str(e)}

    def add_observer(self, name: str,
                     cb: Callable[[str, Any], None]) -> None:
        self.observers.setdefault(name, []).append(cb)

    def parse_ini(self, text: str, section: str = "global") -> None:
        """ceph.conf-style ini source."""
        cp = configparser.ConfigParser()
        cp.read_string(text)
        if cp.has_section(section):
            for k, v in cp.items(section):
                key = k.replace(" ", "_")
                if key in self.schema:
                    self.set_val(key, v)

    def show_config(self) -> Dict[str, Any]:
        return {name: self.get_val(name) for name in sorted(self.schema)}


# process-wide config, like g_conf
g_conf = ConfigProxy()
