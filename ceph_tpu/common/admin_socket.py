"""AdminSocket — runtime introspection commands.

Mirrors the reference's unix-socket JSON command surface
(src/common/admin_socket.{h,cc}; tests drive it as `ceph --admin-daemon
<sock> perf dump`): hooks register under a command prefix and return JSON.
In-process calls are the primary surface; `serve_unix()` optionally
exposes the same commands over a real unix socket (newline-delimited
command in, JSON out) for external tooling.
"""
from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
from typing import Callable, Dict, Optional

Hook = Callable[[str, Dict[str, str]], object]


class AdminSocket:
    def __init__(self):
        self._hooks: Dict[str, Hook] = {}
        self._help: Dict[str, str] = {}
        self._server: Optional[socketserver.ThreadingUnixStreamServer] = None
        self.register("help", lambda cmd, args: dict(self._help),
                      "list available commands")

    def register(self, command: str, hook: Hook, help: str = "") -> None:
        if command in self._hooks:
            raise KeyError(f"command {command!r} already registered")
        self._hooks[command] = hook
        self._help[command] = help

    def unregister(self, command: str) -> None:
        self._hooks.pop(command, None)
        self._help.pop(command, None)

    def execute(self, command: str, args: Optional[Dict[str, str]] = None):
        """Longest-prefix dispatch, like the reference's hook matching."""
        args = args or {}
        cand = command
        while cand:
            if cand in self._hooks:
                return self._hooks[cand](command, args)
            cand = cand.rsplit(" ", 1)[0] if " " in cand else ""
        raise KeyError(f"unknown command {command!r}")

    def execute_json(self, command: str,
                     args: Optional[Dict[str, str]] = None) -> str:
        try:
            return json.dumps(self.execute(command, args), default=str)
        except (KeyError, ValueError) as e:
            # hooks signal bad arguments with ValueError (e.g. config
            # set on an unknown option); socket clients must still get
            # a JSON reply, not a dropped connection
            return json.dumps({"error": str(e)})

    # ---- optional real unix socket ----------------------------------------
    def serve_unix(self, path: str) -> None:
        if os.path.exists(path):
            os.unlink(path)
        admin = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                line = self.rfile.readline().decode().strip()
                self.wfile.write(admin.execute_json(line).encode() + b"\n")

        self._server = socketserver.ThreadingUnixStreamServer(path, Handler)
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None
