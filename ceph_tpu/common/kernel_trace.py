"""Per-kernel device timing + profiler hooks — the tracing subsystem.

The reference instruments its hot paths with LTTng tracepoints
(src/tracing/*.tp, emitted from e.g. OSD.cc:6606) and threads one
ZTracer trace id through every op (msg/Message.h:254).  The TPU-native
equivalents here:

- ``KernelTimer``: named cumulative timing of device dispatches.  Off
  by default (timing forces a ``block_until_ready`` sync per call,
  which kills dispatch pipelining); flip on via config
  ``tracing_kernels`` or ``KernelTimer.enable()`` when diagnosing.
  Dumped over the admin socket ("kernel timings") next to perf
  counters — the "perf dump" of the device side.
- ``annotate(name)``: a jax.profiler.TraceAnnotation passthrough so
  framework phases show up named in a jax profiler trace (the
  tracepoint provider analog); harmless no-op when the profiler is
  inactive or jax is absent.
- trace ids: already carried end-to-end by every message
  (msg/messages.py new_trace_id), surfaced in OpTracker events.
"""
from __future__ import annotations

import contextlib
import threading

from .lockdep import DebugLock
import time
from typing import Any, Dict, Optional

# NOTE: ..trace imports back into common (span/histogram take their
# DebugLocks from common.lockdep), so g_tracer must resolve lazily
# or the two package __init__s deadlock on import order
_g_tracer = None


def _tracer():
    global _g_tracer
    if _g_tracer is None:
        from ..trace import g_tracer
        _g_tracer = g_tracer
    return _g_tracer


class KernelTimer:
    """Cumulative wall timing per named kernel.

    Thread-safe: concurrent OSD dispatch threads (osd_op_num_threads)
    record into the same stats dict, so the read-modify-write in
    ``_record`` runs under a lock — a lost sample would silently skew
    the very numbers this exists to make trustworthy.
    """

    def __init__(self):
        self.enabled = False
        self.stats: Dict[str, Dict[str, float]] = {}
        self._lock = DebugLock("KernelTrace::lock")

    def enable(self, on: bool = True) -> None:
        self.enabled = on

    @contextlib.contextmanager
    def time(self, name: str):
        """Time a host-side block (callers drain device values inside)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._record(name, time.perf_counter() - t0)

    def timed(self, name: str, fn, *args, **kw):
        """Call fn and drain its output: the one-shot instrumented
        dispatch used by the device backends when tracing is on.

        With the span tracer active this also emits a ``kernel:<name>``
        child span (and, when timing is on and a sync therefore exists,
        a ``device_drain`` child inside it) so device work shows up in
        the op's span tree.  The sync itself is still gated on
        ``self.enabled`` alone — spans never add one.
        """
        g_tracer = _tracer()
        if not g_tracer.enabled:
            if not self.enabled:
                return fn(*args, **kw)
            return self._timed_sync(name, fn, args, kw, None)
        with g_tracer.span(f"kernel:{name}") as sp:
            if not self.enabled:
                if sp is not None:
                    sp.tags["dispatch_only"] = True
                return fn(*args, **kw)
            return self._timed_sync(name, fn, args, kw, g_tracer)

    def _timed_sync(self, name: str, fn, args, kw, tracer):
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        if tracer is not None:
            drain_span = tracer.begin("device_drain")
        else:
            drain_span = None
        try:
            import jax
            jax.block_until_ready(out)
        except Exception:
            pass
        if tracer is not None:
            tracer.finish(drain_span)
        self._record(name, time.perf_counter() - t0)
        return out

    def _record(self, name: str, dt: float) -> None:
        with self._lock:
            s = self.stats.setdefault(
                name, {"calls": 0, "total_s": 0.0, "max_s": 0.0})
            s["calls"] += 1
            s["total_s"] += dt
            s["max_s"] = max(s["max_s"], dt)

    def dump(self) -> Dict[str, Dict[str, float]]:
        out = {}
        with self._lock:
            snap = {name: dict(s) for name, s in self.stats.items()}
        for name, d in sorted(snap.items()):
            if d["calls"]:
                d["avg_ms"] = round(d["total_s"] / d["calls"] * 1e3, 3)
            out[name] = d
        return out

    def reset(self) -> None:
        with self._lock:
            self.stats.clear()


g_kernel_timer = KernelTimer()


@contextlib.contextmanager
def annotate(name: str):
    """Named region in a jax profiler trace (TraceAnnotation
    passthrough).  Only the profiler plumbing is guarded — exceptions
    from the annotated body always propagate unchanged."""
    cm = None
    try:
        import jax.profiler
        cm = jax.profiler.TraceAnnotation(name)
    except Exception:
        cm = None
    if cm is None:
        yield
    else:
        with cm:
            yield


def start_profiler_trace(log_dir: str) -> bool:
    """Begin a jax profiler trace (view with tensorboard/xprof)."""
    try:
        import jax.profiler
        jax.profiler.start_trace(log_dir)
        return True
    except Exception:
        return False


def stop_profiler_trace() -> bool:
    try:
        import jax.profiler
        jax.profiler.stop_trace()
        return True
    except Exception:
        return False
