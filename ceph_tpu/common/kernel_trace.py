"""Per-kernel device timing + profiler hooks — the tracing subsystem.

The reference instruments its hot paths with LTTng tracepoints
(src/tracing/*.tp, emitted from e.g. OSD.cc:6606) and threads one
ZTracer trace id through every op (msg/Message.h:254).  The TPU-native
equivalents here:

- ``KernelTimer``: named cumulative timing of device dispatches.  Off
  by default (timing forces a ``block_until_ready`` sync per call,
  which kills dispatch pipelining); flip on via config
  ``tracing_kernels`` or ``KernelTimer.enable()`` when diagnosing.
  Dumped over the admin socket ("kernel timings") next to perf
  counters — the "perf dump" of the device side.
- ``annotate(name)``: a jax.profiler.TraceAnnotation passthrough so
  framework phases show up named in a jax profiler trace (the
  tracepoint provider analog); harmless no-op when the profiler is
  inactive or jax is absent.
- trace ids: already carried end-to-end by every message
  (msg/messages.py new_trace_id), surfaced in OpTracker events.
"""
from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Optional


class KernelTimer:
    """Cumulative wall timing per named kernel."""

    def __init__(self):
        self.enabled = False
        self.stats: Dict[str, Dict[str, float]] = {}

    def enable(self, on: bool = True) -> None:
        self.enabled = on

    @contextlib.contextmanager
    def time(self, name: str):
        """Time a host-side block (callers drain device values inside)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._record(name, time.perf_counter() - t0)

    def timed(self, name: str, fn, *args, **kw):
        """Call fn and drain its output: the one-shot instrumented
        dispatch used by the device backends when tracing is on."""
        if not self.enabled:
            return fn(*args, **kw)
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        try:
            import jax
            jax.block_until_ready(out)
        except Exception:
            pass
        self._record(name, time.perf_counter() - t0)
        return out

    def _record(self, name: str, dt: float) -> None:
        s = self.stats.setdefault(
            name, {"calls": 0, "total_s": 0.0, "max_s": 0.0})
        s["calls"] += 1
        s["total_s"] += dt
        s["max_s"] = max(s["max_s"], dt)

    def dump(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for name, s in sorted(self.stats.items()):
            d = dict(s)
            if s["calls"]:
                d["avg_ms"] = round(s["total_s"] / s["calls"] * 1e3, 3)
            out[name] = d
        return out

    def reset(self) -> None:
        self.stats.clear()


g_kernel_timer = KernelTimer()


@contextlib.contextmanager
def annotate(name: str):
    """Named region in a jax profiler trace (TraceAnnotation
    passthrough).  Only the profiler plumbing is guarded — exceptions
    from the annotated body always propagate unchanged."""
    cm = None
    try:
        import jax.profiler
        cm = jax.profiler.TraceAnnotation(name)
    except Exception:
        cm = None
    if cm is None:
        yield
    else:
        with cm:
            yield


def start_profiler_trace(log_dir: str) -> bool:
    """Begin a jax profiler trace (view with tensorboard/xprof)."""
    try:
        import jax.profiler
        jax.profiler.start_trace(log_dir)
        return True
    except Exception:
        return False


def stop_profiler_trace() -> bool:
    try:
        import jax.profiler
        jax.profiler.stop_trace()
        return True
    except Exception:
        return False
