"""rbd CLI (src/tools/rbd in the reference): image admin over a
MiniCluster checkpoint or live in-process cluster.

Subcommands mirror the reference verbs used in its qa suites
(qa/workunits/rbd/): create/ls/info/resize/rm/snap/clone/flatten plus
import/export for moving data in and out of images.
"""
from __future__ import annotations

import argparse
import json
import sys

from ..rbd import RBD, Image


def run(cluster, client, argv) -> int:
    """Drive rbd verbs against an existing cluster+client (the testable
    entry; ``main`` wraps it with checkpoint loading)."""
    ap = argparse.ArgumentParser(prog="rbd")
    ap.add_argument("-p", "--pool", default="rbd")
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("create")
    s.add_argument("image")
    s.add_argument("--size", type=int, required=True)
    s.add_argument("--order", type=int, default=22)
    s.add_argument("--data-pool", default=None)
    s.add_argument("--journaling", action="store_true")
    sub.add_parser("ls")
    s = sub.add_parser("info")
    s.add_argument("image")
    s = sub.add_parser("du")
    s.add_argument("spec", help="image[@snap]")
    s = sub.add_parser("resize")
    s.add_argument("image")
    s.add_argument("--size", type=int, required=True)
    s = sub.add_parser("rm")
    s.add_argument("image")
    s = sub.add_parser("snap")
    s.add_argument("verb", choices=["create", "rm", "ls", "protect",
                                    "unprotect", "rollback"])
    s.add_argument("spec", help="image[@snap]")
    s = sub.add_parser("clone")
    s.add_argument("parent_spec", help="image@snap")
    s.add_argument("child")
    s = sub.add_parser("flatten")
    s.add_argument("image")
    s = sub.add_parser("lock")
    s.add_argument("verb", choices=["add", "ls", "rm"])
    s.add_argument("image")
    s.add_argument("--cookie", default="")
    s.add_argument("--locker", default="")
    s = sub.add_parser("export")
    s.add_argument("image")
    s.add_argument("path")
    s = sub.add_parser("import")
    s.add_argument("path")
    s.add_argument("image")
    s.add_argument("--order", type=int, default=22)
    s = sub.add_parser("export-diff")
    s.add_argument("image")
    s.add_argument("path")
    s.add_argument("--from-snap", default=None)
    s.add_argument("--snap", default=None)
    s = sub.add_parser("import-diff")
    s.add_argument("path")
    s.add_argument("image")
    s = sub.add_parser("cp")
    s.add_argument("src")
    s.add_argument("dst")
    s.add_argument("--snap", default=None)
    args = ap.parse_args(argv)

    rbd = RBD(client)
    pool = args.pool
    if args.cmd == "create":
        rbd.create(pool, args.image, args.size, args.order,
                   data_pool=args.data_pool,
                   journaling=args.journaling)
    elif args.cmd == "ls":
        print("\n".join(rbd.list(pool)))
    elif args.cmd == "du":
        name, _, snap = args.spec.partition("@")
        img = Image(client, pool, name, snapshot=snap or None)
        print(json.dumps(img.du(), sort_keys=True))
    elif args.cmd == "info":
        print(json.dumps(Image(client, pool, args.image).stat(),
                         indent=2, sort_keys=True))
    elif args.cmd == "resize":
        Image(client, pool, args.image).resize(args.size)
    elif args.cmd == "rm":
        rbd.remove(pool, args.image)
    elif args.cmd == "snap":
        if args.verb == "ls":
            img = Image(client, pool, args.spec)
            print(json.dumps(img.snap_list(), indent=2, sort_keys=True))
        else:
            name, snap = args.spec.split("@", 1)
            img = Image(client, pool, name)
            getattr(img, {"create": "snap_create", "rm": "snap_remove",
                          "protect": "snap_protect",
                          "unprotect": "snap_unprotect",
                          "rollback": "snap_rollback"}[args.verb])(snap)
    elif args.cmd == "clone":
        pname, snap = args.parent_spec.split("@", 1)
        rbd.clone(pool, pname, snap, pool, args.child)
    elif args.cmd == "flatten":
        Image(client, pool, args.image).flatten()
    elif args.cmd == "lock":
        img = Image(client, pool, args.image)
        if args.verb == "add":
            r = img.lock_exclusive(args.cookie)
            if r < 0:
                print(f"lock failed: {r}", file=sys.stderr)
                return 1
        elif args.verb == "ls":
            print(json.dumps(img.list_lockers(), indent=2,
                             sort_keys=True))
        elif args.verb == "rm":
            r = (img.break_lock(args.locker, args.cookie)
                 if args.locker else img.unlock(args.cookie))
            if r < 0:
                print(f"unlock failed: {r}", file=sys.stderr)
                return 1
    elif args.cmd == "export":
        img = Image(client, pool, args.image)
        with open(args.path, "wb") as f:
            f.write(img.read(0, img.size()))
    elif args.cmd == "export-diff":
        img = Image(client, pool, args.image)
        with open(args.path, "wb") as fh:
            fh.write(img.export_diff(from_snap=args.from_snap,
                                     to_snap=args.snap))
    elif args.cmd == "import-diff":
        with open(args.path, "rb") as fh:
            Image(client, pool, args.image).import_diff(fh.read())
    elif args.cmd == "cp":
        rbd.copy(pool, args.src, pool, args.dst, src_snap=args.snap)
    elif args.cmd == "import":
        with open(args.path, "rb") as f:
            data = f.read()
        rbd.create(pool, args.image, len(data), args.order)
        Image(client, pool, args.image).write(0, data)
    return 0


def main(argv=None) -> int:  # pragma: no cover - thin shell wrapper
    argv = list(sys.argv[1:] if argv is None else argv)
    ap = argparse.ArgumentParser(prog="rbd", add_help=False)
    ap.add_argument("--checkpoint", required=True)
    ns, rest = ap.parse_known_args(argv)
    from ..cluster import MiniCluster
    c = MiniCluster.restore(ns.checkpoint)
    rc = run(c, c.client("client.rbd-cli"), rest)
    # match rados.py: persist mutations back into the checkpoint,
    # but don't rewrite it for read-only verbs
    toks: list[str] = []
    skip = False
    for t in rest:
        if skip:
            skip = False
        elif t in ("-p", "--pool"):
            skip = True                # option value, not a verb
        elif not t.startswith("-"):
            toks.append(t)
    readonly = (not toks or toks[0] in ("ls", "info", "du", "export",
                                        "export-diff")
                or (toks[0] in ("snap", "lock") and len(toks) > 1
                    and toks[1] == "ls"))
    if rc == 0 and not readonly:
        c.checkpoint(ns.checkpoint)
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
