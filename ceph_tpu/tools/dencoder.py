"""ceph-dencoder: encode/decode/inspect the framework's wire types
(src/test/encoding/ceph_dencoder.cc role, same command-stream CLI).

One in-memory object + one encoded buffer, driven by a sequence of
commands::

    ceph-dencoder type MOSDOp select_test 1 encode decode dump_json
    ceph-dencoder type OSDMap import mapfile decode dump_json
    ceph-dencoder type MMonPaxos is_deterministic

Registered types: every wire-codable M* message (msg/wire.py's
registry), plus the structured cluster types with their own codecs —
OSDMap and OSDMap.Incremental (osdmap/encoding.py, the mon-store
representation), CrushWrapper (the reference-compatible crushmap
binary, crush/binfmt.py) and MonMap (mon/monmap.py).

This is the encoding non-regression surface the reference drives
with ceph-object-corpus + test/encoding/readable.sh: round-trip
identity and encode-determinism per type (tests/test_dencoder.py
replays both checks over every registered type).
"""
from __future__ import annotations

import dataclasses
import json
import os
import sys
from typing import Any, Callable, Dict, List, Optional

USAGE = """usage: ceph-dencoder [commands ...]

  version             print version string (for utility)
  import <encfile>    read encoded data from encfile
  export <outfile>    write encoded data to outfile
  list_types          list supported types
  type <classname>    select in-memory type
  skip <num>          skip <num> leading bytes before decoding
  decode              decode into in-memory object
  encode              encode in-memory object
  dump_json           dump in-memory object as json (to stdout)
  copy                copy object (via operator=)
  copy_ctor           copy object (via copy ctor)
  count_tests         print number of generated test objects
  select_test <n>     select generated test object as in-memory object
  is_deterministic    exit w/ success if type encodes deterministically
"""

VERSION = "ceph-tpu dencoder"


class TypeHandler:
    """One registered type: encode/decode pair + generated test
    instances (the reference's generate_test_instances())."""

    def __init__(self, name: str,
                 encode: Callable[[Any], bytes],
                 decode: Callable[[bytes], Any],
                 tests: Callable[[], List[Any]],
                 to_jsonable: Optional[Callable[[Any], Any]] = None):
        self.name = name
        self.encode = encode
        self.decode = decode
        self.tests = tests
        self.to_jsonable = to_jsonable or _generic_jsonable


def _generic_jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _generic_jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {str(k): _generic_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_generic_jsonable(v) for v in obj]
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return bytes(obj).hex()
    if isinstance(obj, (int, float, str, bool)) or obj is None:
        return obj
    return repr(obj)


def _synth(tp: Any, depth: int = 0) -> Any:
    """A filled-in synthetic value for a dataclass field type."""
    import typing
    origin = typing.get_origin(tp)
    if tp is int:
        return 7
    if tp is float:
        return 2.5
    if tp is bool:
        return True
    if tp is str:
        return "t"
    if tp is bytes:
        return b"\x01\x02"
    if origin in (list, typing.List):
        return []
    if origin in (dict, typing.Dict):
        return {}
    if origin in (tuple, typing.Tuple):
        return ()
    return None


def _message_tests(cls: type) -> List[Any]:
    """Two instances per message: all-defaults and synth-filled
    (generate_test_instances(): 'at least two, one default and one
    filled with semi-meaningful values')."""
    default = cls()
    filled = cls()
    hints: Dict[str, Any] = {}
    try:
        import typing
        hints = typing.get_type_hints(cls)
    except Exception:
        pass
    for f in dataclasses.fields(cls):
        cur = getattr(filled, f.name)
        if cur in (0, "", b"", None, False):
            v = _synth(hints.get(f.name, type(cur)))
            if v is not None:
                setattr(filled, f.name, v)
    return [default, filled]


def _checked_decode(buf: bytes, cls: type) -> Any:
    """decode_message dispatches on the class name in the frame; the
    dencoder contract is stricter — the buffer must BE the selected
    type (the reference decodes as the selected type and fails on
    mismatched data)."""
    from ..msg import wire
    msg = wire.decode_message(buf)
    if type(msg) is not cls:
        raise ValueError(f"buffer contains {type(msg).__name__}, "
                         f"not {cls.__name__}")
    return msg


def _registry() -> Dict[str, TypeHandler]:
    from ..msg import wire
    reg: Dict[str, TypeHandler] = {}
    for name, cls in sorted(wire._MSG_CLASSES.items()):
        if name == "Message":
            continue
        reg[name] = TypeHandler(
            name, wire.encode_message,
            (lambda c: (lambda b: _checked_decode(b, c)))(cls),
            (lambda c: (lambda: _message_tests(c)))(cls))

    from ..osdmap import encoding as oenc
    from ..osdmap.simple_build import build_simple

    def osdmap_tests() -> List[Any]:
        return [build_simple(4)]

    reg["OSDMap"] = TypeHandler(
        "OSDMap",
        lambda m: wire.encode_blob(oenc.osdmap_to_dict(m)),
        lambda b: oenc.osdmap_from_dict(wire.decode_blob(b)),
        osdmap_tests,
        lambda m: oenc.osdmap_to_dict(m))

    def inc_tests() -> List[Any]:
        from ..osdmap.osdmap import Incremental
        inc = Incremental(epoch=2)
        inc2 = Incremental(epoch=3)
        inc2.new_weight[0] = 0
        return [inc, inc2]

    reg["OSDMap::Incremental"] = TypeHandler(
        "OSDMap::Incremental",
        lambda i: wire.encode_blob(oenc.incremental_to_dict(i)),
        lambda b: oenc.incremental_from_dict(wire.decode_blob(b)),
        inc_tests,
        lambda i: oenc.incremental_to_dict(i))

    from ..crush import binfmt
    from ..crush.wrapper import CrushWrapper
    from ..crush.constants import CRUSH_BUCKET_STRAW2

    def crush_tests() -> List[Any]:
        cw = CrushWrapper()
        cw.set_type_name(1, "host")
        cw.set_type_name(10, "root")
        h = cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, "host0", [0, 1],
                          [0x10000, 0x10000], id=-2)
        cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", [h],
                      [0x20000], id=-1)
        cw.set_max_devices(2)
        cw.add_simple_rule("data", "default", "host", mode="firstn")
        return [cw]

    from ..crush.dumpfmt import dump_map
    reg["CrushWrapper"] = TypeHandler(
        "CrushWrapper", binfmt.encode_crushmap, binfmt.decode_crushmap,
        crush_tests, lambda cw: dump_map(cw))

    from ..mon.monmap import MonMap

    def monmap_tests() -> List[Any]:
        mm = MonMap(fsid="00000000-1111-2222-3333-444444444444")
        mm.add("a", "127.0.0.1:6789")
        mm.add("b", "127.0.0.1:6790")
        # pin wall-clock fields so the archived corpus regenerates
        # byte-identically (a real diff must mean a codec change)
        mm.created = 1750000000.0
        mm.last_changed = 1750000000.0
        return [mm]

    reg["MonMap"] = TypeHandler(
        "MonMap", lambda m: m.to_bytes(),
        lambda b: MonMap.from_bytes(b), monmap_tests,
        lambda m: {"lines": m.print_lines()})
    return reg


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        sys.stderr.write(USAGE)
        return 1
    reg = _registry()
    handler: Optional[TypeHandler] = None
    obj: Any = None
    buf: Optional[bytes] = None
    skip = 0
    i = 0

    def need() -> Optional[str]:
        nonlocal i
        i += 1
        return args[i] if i < len(args) else None

    while i < len(args):
        cmd = args[i]
        if cmd in ("-h", "--help", "usage"):
            sys.stdout.write(USAGE)
            return 0
        elif cmd == "version":
            print(VERSION)
        elif cmd == "list_types":
            for name in reg:
                print(name)
        elif cmd == "type":
            name = need()
            if name not in reg:
                sys.stderr.write(f"class '{name}' unknown\n")
                return 1
            handler = reg[name]
            obj = None
        elif cmd == "skip":
            arg = need()
            if arg is None or not arg.lstrip("-").isdigit():
                sys.stderr.write("skip requires a numeric argument\n")
                return 1
            skip = int(arg)
        elif cmd == "import":
            path = need()
            if path is None:
                sys.stderr.write("import requires a file path\n")
                return 1
            try:
                with open(path, "rb") as f:
                    buf = f.read()
            except OSError as e:
                sys.stderr.write(f"error reading {path}: "
                                 f"{e.strerror}\n")
                return 1
        elif cmd == "export":
            path = need()
            if path is None:
                sys.stderr.write("export requires a file path\n")
                return 1
            if buf is None:
                sys.stderr.write("must first encode something\n")
                return 1
            with open(path, "wb") as f:
                f.write(buf)
        elif cmd == "decode":
            if handler is None:
                sys.stderr.write("must first select type with 'type "
                                 "<name>'\n")
                return 1
            if buf is None:
                sys.stderr.write("must first import data\n")
                return 1
            try:
                obj = handler.decode(buf[skip:])
            except Exception as e:
                sys.stderr.write(f"failed to decode: {e!r}\n")
                return 1
        elif cmd == "encode":
            if handler is None or obj is None:
                sys.stderr.write("must first select and fill an "
                                 "object ('type', then 'decode' or "
                                 "'select_test')\n")
                return 1
            buf = handler.encode(obj)
        elif cmd == "dump_json":
            if handler is None or obj is None:
                sys.stderr.write("must first select and fill an "
                                 "object\n")
                return 1
            print(json.dumps(handler.to_jsonable(obj), indent=4,
                             sort_keys=True, default=repr))
        elif cmd in ("copy", "copy_ctor"):
            if handler is None or obj is None:
                sys.stderr.write("must first select and fill an "
                                 "object\n")
                return 1
            # re-materialize through the codec: the strongest
            # copy-identity check available without C++ ctors
            obj = handler.decode(handler.encode(obj))
        elif cmd == "count_tests":
            if handler is None:
                sys.stderr.write("must first select type\n")
                return 1
            print(len(handler.tests()))
        elif cmd == "select_test":
            arg = need()
            if arg is None or not arg.isdigit():
                sys.stderr.write("select_test requires a test "
                                 "number\n")
                return 1
            n = int(arg)
            if handler is None:
                sys.stderr.write("must first select type\n")
                return 1
            tests = handler.tests()
            if not 1 <= n <= len(tests):
                sys.stderr.write(f"test number {n} out of range "
                                 f"(1..{len(tests)})\n")
                return 1
            obj = tests[n - 1]
        elif cmd == "is_deterministic":
            if handler is None:
                sys.stderr.write("must first select type\n")
                return 1
            for t in handler.tests():
                a = handler.encode(t)
                b = handler.encode(handler.decode(a))
                if a != handler.encode(t) or a != b:
                    print("type is NOT deterministic")
                    return 1
            print("type is deterministic")
        else:
            sys.stderr.write(f"unknown command '{cmd}'\n")
            sys.stderr.write(USAGE)
            return 1
        i += 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    try:
        sys.exit(main())
    except BrokenPipeError:
        # downstream pager/head closed the pipe; not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
