"""ceph-kvstore-tool: offline key/value store surgery
(src/tools/ceph_kvstore_tool.cc), usage and command surface pinned by
src/test/cli/ceph-kvstore-tool/help.t.

The backing store here is a directory of url-escaped
``<path>/<prefix>/<key>`` files — the KeyValueDB role (leveldb/
rocksdb/bluestore-kv in the reference) for this framework's offline
tooling: durable, inspectable, and transactional enough for a
repair/copy tool (each set/rm is a whole-file atomic rename).
"""
from __future__ import annotations

import os
import sys
from typing import Iterator, Optional, Tuple

from ..utils.crc32c import crc32c

USAGE = """Usage: ceph-kvstore-tool <leveldb|rocksdb|bluestore-kv> <store path> command [args...]

Commands:
  list [prefix]
  list-crc [prefix]
  exists <prefix> [key]
  get <prefix> <key> [out <file>]
  crc <prefix> <key>
  get-size [<prefix> <key>]
  set <prefix> <key> [ver <N>|in <file>]
  rm <prefix> <key>
  rm-prefix <prefix>
  store-copy <path> [num-keys-per-tx] [leveldb|rocksdb|...] 
  store-crc <path>
  compact
  compact-prefix <prefix>
  compact-range <prefix> <start> <end>
  repair

"""

TYPES = ("leveldb", "rocksdb", "bluestore-kv")


def url_escape(s: str) -> str:
    out = []
    for ch in s.encode():
        if ch <= 0x20 or ch >= 0x7F or ch in (0x25, 0x2F):  # % and /
            out.append("%%%02x" % ch)
        else:
            out.append(chr(ch))
    return "".join(out)


def url_unescape(s: str) -> str:
    out = bytearray()
    i = 0
    hexd = "0123456789abcdefABCDEF"
    while i < len(s):
        if s[i] == "%" and i + 2 < len(s) and s[i + 1] in hexd \
                and s[i + 2] in hexd:
            out.append(int(s[i + 1:i + 3], 16))
            i += 3
        else:
            out.append(ord(s[i]))
            i += 1
    return out.decode()


class DirStore:
    """KeyValueDB-lite over a directory tree."""

    def __init__(self, path: str, create: bool = False):
        self.path = path
        if create:
            os.makedirs(path, exist_ok=True)
        if not os.path.isdir(path):
            raise FileNotFoundError(path)

    def _pdir(self, prefix: str) -> str:
        return os.path.join(self.path, url_escape(prefix))

    def _kfile(self, prefix: str, key: str) -> str:
        return os.path.join(self._pdir(prefix), url_escape(key))

    def iterate(self, prefix: str = ""
                ) -> Iterator[Tuple[str, str, bytes]]:
        for pesc in sorted(os.listdir(self.path)):
            p = url_unescape(pesc)
            if prefix and p != prefix:
                continue
            pdir = os.path.join(self.path, pesc)
            if not os.path.isdir(pdir):
                continue
            for kesc in sorted(os.listdir(pdir)):
                with open(os.path.join(pdir, kesc), "rb") as f:
                    yield p, url_unescape(kesc), f.read()

    def get(self, prefix: str, key: str) -> Optional[bytes]:
        try:
            with open(self._kfile(prefix, key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def set(self, prefix: str, key: str, value: bytes) -> None:
        os.makedirs(self._pdir(prefix), exist_ok=True)
        tmp = self._kfile(prefix, key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, self._kfile(prefix, key))

    def rm(self, prefix: str, key: str) -> bool:
        try:
            os.unlink(self._kfile(prefix, key))
            return True
        except OSError:
            return False

    def rm_prefix(self, prefix: str) -> None:
        pdir = self._pdir(prefix)
        if os.path.isdir(pdir):
            for k in os.listdir(pdir):
                os.unlink(os.path.join(pdir, k))
            os.rmdir(pdir)

    def exists_any(self, prefix: str) -> bool:
        pdir = self._pdir(prefix)
        return os.path.isdir(pdir) and bool(os.listdir(pdir))

    def size(self) -> int:
        """Whole-store byte size (StoreTool::get_size role) via stat,
        without reading any values."""
        total = 0
        for pesc in os.listdir(self.path):
            pdir = os.path.join(self.path, pesc)
            if not os.path.isdir(pdir):
                continue
            for kesc in os.listdir(pdir):
                total += os.stat(os.path.join(pdir, kesc)).st_size
        return total


def _pair_crc(prefix: str, key: str, value: bytes,
              seed: int = 0) -> int:
    """crc32c over prefix+key+value concatenated with no separators
    (StoreTool::traverse builds one bufferlist of the three)."""
    return crc32c(prefix.encode() + key.encode() + value, seed)


def _si_t(n: int) -> str:
    """byte count with binary-SI suffix (include/types.h si_t)."""
    for mag, suffix in ((40, "T"), (30, "G"), (20, "M"), (10, "k")):
        if n >= 1 << mag:
            return f"{n >> mag}{suffix}"
    return str(n)


def main(argv: Optional[list] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if len(args) < 3:
        sys.stderr.write(USAGE)
        return 1
    type_, path, cmd, rest = args[0], args[1], args[2], args[3:]
    if type_ not in TYPES:
        sys.stderr.write(f"Unrecognized type: {type_}\n")
        sys.stderr.write(USAGE)
        return 1
    try:
        st = DirStore(path, create=cmd in ("set", "repair"))
    except FileNotFoundError:
        sys.stderr.write(f"failed to open type {type_} path {path}\n")
        return 1

    if cmd == "repair":
        print("repair kvstore successfully")
        return 0
    if cmd in ("list", "list-crc"):
        prefix = url_unescape(rest[0]) if rest else ""
        for p, k, v in st.iterate(prefix):
            line = f"{url_escape(p)}\t{url_escape(k)}"
            if cmd == "list-crc":
                line += f"\t{_pair_crc(p, k, v)}"
            print(line)
        return 0
    if cmd == "exists":
        if not rest:
            sys.stderr.write(USAGE)
            return 1
        prefix = url_unescape(rest[0])
        key = url_unescape(rest[1]) if len(rest) > 1 else ""
        if key:
            found = st.get(prefix, key) is not None
        else:
            found = st.exists_any(prefix)
        print(f"({url_escape(prefix)}, {url_escape(key)}) "
              + ("exists" if found else "does not exist"))
        return 0 if found else 1
    if cmd == "get":
        if len(rest) < 2:
            sys.stderr.write(USAGE)
            return 1
        prefix, key = url_unescape(rest[0]), url_unescape(rest[1])
        v = st.get(prefix, key)
        head = f"({url_escape(prefix)}, {url_escape(key)})"
        if v is None:
            print(head + " does not exist")
            return 1
        print(head)
        if len(rest) >= 3:
            if rest[2] != "out":
                sys.stderr.write(f"unrecognized subcmd '{rest[2]}'\n")
                return 1
            if len(rest) < 4 or not rest[3]:
                sys.stderr.write("output path not specified\n")
                return 1
            with open(rest[3], "wb") as f:
                f.write(v)
            print(f"wrote {len(v)} bytes to {rest[3]}")
        else:
            # hexdump-style preview matching bufferlist::hexdump's role
            for off in range(0, len(v), 16):
                chunk = v[off:off + 16]
                hexs = " ".join(f"{b:02x}" for b in chunk)
                print(f"{off:08x}  {hexs}")
        return 0
    if cmd == "crc":
        if len(rest) < 2:
            sys.stderr.write(USAGE)
            return 1
        prefix, key = url_unescape(rest[0]), url_unescape(rest[1])
        v = st.get(prefix, key)
        if v is None:
            print(f"({url_escape(prefix)}, {url_escape(key)}) "
                  "does not exist")
            return 1
        print(f"({url_escape(prefix)}, {url_escape(key)}) crc "
              f"{_pair_crc(prefix, key, v)}")
        return 0
    if cmd == "get-size":
        # reference shape (ceph_kvstore_tool.cc:446-467): the whole-
        # store estimate always prints first; a lone extra arg is a
        # usage error; prefix+key adds the pair's size line
        print(f"estimated store size: {st.size()}")
        if not rest:
            return 0
        if len(rest) < 2:
            sys.stderr.write(USAGE)
            return 1
        prefix, key = url_unescape(rest[0]), url_unescape(rest[1])
        v = st.get(prefix, key)
        if v is None:
            sys.stderr.write(f"({url_escape(prefix)},"
                             f"{url_escape(key)}) does not exist\n")
            return 1
        print(f"({url_escape(prefix)},{url_escape(key)}) size "
              f"{_si_t(len(v))}")
        return 0
    if cmd == "set":
        if len(rest) < 2:
            sys.stderr.write(USAGE)
            return 1
        prefix, key = url_unescape(rest[0]), url_unescape(rest[1])
        if len(rest) >= 4 and rest[2] == "ver":
            import struct
            val = struct.pack("<Q", int(rest[3]))
        elif len(rest) >= 4 and rest[2] == "in":
            try:
                with open(rest[3], "rb") as f:
                    val = f.read()
            except OSError as e:
                sys.stderr.write(f"error reading file {rest[3]}: "
                                 f"{e.strerror}\n")
                return 1
        else:
            sys.stderr.write(USAGE)
            return 1
        st.set(prefix, key, val)
        return 0
    if cmd == "rm":
        if len(rest) < 2:
            sys.stderr.write(USAGE)
            return 1
        ok = st.rm(url_unescape(rest[0]), url_unescape(rest[1]))
        return 0 if ok else 1
    if cmd == "rm-prefix":
        if not rest:
            sys.stderr.write(USAGE)
            return 1
        st.rm_prefix(url_unescape(rest[0]))
        return 0
    if cmd == "store-copy":
        if not rest:
            sys.stderr.write(USAGE)
            return 1
        dst = DirStore(rest[0], create=True)
        n = 0
        for p, k, v in st.iterate(""):
            dst.set(p, k, v)
            n += 1
        print("summary:")
        print(f"  copied {n} keys")
        return 0
    if cmd == "store-crc":
        # traverse with the dump written to <path> (the reference's
        # ofstream(argv[4])), crc chained with no separators from -1
        if not rest:
            sys.stderr.write(USAGE)
            return 1
        crc = 0xFFFFFFFF
        with open(rest[0], "w") as dump:
            for p, k, v in st.iterate(""):
                dump.write(f"{url_escape(p)}\t{url_escape(k)}\t"
                           f"{_pair_crc(p, k, v)}\n")
                crc = _pair_crc(p, k, v, crc)
        print(f"store at '{rest[0]}' crc {crc}")
        return 0
    if cmd in ("compact", "compact-prefix", "compact-range"):
        return 0        # directory store has nothing to compact
    sys.stderr.write(f"Unrecognized command: {cmd}\n")
    sys.stderr.write(USAGE)
    return 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
