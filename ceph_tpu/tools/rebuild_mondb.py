"""rebuild-mondb: reconstruct a LOST monitor store from surviving
OSDs (src/tools/rebuild_mondb.cc update_osdmap / the documented
mon-store disaster-recovery flow).

Every OSD persists each osdmap incremental it applies into its meta
collection (inc_osdmap.<epoch>, osd/osd.py _persist_incremental);
this tool scans every osd store in a checkpoint directory, takes the
UNION of epochs across OSDs (any single OSD may have joined late or
died early), replays them from scratch, and writes a fresh mon.json
the cluster restores from.

usage: rebuild-mondb <checkpoint-dir> [--mon NAME=ADDR ...] [--force]
"""
from __future__ import annotations

import glob
import json
import os
import sys
from typing import Dict, List, Optional

USAGE = ("usage: rebuild-mondb <checkpoint-dir> "
         "[--mon NAME=ADDR ...] [--force]\n")


def collect_incrementals(ckpt: str) -> Dict[int, dict]:
    """epoch -> incremental dict, unioned across every osd store."""
    from ..msg.wire import decode_blob
    from ..os_store.memstore import MemStore

    out: Dict[int, dict] = {}
    stores = sorted(glob.glob(os.path.join(ckpt, "osd.*.store")))
    if not stores:
        raise FileNotFoundError(f"no osd stores under {ckpt}")
    for path in stores:
        store = MemStore.load(path)
        if not store.collection_exists("meta"):
            continue
        for ho in store.list_objects("meta"):
            name = ho.oid if isinstance(ho.oid, str) else str(ho.oid)
            if not name.startswith("inc_osdmap."):
                continue
            epoch = int(name.split(".", 1)[1])
            if epoch in out:
                continue
            raw = store.read("meta", ho, 0, 1 << 30)
            out[epoch] = decode_blob(bytes(raw))
    return out


def rebuild(ckpt: str, mons: Optional[List[str]] = None,
            force: bool = False) -> str:
    """Reconstruct <ckpt>/mon.json; returns a summary line."""
    from ..mon.monitor import mon_store_state
    from ..mon.monmap import MonMap
    from ..osdmap.encoding import incremental_from_dict
    from ..osdmap.osdmap import OSDMap

    mon_path = os.path.join(ckpt, "mon.json")
    if os.path.exists(mon_path) and not force:
        raise FileExistsError(
            f"{mon_path} already exists; pass --force to overwrite")

    incs = collect_incrementals(ckpt)
    if not incs:
        raise ValueError("no osdmap incrementals found in any osd "
                         "store — nothing to rebuild from")
    epochs = sorted(incs)
    if epochs[0] != 1:
        raise ValueError(f"history starts at epoch {epochs[0]}, not 1 "
                         "— a full map cannot be reconstructed")
    missing = [e for e in range(1, epochs[-1] + 1) if e not in incs]
    if missing:
        raise ValueError(f"gaps in the recovered history: {missing}")

    m = OSDMap()
    inc_objs = []
    for e in epochs:
        inc = incremental_from_dict(incs[e])
        inc_objs.append(inc)
        m.apply_incremental(inc)

    # the monmap is mon-side state the OSD stores never held; rebuild
    # a fresh epoch-1 map (names from --mon, or the single default)
    mm = MonMap()
    for spec in (mons or ["mon=127.0.0.1:6789"]):
        name, _, addr = spec.partition("=")
        mm.add(name, addr or "127.0.0.1:6789")
    mm.epoch = 1                       # a committed roster, not epoch 0

    state = mon_store_state(m, inc_objs, mm)
    tmp = mon_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
    os.replace(tmp, mon_path)
    return (f"rebuilt {mon_path}: epochs 1..{epochs[-1]} from "
            f"{len(epochs)} incrementals")


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if args and args[0] in ("-h", "--help"):
        sys.stdout.write(USAGE)
        return 0
    if not args:
        sys.stderr.write(USAGE)
        return 1
    ckpt = args[0]
    mons: List[str] = []
    force = False
    i = 1
    while i < len(args):
        if args[i] == "--mon":
            if i + 1 >= len(args):
                sys.stderr.write("--mon requires NAME=ADDR\n")
                return 1
            mons.append(args[i + 1])
            i += 2
        elif args[i] == "--force":
            force = True
            i += 1
        else:
            sys.stderr.write(f"unknown argument '{args[i]}'\n{USAGE}")
            return 1
    try:
        print(rebuild(ckpt, mons or None, force))
    except (OSError, ValueError) as e:
        sys.stderr.write(f"rebuild-mondb: {e}\n")
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
