"""ceph-authtool — keyring create/list/mutate CLI
(src/tools/ceph_authtool.cc role over auth/keyring.py).

Output strings and exit codes are pinned byte-exact against the
reference's recorded cram suite (src/test/cli/ceph-authtool/*.t):
create/gen/list round-trips, --add-key with auid and its decode
failure, the all-replacing --cap semantics, and the doubled
can't-open message on a missing keyring.
"""
from __future__ import annotations

import base64
import binascii
import sys

USAGE = """usage: ceph-authtool keyringfile [OPTIONS]...
where the options are:
  -l, --list                    will list all keys and capabilities present in
                                the keyring
  -p, --print-key               will print an encoded key for the specified
                                entityname. This is suitable for the
                                'mount -o secret=..' argument
  -C, --create-keyring          will create a new keyring, overwriting any
                                existing keyringfile
  -g, --gen-key                 will generate a new secret key for the
                                specified entityname
  --gen-print-key               will generate a new secret key without set it
                                to the keyringfile, prints the secret to stdout
  --import-keyring FILE         will import the content of a given keyring
                                into the keyringfile
  -n NAME, --name NAME          specify entityname to operate on
  -u AUID, --set-uid AUID       sets the auid (authenticated user id) for the
                                specified entityname
  -a BASE64, --add-key BASE64   will add an encoded key to the keyring
  --cap SUBSYSTEM CAPABILITY    will set the capability for given subsystem
  --caps CAPSFILE               will set all of capabilities associated with a
                                given key, for all subsystems"""

DEFAULT_AUID = 18446744073709551615          # CEPH_AUTH_UID_DEFAULT


def _gen_secret() -> bytes:
    # the CryptoKey encoding shape (type + stamp + len + 16 random
    # bytes = 28 bytes) so generated keys look like the reference's
    import os as _os
    import struct
    import time as _time
    t = _time.time()
    return struct.pack("<HII H", 1, int(t), int((t % 1) * 1e9),
                       16) + _os.urandom(16)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        return _parse_and_run(argv)
    except IndexError:
        # a flag missing its operand (--cap osd, -n, ...)
        print(USAGE)
        return 1


def _parse_and_run(argv) -> int:
    from ..auth.keyring import Keyring
    fname = None
    do_list = do_create = do_gen = do_print_key = False
    gen_print = False
    name = "client.admin"
    add_key = None
    auid = DEFAULT_AUID
    caps = []
    import_file = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print("no command specified")
            print(USAGE)
            return 1
        elif a in ("-l", "--list"):
            do_list = True
        elif a in ("-C", "--create-keyring"):
            do_create = True
        elif a in ("-g", "--gen-key"):
            do_gen = True
        elif a == "--gen-print-key":
            gen_print = True
        elif a in ("-p", "--print-key"):
            do_print_key = True
        elif a in ("-n", "--name") or a.startswith("--name="):
            if "=" in a:
                name = a.split("=", 1)[1]
            else:
                i += 1
                name = argv[i]
        elif a in ("-u", "--set-uid"):
            i += 1
            auid = int(argv[i])
        elif a in ("-a", "--add-key") or a.startswith("--add-key="):
            if "=" in a and a.startswith("--add-key="):
                add_key = a.split("=", 1)[1]
            else:
                i += 1
                add_key = argv[i] if i < len(argv) else ""
            if not add_key:
                print("Option --add-key requires an argument")
                return 1
        elif a == "--cap":
            caps.append((argv[i + 1], argv[i + 2]))
            i += 2
        elif a == "--import-keyring":
            i += 1
            import_file = argv[i]
        else:
            fname = a
        i += 1
    if gen_print and not fname:
        print(base64.b64encode(_gen_secret()).decode())
        return 0
    if fname is None:
        print("ceph-authtool: must specify filename")
        print(USAGE)
        return 1

    kr = Keyring()
    if do_create:
        print(f"creating {fname}")
    else:
        try:
            kr = Keyring.load(fname)
        except FileNotFoundError:
            print(f"can't open {fname}: can't open {fname}: (2) No "
                  f"such file or directory")
            return 1
    modified = do_create
    if import_file is not None:
        other = Keyring.load(import_file)
        kr.keys.update(other.keys)
        for ent, c in other.caps.items():
            kr.caps[ent] = dict(c)
        modified = True
    if do_gen:
        kr.keys[name] = _gen_secret()
        modified = True
    if gen_print:
        print(base64.b64encode(_gen_secret()).decode())
    if add_key is not None:
        parts = add_key.split()
        try:
            secret = base64.b64decode(parts[0], validate=True)
            if not secret or len(parts[0]) % 4:
                raise binascii.Error("bad")
        except (binascii.Error, ValueError):
            print(f"can't decode key '{add_key}'")
            return 1
        if len(parts) > 1:
            auid = int(parts[1])
        kr.keys[name] = secret
        ncaps = len(kr.caps.get(name, {}))
        print(f"added entity {name} auth auth(auid = {auid} "
              f"key={parts[0]} with {ncaps} caps)")
        modified = True
    if caps:
        # --cap REPLACES the whole cap set (KeyRing semantics the
        # reference's cap-overwrite.t records)
        kr.set_caps(name, dict(caps))
        modified = True
    if do_print_key:
        sec = kr.get(name)
        if sec is None:
            print(f"entity {name} not found")
            return 1
        print(base64.b64encode(sec).decode())
    if do_list:
        for line in kr.lines():
            print(line)
    if modified:
        kr.save(fname)
    return 0


if __name__ == "__main__":
    # die silently on a closed pipe (`tool ... | head`), like the
    # C++ tools' default SIGPIPE disposition
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
