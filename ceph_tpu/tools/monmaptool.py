"""monmaptool — build/inspect MonMap files (src/tools/monmaptool.cc).

Output strings, staging order, and exit codes are pinned byte-exact
against the reference's own recorded cram suite
(src/test/cli/monmaptool/*.t): create/clobber, add/rm with their
usage-on-error shapes, --print, and the feature set/unset/list
machinery including unknown(N) rendering.
"""
from __future__ import annotations

import os
import sys
import uuid as _uuid

USAGE = """ usage: [--print] [--create [--clobber][--fsid uuid]]
        [--generate] [--set-initial-members]
        [--add name 1.2.3.4:567] [--rm name]
        [--feature-list [plain|parseable]]
        [--feature-set <value> [--optional|--persistent]]
        [--feature-unset <value> [--optional|--persistent]] <mapfilename>"""


def _usage() -> None:
    print(USAGE)


def _parse_feature(val: str):
    from ..mon.monmap import FEATURE_VALUES
    if val in FEATURE_VALUES:
        return FEATURE_VALUES[val]
    try:
        return int(val)
    except ValueError:
        return None


def _fmt_features(bits: int) -> str:
    from ..mon.monmap import FEATURE_NAMES
    if not bits:
        return "[none]"
    parts = []
    b = 1
    while b <= bits:
        if bits & b:
            parts.append(f"{FEATURE_NAMES.get(b, 'unknown')}({b})")
        b <<= 1
    return "[" + ",".join(parts) + "]"


def _feature_list(m, mode: str) -> None:
    from ..mon.monmap import PERSISTENT, SUPPORTED
    req = m.persistent_features | m.optional_features
    if mode == "parseable":
        print(f"monmap:persistent:{_fmt_features(m.persistent_features)}")
        print(f"monmap:optional:{_fmt_features(m.optional_features)}")
        print(f"monmap:required:{_fmt_features(req)}")
        print(f"available:supported:{_fmt_features(SUPPORTED)}")
        print(f"available:persistent:{_fmt_features(PERSISTENT)}")
        return
    print("MONMAP FEATURES:")
    print(f"    persistent: {_fmt_features(m.persistent_features)}")
    print(f"    optional:   {_fmt_features(m.optional_features)}")
    print(f"    required:   {_fmt_features(req)}")
    print("")
    print("AVAILABLE FEATURES:")
    print(f"    supported:  {_fmt_features(SUPPORTED)}")
    print(f"    persistent: {_fmt_features(PERSISTENT)}")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        return _parse_and_run(argv)
    except IndexError:
        # a flag missing its operand (--add name, --fsid, ...)
        _usage()
        return 1


def _parse_and_run(argv) -> int:
    from ..mon.monmap import MonMap
    fname = None
    do_print = create = clobber = False
    fsid = None
    adds = []            # (name, addr)
    rms = []
    # feature ops: (set?, value, which) resolved in argv order — the
    # --optional/--persistent MODIFIER binds to the preceding op
    fops = []
    flists = []          # list-mode strings in argv order
    i = 0
    seen_dashdash = False
    while i < len(argv):
        a = argv[i]
        if a == "--" and not seen_dashdash:
            seen_dashdash = True
        elif not seen_dashdash and a == "--help":
            _usage()
            return 1
        elif not seen_dashdash and a == "--print":
            do_print = True
        elif not seen_dashdash and a == "--create":
            create = True
        elif not seen_dashdash and a == "--clobber":
            clobber = True
        elif not seen_dashdash and a == "--generate":
            pass                               # conf-driven: lite no-op
        elif not seen_dashdash and a == "--set-initial-members":
            pass
        elif not seen_dashdash and a == "--fsid":
            i += 1
            fsid = argv[i]
        elif not seen_dashdash and a == "--add":
            name, addr = argv[i + 1], argv[i + 2]
            i += 2
            adds.append((name, addr))
        elif not seen_dashdash and a == "--rm":
            i += 1
            rms.append(argv[i])
        elif not seen_dashdash and a in ("--feature-set",
                                         "--feature-unset"):
            i += 1
            raw = argv[i] if i < len(argv) else ""
            val = _parse_feature(raw)
            if val is None:
                print(f"unknown features name '{raw}' or unable to "
                      f"parse value: Expected option value to be "
                      f"integer, got '{raw}'")
                _usage()
                return 1
            fops.append([a == "--feature-set", val, "persistent"])
        elif not seen_dashdash and a in ("--optional", "--persistent"):
            if fops:
                fops[-1][2] = a[2:]
        elif not seen_dashdash and a == "--feature-list":
            # optional mode argument
            if i + 1 < len(argv) and argv[i + 1] in ("plain",
                                                     "parseable"):
                i += 1
                flists.append(argv[i])
            else:
                flists.append("plain")
        else:
            fname = a
        i += 1
    if fname is None:
        print("monmaptool: must specify monmap filename")
        _usage()
        return 1
    print(f"monmaptool: monmap file {fname}")
    modified = False
    if create:
        if os.path.exists(fname) and not clobber:
            print(f"monmaptool: {fname} exists, --clobber to "
                  f"overwrite")
            return 255
        m = MonMap(fsid=fsid)
        if fsid is None:
            print(f"monmaptool: generated fsid {m.fsid}")
        else:
            try:
                _uuid.UUID(fsid)
            except ValueError:
                print(f"monmaptool: invalid fsid '{fsid}'")
                return 255
        modified = True
    else:
        try:
            raw = open(fname, "rb").read()
        except FileNotFoundError:
            print(f"monmaptool: couldn't open {fname}: (2) No such "
                  f"file or directory")
            return 255
        try:
            m = MonMap.from_bytes(raw)
        except (ValueError, KeyError):
            print("monmaptool: unable to read monmap file")
            return 255
    for name, addr in adds:
        if ":" not in addr.split("/", 1)[0]:
            addr += ":6789"      # the reference's default mon port
        if m.contains(name):
            print(f"monmaptool: map already contains mon.{name}")
            _usage()
            return 1
        m.add(name, addr)
        modified = True
    for name in rms:
        print(f"monmaptool: removing {name}")
        if not m.contains(name):
            print(f"monmaptool: map does not contain {name}")
            _usage()
            return 1
        m.remove(name)
        modified = True
    for is_set, val, which in fops:
        attr = f"{which}_features"
        cur = getattr(m, attr)
        setattr(m, attr, (cur | val) if is_set else (cur & ~val))
        modified = True
    for mode in flists:
        _feature_list(m, mode)
    if do_print:
        for line in m.print_lines():
            print(line)
    if modified:
        import time as _time
        m.last_changed = _time.time()
        print(f"monmaptool: writing epoch {m.epoch} to {fname} "
              f"({len(m.mons)} monitors)")
        with open(fname, "wb") as f:
            f.write(m.to_bytes())
    return 0


if __name__ == "__main__":
    # die silently on a closed pipe (`tool ... | head`), like the
    # C++ tools' default SIGPIPE disposition
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
