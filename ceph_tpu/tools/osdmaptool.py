"""osdmaptool — inspect and exercise OSDMaps.

CLI surface mirrors the reference tool (src/tools/osdmaptool.cc):
--createsimple N builds a map, --test-map-pgs maps every PG of every pool
(the full-cluster remap benchmark harness, backed by OSDMapMapping's device
batch path), --test-map-object maps one object, --upmap runs the balancer
(calc_pg_upmaps), --mark-up-in resets osd states.  Maps are python pickles.
"""
from __future__ import annotations

import os
import pickle
import sys
import time

import numpy as np

from ..crush.constants import CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE
from ..osdmap import (
    CEPH_OSD_IN, Incremental, OSDMap, OSDMapMapping, TYPE_REPLICATED,
    pg_pool_t, pg_t,
)


def createsimple_legacy(n_osds: int, pg_num: int = 128,
                 osds_per_host: int = 4) -> OSDMap:
    m = OSDMap()
    m.set_max_osd(n_osds)
    cw = m.crush
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    hosts = []
    n_hosts = (n_osds + osds_per_host - 1) // osds_per_host
    for h in range(n_hosts):
        osds = list(range(h * osds_per_host,
                          min((h + 1) * osds_per_host, n_osds)))
        hid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}", osds,
                            [0x10000] * len(osds), id=-(h + 2))
        hosts.append((hid, len(osds)))
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default",
                  [h for h, _ in hosts],
                  [0x10000 * n for _, n in hosts], id=-1)
    for i in range(n_osds):
        m.set_osd(i, up=True, weight=CEPH_OSD_IN)
        cw.set_item_name(i, f"osd.{i}")
    rno = cw.add_simple_rule("replicated_rule", "default", "host",
                             mode="firstn")
    m.add_pool("rbd", pg_pool_t(type=TYPE_REPLICATED, size=3,
                                crush_rule=rno, pg_num=pg_num,
                                pgp_num=pg_num))
    m.epoch = 1
    return m


def _crush_item_weights(m: OSDMap) -> dict:
    """osd -> crush item weight, one pass over every bucket."""
    out: dict = {}
    for b in m.crush.crush.buckets:
        if b is None:
            continue
        for i, it in enumerate(b.items):
            if it >= 0:
                out[it] = b.item_weights[i]
    return out


def test_map_pgs(m: OSDMap, use_device: bool, out,
                 test_random: bool = False, only_pool: int = -1) -> None:
    """--test-map-pgs in the reference's output format
    (src/tools/osdmaptool.cc): per-pool pg_num lines, the per-IN-osd
    count table, ' in/avg/min/max' stats, and the size histogram —
    plus one trailing 'mapped ...' line naming the batch backend."""
    mapping = OSDMapMapping(use_device=use_device)
    t0 = time.perf_counter()
    if not test_random:
        mapping.update(m)
    dt = time.perf_counter() - t0
    count = np.zeros(m.max_osd, dtype=np.int64)
    first = np.zeros(m.max_osd, dtype=np.int64)
    primaries = np.zeros(m.max_osd, dtype=np.int64)
    sizes = np.zeros(30, dtype=np.int64)
    total_pgs = 0
    rng = np.random.default_rng()
    for pid in sorted(m.pools):
        if only_pool >= 0 and pid != only_pool:
            continue
        pool = m.pools[pid]
        print(f"pool {pid} pg_num {pool.pg_num}", file=out)
        for ps in range(pool.pg_num):
            total_pgs += 1
            if test_random:
                row = rng.integers(0, m.max_osd, size=pool.size)
                prim = int(row[0])
            else:
                pm = mapping.pools[pid]
                row = [o for o in pm.acting[ps] if o != CRUSH_ITEM_NONE]
                prim = int(pm.acting_primary[ps])
            sizes[len(row)] += 1
            for o in row:
                count[o] += 1
            if len(row):
                first[row[0]] += 1
            if prim >= 0:
                primaries[prim] += 1
    n_in = 0
    total = 0
    min_osd = max_osd = -1
    crush_w = _crush_item_weights(m)
    print("#osd\tcount\tfirst\tprimary\tc wt\twt", file=out)
    for o in range(m.max_osd):
        if m.osd_weight[o] == 0:
            continue
        cw = crush_w.get(o, 0)
        if cw <= 0:
            continue
        n_in += 1
        print(f"osd.{o}\t{count[o]}\t{first[o]}\t{primaries[o]}"
              f"\t{cw / 0x10000:g}\t{m.osd_weight[o] / 0x10000:g}",
              file=out)
        total += count[o]
        if count[o] and (min_osd < 0 or count[o] < count[min_osd]):
            min_osd = o
        if count[o] and (max_osd < 0 or count[o] > count[max_osd]):
            max_osd = o
    avg = total // n_in if n_in else 0
    dev = 0.0
    for o in range(m.max_osd):
        if m.osd_weight[o] == 0 or crush_w.get(o, 0) <= 0:
            continue
        dev += float(avg - count[o]) ** 2
    dev = (dev / n_in) ** 0.5 if n_in else 0.0
    edev = ((total / n_in) * (1.0 - 1.0 / n_in)) ** 0.5 if n_in else 0.0
    print(f" in {n_in}", file=out)
    print(f" avg {avg} stddev {dev:g} ({dev / avg if avg else 0:g}x) "
          f"(expected {edev:g} {edev / avg if avg else 0:g}x))",
          file=out)
    if min_osd >= 0:
        print(f" min osd.{min_osd} {count[min_osd]}", file=out)
    if max_osd >= 0:
        print(f" max osd.{max_osd} {count[max_osd]}", file=out)
    for i in range(4):
        print(f"size {i}\t{sizes[i]}", file=out)
    backends = ",".join(sorted(set(mapping.last_backend.values()))) \
        if not test_random else "random"
    print(f"mapped {total_pgs} pgs in {dt * 1000:.1f} ms "
          f"(backend: {backends})", file=out)


USAGE = """ usage: [--print] [--createsimple <numosd> [--clobber] [--pg_bits <bitsperosd>]] <mapfilename>
   --export-crush <file>   write osdmap's crush map to <file>
   --import-crush <file>   replace osdmap's crush map with <file>
   --test-map-pgs [--pool <poolid>] [--pg_num <pg_num>] map all pgs
   --test-map-pgs-dump [--pool <poolid>] map all pgs
   --test-map-pgs-dump-all [--pool <poolid>] map all pgs to osds
   --health                dump health checks
   --mark-up-in            mark osds up and in (but do not persist)
   --mark-out <osdid>      mark an osd as out (but do not persist)
   --with-default-pool     include default pool when creating map
   --clear-temp            clear pg_temp and primary_temp
   --test-random           do random placements
   --test-map-pg <pgid>    map a pgid to osds
   --test-map-object <objectname> [--pool <poolid>] map an object to osds
   --upmap-cleanup <file>  clean up pg_upmap[_items] entries, writing
                           commands to <file> [default: - for stdout]
   --upmap <file>          calculate pg upmap entries to balance pg layout
                           writing commands to <file> [default: - for stdout]
   --upmap-max <max-count> set max upmap entries to calculate [default: 100]
   --upmap-deviation <max-deviation>
                           max deviation from target [default: .01]
   --upmap-pool <poolname> restrict upmap balancing to 1 or more pools
   --upmap-save            write modified OSDMap with upmap changes"""

def _pool_flags_string(flags: int) -> str:
    from ..osdmap.types import (
        FLAG_EC_OVERWRITES, FLAG_FULL, FLAG_FULL_QUOTA, FLAG_HASHPSPOOL,
        FLAG_NEARFULL,
    )
    names = [(FLAG_HASHPSPOOL, "hashpspool"), (FLAG_FULL, "full"),
             (FLAG_NEARFULL, "nearfull"),
             (FLAG_FULL_QUOTA, "full_quota"),
             (FLAG_EC_OVERWRITES, "ec_overwrites")]
    return ",".join(n for bit, n in sorted(names) if flags & bit)


def pool_print_line(pid: int, name: str, pool) -> str:
    """osd_types.cc operator<<(pg_pool_t) with the pool id/name prefix
    OSDMap::print_pools adds."""
    kind = "erasure" if pool.is_erasure() else "replicated"
    out = (f"pool {pid} '{name}' {kind} size {pool.size} "
           f"min_size {pool.min_size} crush_rule {pool.crush_rule} "
           f"object_hash rjenkins pg_num {pool.pg_num} "
           f"pgp_num {pool.pgp_num} last_change {pool.last_change}")
    if pool.flags:
        out += f" flags {_pool_flags_string(pool.flags)}"
    if pool.quota_max_bytes:
        out += f" max_bytes {pool.quota_max_bytes}"
    if pool.quota_max_objects:
        out += f" max_objects {pool.quota_max_objects}"
    out += f" stripe_width {pool.stripe_width}"
    if getattr(pool, "application", ""):
        out += f" application {pool.application}"
    return out


def _stamp(t: float) -> str:
    lt = time.localtime(t)
    frac = int((t % 1) * 1_000_000)
    return time.strftime("%Y-%m-%d %H:%M:%S", lt) + f".{frac:06d}"


def osdmap_print(m, out) -> None:
    """OSDMap::print (OSDMap.cc:3113), pinned by create-print.t /
    clobber.t.  The osd-status section covers the fields this map
    model tracks (state + weight)."""
    zero = "00000000-0000-0000-0000-000000000000"
    print(f"epoch {m.epoch}", file=out)
    # getattr defaults: maps pickled before these fields existed must
    # still print, not die with AttributeError
    print(f"fsid {getattr(m, 'fsid', zero)}", file=out)
    print(f"created {_stamp(getattr(m, 'created', 0.0))}", file=out)
    print(f"modified {_stamp(getattr(m, 'modified', 0.0))}",
          file=out)
    print("flags ", file=out)
    print(f"crush_version {getattr(m, 'crush_version', 1)}",
          file=out)
    print("full_ratio 0", file=out)
    print("backfillfull_ratio 0", file=out)
    print("nearfull_ratio 0", file=out)
    print("min_compat_client jewel", file=out)
    print("", file=out)
    for pid in sorted(m.pools):
        print(pool_print_line(pid, m.pool_name[pid], m.pools[pid]),
              file=out)
    if m.pools:
        print("", file=out)
    print(f"max_osd {m.max_osd}", file=out)
    for i in range(m.max_osd):
        if m.exists(i):
            updown = "up  " if m.is_up(i) else "down"
            inout = "in " if m.osd_weight[i] > 0 else "out"
            print(f"osd.{i} {updown} {inout} weight "
                  f"{m.osd_weight[i] / 0x10000:g}", file=out)
    print("", file=out)
    for pg in sorted(m.pg_upmap_items):
        pairs = ",".join(f"{a}->{b}" for a, b in m.pg_upmap_items[pg])
        print(f"pg_upmap_items {pg} [{pairs}]", file=out)


class _ArgError(Exception):
    def __init__(self, msg: str, blank: bool = False):
        super().__init__(msg)
        self.blank = blank


class _Args:
    """ceph_argparse-shaped scanner: --flag, --flag val, --flag=val;
    missing/invalid values reproduce the reference's messages."""

    def __init__(self, argv):
        self.argv = list(argv)
        self.i = 0

    def done(self):
        return self.i >= len(self.argv)

    def cur(self):
        return self.argv[self.i]

    def take(self):
        v = self.argv[self.i]
        self.i += 1
        return v

    def witharg(self, *names: str):
        a = self.cur()
        for n in names:
            if a == n:
                if self.i + 1 >= len(self.argv):
                    raise _ArgError(f"Option {n} requires an "
                                    f"argument.", blank=True)
                self.i += 1
                return self.take()
            if a.startswith(n + "="):
                self.i += 1
                return a[len(n) + 1:]
        return None

    def intarg(self, *names: str):
        v = self.witharg(*names)
        if v is None:
            return None
        try:
            return int(v)
        except ValueError:
            raise _ArgError(f"The option value '{v}' is invalid")

    def floatarg(self, *names: str):
        v = self.witharg(*names)
        if v is None:
            return None
        try:
            return float(v)
        except ValueError:
            raise _ArgError(f"The option value '{v}' is invalid")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    fn = None
    createsimple = None
    create_from_conf = False
    conf = None
    clobber = with_default_pool = False
    pg_bits_arg = pgp_bits_arg = None
    mark_up_in = False
    mark_out = None
    clear_temp = False
    do_print = test_map_pgs_f = test_random = False
    import_crush = export_crush = None
    test_map_object = None
    test_map_pg = None
    pool = None
    upmap_file = None
    upmap_max = 100
    upmap_deviation = 0.01
    tree_fmt = None
    host_mapper = False
    pg_num_arg = None

    sc = _Args(argv)
    try:
        while not sc.done():
            a = sc.cur()
            if a in ("-h", "--help"):
                print(USAGE)
                return 1
            v = sc.intarg("--createsimple")
            if v is not None:
                createsimple = v
                continue
            v = sc.witharg("-c", "--conf")
            if v is not None:
                conf = v
                continue
            v = sc.intarg("--pg_bits")
            if v is not None:
                pg_bits_arg = v
                continue
            v = sc.intarg("--pgp_bits")
            if v is not None:
                pgp_bits_arg = v
                continue
            v = sc.intarg("--pg-num", "--pg_num")
            if v is not None:
                pg_num_arg = v
                continue
            v = sc.intarg("--mark-out")
            if v is not None:
                mark_out = v
                continue
            v = sc.intarg("--pool")
            if v is not None:
                pool = v
                continue
            v = sc.witharg("--import-crush")
            if v is not None:
                import_crush = v
                continue
            v = sc.witharg("--export-crush")
            if v is not None:
                export_crush = v
                continue
            v = sc.witharg("--test-map-object")
            if v is not None:
                test_map_object = v
                continue
            v = sc.witharg("--test-map-pg")
            if v is not None:
                test_map_pg = v
                continue
            v = sc.witharg("--upmap")
            if v is not None:
                upmap_file = v
                continue
            v = sc.intarg("--upmap-max")
            if v is not None:
                upmap_max = v
                continue
            v = sc.floatarg("--upmap-deviation")
            if v is not None:
                upmap_deviation = v
                continue
            v = sc.witharg("--tree")
            if v is not None:
                tree_fmt = v
                continue
            if a == "--create-from-conf":
                create_from_conf = True
            elif a == "--with-default-pool":
                with_default_pool = True
            elif a == "--clobber":
                clobber = True
            elif a == "--mark-up-in":
                mark_up_in = True
            elif a == "--clear-temp":
                clear_temp = True
            elif a == "--print":
                do_print = True
            elif a == "--test-map-pgs":
                test_map_pgs_f = True
            elif a == "--test-random":
                test_random = True
            elif a == "--host-mapper":
                host_mapper = True
            elif a.startswith("-"):
                print(f"unrecognized arg {a}", file=sys.stderr)
                print(USAGE)
                return 1
            else:
                if fn is not None:
                    print("osdmaptool: too many arguments",
                          file=sys.stderr)
                    print(USAGE)
                    return 1
                fn = a
            sc.take()
    except _ArgError as e:
        print(e)
        if e.blank:
            print("")
        return 1

    if fn is None:
        print("osdmaptool: must specify osdmap filename",
              file=sys.stderr)
        print(USAGE)
        return 1
    pg_bits = 6 if pg_bits_arg is None else pg_bits_arg
    pgp_bits = pg_bits if pgp_bits_arg is None else pgp_bits_arg

    print(f"osdmaptool: osdmap file '{fn}'", file=sys.stderr)
    modified = False
    creating = createsimple is not None or create_from_conf
    if creating and not clobber and os.path.exists(fn):
        print(f"osdmaptool: {fn} exists, --clobber to overwrite",
              file=sys.stderr)
        return 255
    if createsimple is not None:
        if createsimple < 1:
            print("osdmaptool: osd count must be > 0",
                  file=sys.stderr)
            return 1
        from ..osdmap.simple_build import build_simple
        if pg_bits_arg is None and not with_default_pool \
                and pg_num_arg is not None:
            m = createsimple_legacy(createsimple, pg_num_arg)
        else:
            m = build_simple(createsimple,
                             with_default_pool=with_default_pool,
                             pg_bits=pg_bits, pgp_bits=pgp_bits)
        m.epoch = 0              # inc_epoch below writes epoch 1
        modified = True
    elif create_from_conf:
        from ..osdmap.simple_build import build_from_conf
        if not conf:
            print("--create-from-conf requires -c <conffile>",
                  file=sys.stderr)
            return 1
        with open(conf) as f:
            conf_text = f.read()
        m = build_from_conf(conf_text,
                            with_default_pool=with_default_pool,
                            pg_bits=pg_bits, pgp_bits=pgp_bits)
        m.epoch = 0
        modified = True
    else:
        try:
            with open(fn, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            print(f"osdmaptool: couldn't open {fn}: can't open {fn}: "
                  f"(2) No such file or directory", file=sys.stderr)
            return 255
        try:
            m = pickle.loads(raw)
            assert isinstance(m, OSDMap)
        except Exception:
            print(f"osdmaptool: error decoding osdmap '{fn}'",
                  file=sys.stderr)
            return 255

    if mark_up_in:
        print("marking all OSDs up and in")
        from ..osdmap.simple_build import mark_up_in as _mui
        _mui(m)

    if mark_out is not None and 0 <= mark_out < m.max_osd:
        print(f"marking OSD@{mark_out} as out")
        from ..osdmap.simple_build import mark_out as _mo
        _mo(m, mark_out)

    if clear_temp:
        print("clearing pg/primary temp")
        m.pg_temp.clear()
        m.primary_temp.clear()

    if upmap_file:
        from ..osdmap.upmap import PendingInc
        from ..osdmap.upmap import calc_pg_upmaps as exact_upmaps
        print(f"writing upmap command output to: {upmap_file}")
        print("checking for upmap cleanups")
        print(f"upmap, max-count {upmap_max}, "
              f"max deviation {upmap_deviation:g}")
        inc = PendingInc()
        pools = {pool} if pool is not None else None
        exact_upmaps(m, upmap_deviation, upmap_max, pools, inc)
        # '-' means stdout (the USAGE's documented default)
        f = sys.stdout if upmap_file == "-" else open(upmap_file, "w")
        try:
            for pg in sorted(inc.old_pg_upmap_items):
                f.write(f"ceph osd rm-pg-upmap-items {pg}\n")
            for pg in sorted(inc.new_pg_upmap_items):
                pairs = " ".join(
                    f"{a} {b}" for a, b in inc.new_pg_upmap_items[pg])
                f.write(f"ceph osd pg-upmap-items {pg} {pairs}\n")
        finally:
            if f is not sys.stdout:
                f.close()

    if import_crush:
        from ..crush.binfmt import decode_crushmap
        try:
            with open(import_crush, "rb") as f:
                cbl = f.read()
            cw = decode_crushmap(cbl)
        except FileNotFoundError as e:
            print(f"osdmaptool: error reading crush map from "
                  f"{import_crush}: {e}", file=sys.stderr)
            return 1
        if cw.crush.max_devices > m.max_osd:
            print(f"osdmaptool: crushmap max_devices "
                  f"{cw.crush.max_devices} > osdmap max_osd "
                  f"{m.max_osd}", file=sys.stderr)
            return 1
        m.crush = cw
        m.epoch += 1             # the applied incremental's epoch
        m.crush_version = getattr(m, "crush_version", 1) + 1
        print(f"osdmaptool: imported {len(cbl)} byte crush map from "
              f"{import_crush}")
        modified = True

    if export_crush:
        from ..crush.binfmt import encode_crushmap
        with open(export_crush, "wb") as f:
            f.write(encode_crushmap(m.crush))
        print(f"osdmaptool: exported crush map to {export_crush}")

    if test_map_object:
        if pool is None:
            print("osdmaptool: assuming pool 1 (use --pool to "
                  "override)")
            pool = 1
        if pool not in m.pools:
            print(f"There is no pool {pool}", file=sys.stderr)
            return 1
        pg = m.map_to_pg(pool, test_map_object)
        p_ = m.pools[pool]
        from ..osdmap import ceph_stable_mod
        ps = ceph_stable_mod(pg.ps, p_.pg_num, p_.pg_num_mask)
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg_t(pool, ps))
        print(f" object '{test_map_object}' -> {pool}.{ps:x} -> "
              f"{acting}")

    if test_map_pg:
        try:
            pstr, sstr = test_map_pg.split(".", 1)
            pgid = pg_t(int(pstr), int(sstr, 16))
        except ValueError:
            print(f"osdmaptool: failed to parse pg '{test_map_pg}",
                  file=sys.stderr)
            print(USAGE)
            return 1
        print(f" parsed '{test_map_pg}' -> {pgid}")

        def _vec(v):
            return "[" + ",".join(str(o) for o in v) + "]"
        if pgid.pool in m.pools:
            raw, rawp = m.pg_to_raw_osds(pgid)
            up, upp, acting, actp = m.pg_to_up_acting_osds(pgid)
        else:
            raw, rawp, up, upp, acting, actp = \
                [], -1, [], -1, [], -1
        print(f"{pgid} raw ({_vec(raw)}, p{rawp}) "
              f"up ({_vec(up)}, p{upp}) "
              f"acting ({_vec(acting)}, p{actp})")

    if test_map_pgs_f:
        if pool is not None and pool not in m.pools:
            print(f"There is no pool {pool}", file=sys.stderr)
            return 1
        test_map_pgs(m, not host_mapper, sys.stdout,
                     test_random=test_random,
                     only_pool=-1 if pool is None else pool)

    nothing = not (do_print or tree_fmt or modified or export_crush
                   or import_crush or test_map_object or test_map_pg
                   or test_map_pgs_f or upmap_file)
    if nothing:
        print("osdmaptool: no action specified?", file=sys.stderr)
        print(USAGE)
        return 1

    if modified:
        m.epoch += 1             # osdmaptool.cc:638 inc_epoch

    if do_print:
        osdmap_print(m, sys.stdout)

    if tree_fmt:
        from ..crush.treedump import osd_tree_json, osd_tree_lines
        if tree_fmt in ("json", "json-pretty"):
            sys.stdout.write(osd_tree_json(m))
        else:
            for line in osd_tree_lines(m):
                print(line)

    if modified:
        print(f"osdmaptool: writing epoch {m.epoch} to {fn}")
        with open(fn, "wb") as f:
            pickle.dump(m, f)
    return 0


if __name__ == "__main__":
    # die silently on a closed pipe (`tool ... | head`), like the
    # C++ tools' default SIGPIPE disposition
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
