"""osdmaptool — inspect and exercise OSDMaps.

CLI surface mirrors the reference tool (src/tools/osdmaptool.cc):
--createsimple N builds a map, --test-map-pgs maps every PG of every pool
(the full-cluster remap benchmark harness, backed by OSDMapMapping's device
batch path), --test-map-object maps one object, --upmap runs the balancer
(calc_pg_upmaps), --mark-up-in resets osd states.  Maps are python pickles.
"""
from __future__ import annotations

import argparse
import pickle
import sys
import time

import numpy as np

from ..crush.constants import CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE
from ..osdmap import (
    CEPH_OSD_IN, Incremental, OSDMap, OSDMapMapping, TYPE_REPLICATED,
    pg_pool_t, pg_t,
)


def createsimple(n_osds: int, pg_num: int = 128,
                 osds_per_host: int = 4) -> OSDMap:
    m = OSDMap()
    m.set_max_osd(n_osds)
    cw = m.crush
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    hosts = []
    n_hosts = (n_osds + osds_per_host - 1) // osds_per_host
    for h in range(n_hosts):
        osds = list(range(h * osds_per_host,
                          min((h + 1) * osds_per_host, n_osds)))
        hid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}", osds,
                            [0x10000] * len(osds), id=-(h + 2))
        hosts.append((hid, len(osds)))
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default",
                  [h for h, _ in hosts],
                  [0x10000 * n for _, n in hosts], id=-1)
    for i in range(n_osds):
        m.set_osd(i, up=True, weight=CEPH_OSD_IN)
        cw.set_item_name(i, f"osd.{i}")
    rno = cw.add_simple_rule("replicated_rule", "default", "host",
                             mode="firstn")
    m.add_pool("rbd", pg_pool_t(type=TYPE_REPLICATED, size=3,
                                crush_rule=rno, pg_num=pg_num,
                                pgp_num=pg_num))
    m.epoch = 1
    return m


def _crush_item_weights(m: OSDMap) -> dict:
    """osd -> crush item weight, one pass over every bucket."""
    out: dict = {}
    for b in m.crush.crush.buckets:
        if b is None:
            continue
        for i, it in enumerate(b.items):
            if it >= 0:
                out[it] = b.item_weights[i]
    return out


def test_map_pgs(m: OSDMap, use_device: bool, out,
                 test_random: bool = False, only_pool: int = -1) -> None:
    """--test-map-pgs in the reference's output format
    (src/tools/osdmaptool.cc): per-pool pg_num lines, the per-IN-osd
    count table, ' in/avg/min/max' stats, and the size histogram —
    plus one trailing 'mapped ...' line naming the batch backend."""
    mapping = OSDMapMapping(use_device=use_device)
    t0 = time.perf_counter()
    if not test_random:
        mapping.update(m)
    dt = time.perf_counter() - t0
    count = np.zeros(m.max_osd, dtype=np.int64)
    first = np.zeros(m.max_osd, dtype=np.int64)
    primaries = np.zeros(m.max_osd, dtype=np.int64)
    sizes = np.zeros(30, dtype=np.int64)
    total_pgs = 0
    rng = np.random.default_rng()
    for pid in sorted(m.pools):
        if only_pool >= 0 and pid != only_pool:
            continue
        pool = m.pools[pid]
        print(f"pool {pid} pg_num {pool.pg_num}", file=out)
        for ps in range(pool.pg_num):
            total_pgs += 1
            if test_random:
                row = rng.integers(0, m.max_osd, size=pool.size)
                prim = int(row[0])
            else:
                pm = mapping.pools[pid]
                row = [o for o in pm.acting[ps] if o != CRUSH_ITEM_NONE]
                prim = int(pm.acting_primary[ps])
            sizes[len(row)] += 1
            for o in row:
                count[o] += 1
            if len(row):
                first[row[0]] += 1
            if prim >= 0:
                primaries[prim] += 1
    n_in = 0
    total = 0
    min_osd = max_osd = -1
    crush_w = _crush_item_weights(m)
    print("#osd\tcount\tfirst\tprimary\tc wt\twt", file=out)
    for o in range(m.max_osd):
        if m.osd_weight[o] == 0:
            continue
        cw = crush_w.get(o, 0)
        if cw <= 0:
            continue
        n_in += 1
        print(f"osd.{o}\t{count[o]}\t{first[o]}\t{primaries[o]}"
              f"\t{cw / 0x10000:g}\t{m.osd_weight[o] / 0x10000:g}",
              file=out)
        total += count[o]
        if count[o] and (min_osd < 0 or count[o] < count[min_osd]):
            min_osd = o
        if count[o] and (max_osd < 0 or count[o] > count[max_osd]):
            max_osd = o
    avg = total // n_in if n_in else 0
    dev = 0.0
    for o in range(m.max_osd):
        if m.osd_weight[o] == 0 or crush_w.get(o, 0) <= 0:
            continue
        dev += float(avg - count[o]) ** 2
    dev = (dev / n_in) ** 0.5 if n_in else 0.0
    edev = ((total / n_in) * (1.0 - 1.0 / n_in)) ** 0.5 if n_in else 0.0
    print(f" in {n_in}", file=out)
    print(f" avg {avg} stddev {dev:g} ({dev / avg if avg else 0:g}x) "
          f"(expected {edev:g} {edev / avg if avg else 0:g}x))",
          file=out)
    if min_osd >= 0:
        print(f" min osd.{min_osd} {count[min_osd]}", file=out)
    if max_osd >= 0:
        print(f" max osd.{max_osd} {count[max_osd]}", file=out)
    for i in range(4):
        print(f"size {i}\t{sizes[i]}", file=out)
    backends = ",".join(sorted(set(mapping.last_backend.values()))) \
        if not test_random else "random"
    print(f"mapped {total_pgs} pgs in {dt * 1000:.1f} ms "
          f"(backend: {backends})", file=out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="osdmaptool")
    p.add_argument("mapfn", nargs="?", help="osdmap file")
    p.add_argument("--createsimple", type=int, metavar="N_OSDS")
    p.add_argument("--create-from-conf", action="store_true")
    p.add_argument("-c", "--conf", metavar="CONFFILE")
    p.add_argument("--with-default-pool", action="store_true")
    p.add_argument("--pg_bits", type=int, default=None)
    p.add_argument("--pgp_bits", type=int, default=None)
    p.add_argument("--mark-out", type=int, default=-1, metavar="OSD")
    p.add_argument("--pg-num", type=int, default=128)
    p.add_argument("--test-map-pgs", action="store_true")
    p.add_argument("--test-random", action="store_true")
    p.add_argument("--import-crush", metavar="CRUSHFILE")
    p.add_argument("--test-map-object", metavar="OBJ")
    p.add_argument("--pool", type=int, default=-1)
    p.add_argument("--upmap", metavar="OUTFILE",
                   help="calculate pg upmaps and write the changes")
    p.add_argument("--upmap-max", type=int, default=100)
    p.add_argument("--upmap-deviation", type=float, default=0.01)
    p.add_argument("--mark-up-in", action="store_true")
    p.add_argument("--host-mapper", action="store_true")
    p.add_argument("--print", dest="do_print", action="store_true")
    args = p.parse_args(argv)

    pg_bits = 6 if args.pg_bits is None else args.pg_bits
    pgp_bits = pg_bits if args.pgp_bits is None else args.pgp_bits

    if (args.createsimple or args.create_from_conf) and not args.mapfn:
        p.print_help()
        return 1
    if args.create_from_conf and not args.conf:
        print("--create-from-conf requires -c <conffile>",
              file=sys.stderr)
        return 1

    if args.createsimple:
        if args.pg_bits is not None or args.with_default_pool:
            # the reference shape: pool 1 'rbd', pg_num = N << pg_bits,
            # osds NOT yet up/in (--mark-up-in does that)
            from ..osdmap.simple_build import build_simple
            m = build_simple(args.createsimple,
                             with_default_pool=args.with_default_pool,
                             pg_bits=pg_bits, pgp_bits=pgp_bits)
        else:
            m = createsimple(args.createsimple, args.pg_num)
        print(f"osdmaptool: osdmap file '{args.mapfn}'")
        if args.mapfn:
            with open(args.mapfn, "wb") as f:
                pickle.dump(m, f)
        print(f"osdmaptool: writing epoch {m.epoch} to {args.mapfn}")
        return 0

    if args.create_from_conf:
        # the reference's --create-from-conf (build_simple_with_pool
        # over the conf's [osd.N] host/rack locations)
        from ..osdmap.simple_build import build_from_conf
        with open(args.conf) as f:
            conf_text = f.read()
        m = build_from_conf(conf_text,
                            with_default_pool=args.with_default_pool,
                            pg_bits=pg_bits, pgp_bits=pgp_bits)
        print(f"osdmaptool: osdmap file '{args.mapfn}'")
        with open(args.mapfn, "wb") as f:
            pickle.dump(m, f)
        print(f"osdmaptool: writing epoch {m.epoch} to {args.mapfn}")
        return 0

    if not args.mapfn:
        p.print_help()
        return 1
    print(f"osdmaptool: osdmap file '{args.mapfn}'")
    with open(args.mapfn, "rb") as f:
        m = pickle.load(f)

    if args.mark_up_in:
        print("marking all OSDs up and in")
        from ..osdmap.simple_build import mark_up_in
        mark_up_in(m)

    if args.mark_out >= 0 and args.mark_out < m.max_osd:
        print(f"marking OSD@{args.mark_out} as out")
        from ..osdmap.simple_build import mark_out as _mark_out
        _mark_out(m, args.mark_out)

    if args.do_print:
        print(f"epoch {m.epoch}")
        print(f"max_osd {m.max_osd}")
        for pid in sorted(m.pools):
            pool = m.pools[pid]
            print(f"pool {pid} '{m.pool_name[pid]}' type {pool.type} "
                  f"size {pool.size} pg_num {pool.pg_num} "
                  f"crush_rule {pool.crush_rule}")

    if args.test_map_object:
        pid = args.pool if args.pool >= 0 else sorted(m.pools)[0]
        pg = m.map_to_pg(pid, args.test_map_object)
        pool = m.pools[pid]
        from ..osdmap import ceph_stable_mod
        ps = ceph_stable_mod(pg.ps, pool.pg_num, pool.pg_num_mask)
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg_t(pid, ps))
        print(f" object '{args.test_map_object}' -> {pid}.{ps:x} -> "
              f"up {up} acting {acting}")
        return 0

    if args.import_crush:
        from .crushtool import load_map
        m.crush = load_map(args.import_crush)
        with open(args.mapfn, "wb") as f:
            pickle.dump(m, f)
        return 0

    if args.test_map_pgs:
        if args.pool >= 0 and args.pool not in m.pools:
            print(f"There is no pool {args.pool}", file=sys.stderr)
            return 1
        test_map_pgs(m, not args.host_mapper, sys.stdout,
                     test_random=args.test_random, only_pool=args.pool)
        return 0

    if args.upmap:
        # decision-identical with the reference's calc_pg_upmaps
        # (osdmap/upmap.py); the stdout/file formats mirror
        # src/tools/osdmaptool.cc print_inc_upmaps
        from ..osdmap.upmap import PendingInc
        from ..osdmap.upmap import calc_pg_upmaps as exact_upmaps
        print(f"writing upmap command output to: {args.upmap}")
        print("checking for upmap cleanups")
        print(f"upmap, max-count {args.upmap_max}, "
              f"max deviation {args.upmap_deviation:g}")
        inc = PendingInc()
        pools = {args.pool} if args.pool >= 0 else None
        exact_upmaps(m, args.upmap_deviation, args.upmap_max, pools, inc)
        with open(args.upmap, "w") as f:
            for pg in sorted(inc.old_pg_upmap_items):
                f.write(f"ceph osd rm-pg-upmap-items {pg}\n")
            for pg in sorted(inc.new_pg_upmap_items):
                pairs = " ".join(f"{a} {b}"
                                 for a, b in inc.new_pg_upmap_items[pg])
                f.write(f"ceph osd pg-upmap-items {pg} {pairs}\n")
        return 0

    return 0


if __name__ == "__main__":
    sys.exit(main())
