"""osdmaptool — inspect and exercise OSDMaps.

CLI surface mirrors the reference tool (src/tools/osdmaptool.cc):
--createsimple N builds a map, --test-map-pgs maps every PG of every pool
(the full-cluster remap benchmark harness, backed by OSDMapMapping's device
batch path), --test-map-object maps one object, --upmap runs the balancer
(calc_pg_upmaps), --mark-up-in resets osd states.  Maps are python pickles.
"""
from __future__ import annotations

import argparse
import pickle
import sys
import time

import numpy as np

from ..crush.constants import CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE
from ..osdmap import (
    CEPH_OSD_IN, Incremental, OSDMap, OSDMapMapping, TYPE_REPLICATED,
    pg_pool_t, pg_t,
)
from ..osdmap.balancer import calc_pg_upmaps


def createsimple(n_osds: int, pg_num: int = 128,
                 osds_per_host: int = 4) -> OSDMap:
    m = OSDMap()
    m.set_max_osd(n_osds)
    cw = m.crush
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    hosts = []
    n_hosts = (n_osds + osds_per_host - 1) // osds_per_host
    for h in range(n_hosts):
        osds = list(range(h * osds_per_host,
                          min((h + 1) * osds_per_host, n_osds)))
        hid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}", osds,
                            [0x10000] * len(osds), id=-(h + 2))
        hosts.append((hid, len(osds)))
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default",
                  [h for h, _ in hosts],
                  [0x10000 * n for _, n in hosts], id=-1)
    for i in range(n_osds):
        m.set_osd(i, up=True, weight=CEPH_OSD_IN)
        cw.set_item_name(i, f"osd.{i}")
    rno = cw.add_simple_rule("replicated_rule", "default", "host",
                             mode="firstn")
    m.add_pool("rbd", pg_pool_t(type=TYPE_REPLICATED, size=3,
                                crush_rule=rno, pg_num=pg_num,
                                pgp_num=pg_num))
    m.epoch = 1
    return m


def test_map_pgs(m: OSDMap, use_device: bool, out) -> None:
    mapping = OSDMapMapping(use_device=use_device)
    t0 = time.perf_counter()
    mapping.update(m)
    dt = time.perf_counter() - t0
    count = np.zeros(m.max_osd, dtype=np.int64)
    primaries = np.zeros(m.max_osd, dtype=np.int64)
    total = 0
    size_total = 0
    for pid, pm in mapping.pools.items():
        for ps in range(pm.acting.shape[0]):
            row = pm.acting[ps]
            total += 1
            for o in row:
                if o != CRUSH_ITEM_NONE:
                    count[o] += 1
                    size_total += 1
            p = pm.acting_primary[ps]
            if p >= 0:
                primaries[p] += 1
    used = count[count > 0]
    print(f"pool {sorted(mapping.pools)} pg_num "
          f"{[m.pools[p].pg_num for p in sorted(mapping.pools)]}",
          file=out)
    print(f"#osd\tcount\tfirst\tprimary\tc wt\twt", file=out)
    for o in range(m.max_osd):
        print(f"osd.{o}\t{count[o]}\t{primaries[o]}\t{primaries[o]}"
              f"\t{m.crush.crush.max_devices and 1.0}\t"
              f"{m.osd_weight[o] / 0x10000:.4g}", file=out)
    avg = size_total / max(1, len(used))
    print(f" avg {avg:.4g} stddev {used.std():.4g} "
          f"(expected {np.sqrt(avg):.4g})", file=out)
    print(f" min osd.{int(count.argmin())} {int(count.min())}", file=out)
    print(f" max osd.{int(count.argmax())} {int(count.max())}", file=out)
    print(f"size {size_total // max(1, total)}\t{total}", file=out)
    backends = ",".join(sorted(set(mapping.last_backend.values())))
    print(f"mapped {total} pgs in {dt * 1000:.1f} ms "
          f"(backend: {backends})", file=out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="osdmaptool")
    p.add_argument("mapfn", nargs="?", help="osdmap file")
    p.add_argument("--createsimple", type=int, metavar="N_OSDS")
    p.add_argument("--pg-num", type=int, default=128)
    p.add_argument("--test-map-pgs", action="store_true")
    p.add_argument("--test-map-object", metavar="OBJ")
    p.add_argument("--pool", type=int, default=-1)
    p.add_argument("--upmap", metavar="OUTFILE",
                   help="calculate pg upmaps and write the changes")
    p.add_argument("--upmap-max", type=int, default=100)
    p.add_argument("--upmap-deviation", type=float, default=0.01)
    p.add_argument("--mark-up-in", action="store_true")
    p.add_argument("--host-mapper", action="store_true")
    p.add_argument("--print", dest="do_print", action="store_true")
    args = p.parse_args(argv)

    if args.createsimple:
        m = createsimple(args.createsimple, args.pg_num)
        if args.mapfn:
            with open(args.mapfn, "wb") as f:
                pickle.dump(m, f)
        print(f"osdmaptool: osdmap file '{args.mapfn}'")
        print(f"osdmaptool: writing epoch {m.epoch} to {args.mapfn}")
        return 0

    if not args.mapfn:
        p.print_help()
        return 1
    with open(args.mapfn, "rb") as f:
        m = pickle.load(f)

    if args.mark_up_in:
        for o in range(m.max_osd):
            m.set_osd(o, up=True, weight=CEPH_OSD_IN)

    if args.do_print:
        print(f"epoch {m.epoch}")
        print(f"max_osd {m.max_osd}")
        for pid in sorted(m.pools):
            pool = m.pools[pid]
            print(f"pool {pid} '{m.pool_name[pid]}' type {pool.type} "
                  f"size {pool.size} pg_num {pool.pg_num} "
                  f"crush_rule {pool.crush_rule}")

    if args.test_map_object:
        pid = args.pool if args.pool >= 0 else sorted(m.pools)[0]
        pg = m.map_to_pg(pid, args.test_map_object)
        pool = m.pools[pid]
        from ..osdmap import ceph_stable_mod
        ps = ceph_stable_mod(pg.ps, pool.pg_num, pool.pg_num_mask)
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg_t(pid, ps))
        print(f" object '{args.test_map_object}' -> {pid}.{ps:x} -> "
              f"up {up} acting {acting}")
        return 0

    if args.test_map_pgs:
        test_map_pgs(m, not args.host_mapper, sys.stdout)
        return 0

    if args.upmap:
        inc = Incremental(epoch=m.epoch + 1)
        pools = [args.pool] if args.pool >= 0 else None
        n = calc_pg_upmaps(m, args.upmap_deviation, args.upmap_max,
                           pools, inc)
        with open(args.upmap, "w") as f:
            for pg, items in sorted(inc.new_pg_upmap_items.items(),
                                    key=lambda kv: str(kv[0])):
                pairs = " ".join(f"{a} {b}" for a, b in items)
                f.write(f"ceph osd pg-upmap-items {pg} {pairs}\n")
        print(f"wrote {n} upmap item changes to {args.upmap}")
        return 0

    return 0


if __name__ == "__main__":
    sys.exit(main())
