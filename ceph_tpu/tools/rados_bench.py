"""rados bench — end-to-end pool IO benchmark on the vstart-lite cluster.

The reference's qa tier drives `rados bench` against a localhost cluster
(qa/standalone/erasure-code/test-erasure-code.sh:21-53); this tool spins a
MiniCluster with an EC (or replicated) pool and measures full-stack
write/read throughput — client → primary → batched device EC encode →
shard fan-out → memstore and back.
"""
from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rados_bench")
    p.add_argument("seconds", type=int, nargs="?", default=5)
    p.add_argument("mode", choices=("write", "seq"), nargs="?",
                   default="write")
    p.add_argument("--osds", type=int, default=7)
    p.add_argument("--pool-type", choices=("ec", "replicated"),
                   default="ec")
    p.add_argument("--plugin", default="tpu")
    p.add_argument("-k", type=int, default=4)
    p.add_argument("-m", type=int, default=2)
    p.add_argument("--object-size", type=int, default=1 << 20)
    p.add_argument("--pg-num", type=int, default=16)
    args = p.parse_args(argv)

    from ..cluster import MiniCluster
    c = MiniCluster(n_osds=args.osds)
    if args.pool_type == "ec":
        c.create_ec_pool("bench", k=args.k, m=args.m, pg_num=args.pg_num,
                         plugin=args.plugin)
    else:
        c.create_replicated_pool("bench", pg_num=args.pg_num)
    client = c.client("client.bench")
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=args.object_size,
                        dtype=np.uint8).tobytes()

    # warm (compiles the device encode path)
    client.write_full("bench", "warm", data)

    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < args.seconds:
        assert client.write_full("bench", f"obj{n}", data) == 0
        n += 1
    dt = time.perf_counter() - t0
    wmbs = n * args.object_size / dt / (1 << 20)
    print(f"write: {n} objects x {args.object_size} B in {dt:.2f}s = "
          f"{wmbs:.1f} MB/s")

    if args.mode == "seq" or True:
        t0 = time.perf_counter()
        for i in range(n):
            assert client.read("bench", f"obj{i}") == data
        dt = time.perf_counter() - t0
        rmbs = n * args.object_size / dt / (1 << 20)
        print(f"seq read: {n} objects in {dt:.2f}s = {rmbs:.1f} MB/s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
