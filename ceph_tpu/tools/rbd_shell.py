"""rbd CLI frontend: the reference shell's command matching, help
pages, and argv error contracts (src/tools/rbd/Shell.cc), byte-exact
against the recorded transcripts src/test/cli/rbd/{help,
not-enough-args, too-many-args, invalid-snap-usage}.t.

Structure mirrors the reference's split: the spec table
(rbd_specs.py, generated from the recorded help) plays the role of
the per-action get_arguments registrations; rbd_optfmt renders help;
this module does command-spec extraction, option/positional parsing
(boost::program_options semantics for the error paths), and the
execute-stage validation messages from src/tools/rbd/Utils.cc.
Implemented verbs are bridged onto the live RBD API via
rbd_cli.run's dialect.
"""
from __future__ import annotations

import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .rbd_optfmt import Opt, Positional, print_action_help, \
    print_command_list
from .rbd_specs import SPECS

APP = "rbd"
BANNER = "Command-line interface for managing Ceph RBD images."
EINVAL = 22

GLOBAL_OPTS = [
    Opt("conf", "path to cluster configuration", short="c"),
    Opt("cluster", "cluster name"),
    Opt("id", "client id (without 'client.' prefix)"),
    Opt("user", "client id (without 'client.' prefix)"),
    Opt("name", "client name", short="n"),
    Opt("mon_host", "monitor host", short="m"),
    Opt("secret", "path to secret key (deprecated)"),
    Opt("keyfile", "path to secret key", short="K"),
    Opt("keyring", "path to keyring", short="k"),
]

FEATURE_NAMES = {"layering", "striping", "exclusive-lock", "object-map",
                 "fast-diff", "deep-flatten", "journaling", "data-pool"}

# bridged verbs that never mutate the cluster: a successful run of one
# of these must NOT rewrite the checkpoint
READONLY_SPECS = {"list", "info", "disk-usage", "status", "export",
                  "export-diff", "children", "diff", "snap list",
                  "lock list"}


class Action:
    def __init__(self, entry: dict):
        self.spec: Tuple[str, ...] = tuple(entry["spec"])
        self.alias: Optional[Tuple[str, ...]] = (
            tuple(entry["alias"]) if entry["alias"] else None)
        self.desc: str = entry["desc"]
        self.positionals = [Positional(n, d, v)
                            for n, d, v in entry["positionals"]]
        self.options = [Opt(long, d, short, has_arg, req)
                        for short, long, has_arg, req, d
                        in entry["options"]]
        self.help: str = entry["help"]


ACTIONS = [Action(e) for e in SPECS]

# every no-arg option long name across all commands: get_command_spec
# must know these are switches before the command is even identified
# (Shell.cc get_switch_arguments + at::SWITCH_ARGUMENTS role)
SWITCH_LONGS = {o.long for a in ACTIONS for o in a.options
                if not o.has_arg}
SWITCH_SHORTS = {o.short for a in ACTIONS for o in a.options
                 if not o.has_arg and o.short}


class ArgvError(Exception):
    """boost::program_options-stage failure: exit 1."""


class ValidationError(Exception):
    """execute-stage failure (utils.cc get_* helpers): exit EINVAL."""


def get_command_spec(arguments: Sequence[str]) -> List[str]:
    spec: List[str] = []
    i = 0
    while i < len(arguments):
        arg = arguments[i]
        if arg in ("-h", "--help"):
            return ["help"]
        if arg == "--":
            spec.extend(arguments[i + 1:])
            return spec
        if arg.startswith("-"):
            # a non-switch option consumes the next token as its value
            # unless the value is attached ("--x=v" or "-pv")
            long = arg[2:] if arg.startswith("--") else None
            short = arg[1:2] if not arg.startswith("--") else None
            is_switch = (long in SWITCH_LONGS
                         or (short is not None and short in SWITCH_SHORTS))
            attached = "=" in arg or (short is not None and len(arg) > 2)
            if not is_switch and not attached:
                i += 1
        else:
            spec.append(arg)
        i += 1
    return spec


def find_action(words: Sequence[str]
                ) -> Tuple[Optional[Action], Optional[Tuple[str, ...]],
                           bool]:
    for a in ACTIONS:
        if len(a.spec) <= len(words) and \
                tuple(words[:len(a.spec)]) == a.spec:
            return a, a.spec, False
        if a.alias and len(a.alias) <= len(words) and \
                tuple(words[:len(a.alias)]) == a.alias:
            return a, a.alias, True
    return None, None, False


def parse_arguments(action: Action, matched: Tuple[str, ...],
                    arguments: Sequence[str]
                    ) -> Tuple[Dict[str, str], List[str]]:
    """boost-style pass: returns (option values, positional args after
    the command words).  Raises ArgvError with the messages the
    reference's po catch blocks print."""
    by_long: Dict[str, Opt] = {}
    by_short: Dict[str, Opt] = {}
    for o in list(action.options) + GLOBAL_OPTS:
        by_long[o.long] = o
        if o.short:
            by_short[o.short] = o
    vm: Dict[str, str] = {}
    pos: List[str] = []
    rest_positional = False
    i = 0
    while i < len(arguments):
        arg = arguments[i]
        if rest_positional or not arg.startswith("-") or arg == "-":
            pos.append(arg)
        elif arg == "--":
            rest_positional = True
        else:
            if arg.startswith("--"):
                name, eq, val = arg[2:].partition("=")
                o = by_long.get(name)
            else:
                name, eq, val = arg[1:2], "", ""
                o = by_short.get(name)
                if o is not None and o.has_arg and len(arg) > 2:
                    # "-pvalue" attached-value form
                    val, eq = arg[2:], "="
            if o is None:
                raise ArgvError(f"unrecognised option '{arg}'")
            if o.has_arg and not eq:
                if i + 1 >= len(arguments):
                    raise ArgvError(
                        f"the required argument for option "
                        f"'--{o.long}' is missing")
                i += 1
                val = arguments[i]
            elif not o.has_arg and eq:
                raise ArgvError(
                    f"option '--{o.long}' does not take any arguments")
            vm[o.long] = val if o.has_arg else "1"
        i += 1
    # first len(matched) positionals are the command words themselves
    if pos[:len(matched)] != list(matched):
        raise ArgvError("failed to parse command")
    pos = pos[len(matched):]
    variadic = bool(action.positionals) and action.positionals[-1].variadic
    if not variadic and len(pos) > len(action.positionals):
        raise ArgvError("too many arguments")
    # NOTE: required options (e.g. bench --io-type) are NOT enforced
    # here — the reference's Shell calls po::store without notify(),
    # so requiredness surfaces from the action itself, after the
    # image/snap checks (invalid-snap-usage.t pins that order)
    return vm, pos


def _parse_spec(spec: str) -> Tuple[str, str, str]:
    """[pool/]image[@snap] -> (pool, image, snap)."""
    pool, slash, rest = spec.partition("/")
    if not slash:
        pool, rest = "", spec
    image, at, snap = rest.partition("@")
    return pool, image, snap if at else ""


def _image_check(spec: str, vm: Dict[str, str], presence: str,
                 dest: bool = False) -> Tuple[str, str, str]:
    """utils::get_pool_image_snapshot_names error contract.

    presence: 'none' | 'permitted' | 'required'."""
    prefix = "destination " if dest else ""
    pool, image, snap = _parse_spec(spec)
    if not image:
        image = vm.get("dest" if dest else "image", "")
    if not snap:
        snap = "" if dest else vm.get("snap", "")
    if spec and "@" in spec and presence == "none":
        raise ValidationError(
            f"{prefix}snapname specified for a command that doesn't "
            "use it")
    if not image:
        raise ValidationError(f"{prefix}image name was not specified")
    if presence == "required" and not snap:
        raise ValidationError(f"{prefix}snap name was not specified")
    return pool, image, snap


_PRESENCE = {
    "image-spec": "none",
    "source-image-spec": "none",
    "image-or-snap-spec": "permitted",
    "source-image-or-snap-spec": "permitted",
    "snap-spec": "required",
    "source-snap-spec": "required",
    "group-snap-spec": "required",
}


def validate(action: Action, vm: Dict[str, str],
             pos: List[str]) -> Dict[str, object]:
    """The execute-stage checks each reference action performs before
    touching the cluster; raises ValidationError(msg) -> exit 22."""
    spec_words = " ".join(action.spec)
    out: Dict[str, object] = {}

    def val(i: int) -> str:
        return pos[i] if i < len(pos) else ""

    for idx, p in enumerate(action.positionals):
        name = p.name
        if name in _PRESENCE:
            out["image"] = _image_check(val(idx), vm, _PRESENCE[name])
        elif name == "dest-image-spec":
            pool, image, snap = _image_check(val(idx), vm, "none",
                                             dest=True)
            out["dest"] = (pool or vm.get("dest-pool", ""), image, snap)
        elif name == "dest-snap-spec":
            spec = val(idx)
            _, image, snap = _parse_spec(spec)
            snap = snap or vm.get("dest-snap", "")
            if not snap:
                raise ValidationError(
                    "destination snap name was not specified")
            out["dest-snap"] = snap
        elif name in ("path-name", "diff1-path", "diff2-path"):
            v = val(idx) or vm.get("path", "")
            if not v:
                raise ValidationError(
                    {"diff1-path": "first diff was not specified",
                     "diff2-path": "second diff was not specified",
                     }.get(name, "path was not specified"))
            out[name] = v
        elif name == "features":
            feats = pos[idx:]
            if not feats:
                raise ValidationError(
                    "at least one feature name must be specified")
            out["features"] = feats
        elif name == "key":
            if spec_words.startswith("image-meta"):
                if not val(idx):
                    raise ValidationError(
                        "metadata key was not specified")
                out["key"] = val(idx)
        elif name == "value":
            if spec_words.startswith("image-meta"):
                if not val(idx):
                    raise ValidationError(
                        "metadata value was not specified")
                out["value"] = val(idx)
        elif name == "lock-id":
            if not val(idx):
                raise ValidationError("lock id was not specified")
            out["lock-id"] = val(idx)
        elif name == "locker":
            if not val(idx):
                raise ValidationError("locker was not specified")
            out["locker"] = val(idx)
        elif name == "image-or-snap-or-device-spec":
            if not val(idx) and not vm.get("image"):
                raise ValidationError(
                    "unmap requires either image name or device path")
            out["target"] = val(idx)
        elif name == "mode":
            if val(idx) not in ("image", "pool"):
                raise ValidationError(
                    "must specify 'image' or 'pool' mode.")
            out["mode"] = val(idx)
        elif name == "remote-cluster-spec":
            if not val(idx):
                raise ValidationError("remote cluster was not specified")
            out["remote"] = val(idx)
        elif name == "uuid":
            if not val(idx):
                raise ValidationError("must specify peer uuid")
            out["uuid"] = val(idx)
        elif name == "pool-name":
            out["pool"] = val(idx) or vm.get("pool", "")
        elif name in ("group-spec", "journal-spec", "source-journal-spec",
                      "dest-journal-spec"):
            kind = "group" if "group" in name else "journal"
            _, obj, _snap = _parse_spec(val(idx))
            if not obj and not vm.get(kind):
                raise ValidationError(f"{kind} name was not specified")
            out[name] = val(idx)
        elif name == "image-id":
            if not val(idx) and not vm.get("image-id"):
                raise ValidationError("image id was not specified")
            out["image-id"] = val(idx)
        elif name == "device-spec":
            if not val(idx):
                raise ValidationError("device was not specified")
            out["device"] = val(idx)
    # feature values are validated at po-store time (ImageFeatures
    # validator): any name outside the feature set is a po error
    if "features" in out:
        for f in out["features"]:  # type: ignore[union-attr]
            if f not in FEATURE_NAMES:
                raise ArgvError("the argument for option is invalid")
    return out


def execute_action(action: Action, vm: Dict[str, str],
                   parsed: Dict[str, object], checkpoint: Optional[str]
                   ) -> int:
    """Bridge the validated command onto the live RBD API (rbd_cli
    dialect).  Only reached when argv validation passed; commands
    outside the implemented storage surface report EOPNOTSUPP."""
    from . import rbd_cli

    def n(size: str) -> int:
        mult = {"B": 1, "K": 1 << 10, "M": 1 << 20,
                "G": 1 << 30, "T": 1 << 40}
        s = size.strip()
        try:
            if s and s[-1].upper() in mult:
                return int(float(s[:-1]) * mult[s[-1].upper()])
            return int(float(s) * (1 << 20))   # bare numbers: megabytes
        except ValueError:
            raise ValidationError("the argument for option is invalid")

    spec = " ".join(action.spec)
    img = parsed.get("image")
    pool = (img[0] if img else "") or vm.get("pool", "") or "rbd"
    name = img[1] if img else ""
    snap = img[2] if img else ""
    dest = parsed.get("dest")
    argv: Optional[List[str]] = None
    if spec == "create":
        argv = ["-p", pool, "create", name, "--size", str(n(
            vm.get("size", "0")))]
    elif spec == "list":
        argv = ["-p", parsed.get("pool") or "rbd", "ls"]  # type: ignore
    elif spec == "info":
        argv = ["-p", pool, "info", name]
    elif spec == "disk-usage":
        argv = ["-p", pool, "du", name + (f"@{snap}" if snap else "")]
    elif spec == "resize":
        argv = ["-p", pool, "resize", name, "--size", str(n(
            vm.get("size", "0")))]
    elif spec == "remove":
        argv = ["-p", pool, "rm", name]
    elif spec == "flatten":
        argv = ["-p", pool, "flatten", name]
    elif spec == "clone":
        argv = ["-p", pool, "clone", f"{name}@{snap}",
                dest[1]]  # type: ignore[index]
    elif spec == "copy":
        argv = ["-p", pool, "cp", name, dest[1]]  # type: ignore[index]
        if snap:
            argv += ["--snap", snap]
    elif spec in ("export", "export-diff", "import", "import-diff"):
        path = parsed.get("path-name", "")
        if spec == "export":
            argv = ["-p", pool, "export", name, path]  # type: ignore
        elif spec == "export-diff":
            argv = ["-p", pool, "export-diff", name,
                    path]  # type: ignore[list-item]
            if vm.get("from-snap"):
                argv += ["--from-snap", vm["from-snap"]]
            if snap:
                argv += ["--snap", snap]
        elif spec == "import":
            argv = ["-p", dest[0] or "rbd", "import",  # type: ignore
                    path, dest[1]]  # type: ignore[index]
        else:
            argv = ["-p", pool, "import-diff", path,
                    name]  # type: ignore[list-item]
    elif spec.startswith("snap "):
        verb = action.spec[1]
        verbmap = {"create": "create", "remove": "rm", "list": "ls",
                   "protect": "protect", "unprotect": "unprotect",
                   "rollback": "rollback"}
        if verb in verbmap:
            target = name + (f"@{snap}" if snap else "")
            argv = ["-p", pool, "snap", verbmap[verb], target]
    elif spec == "lock add":
        argv = ["-p", pool, "lock", "add", name,
                "--cookie", parsed.get("lock-id", "")]  # type: ignore
    elif spec == "lock list":
        argv = ["-p", pool, "lock", "ls", name]
    elif spec == "lock remove":
        argv = ["-p", pool, "lock", "rm", name,
                "--cookie", parsed.get("lock-id", ""),  # type: ignore
                "--locker", parsed.get("locker", "")]  # type: ignore
    elif spec == "bench":
        # rbd bench (tools/rbd/action/Bench.cc): drive IO at the image
        # through the librbd-lite API and report the reference's
        # SEC/OPS/OPS/SEC table + elapsed summary
        if checkpoint is None:
            print("rbd: error opening cluster (no --checkpoint)",
                  file=sys.stderr)
            return 1
        from ..cluster import MiniCluster
        c = MiniCluster.restore(checkpoint)
        from ..rbd import Image
        import time as _time
        io_type = vm.get("io-type", "")
        if io_type not in ("read", "write", "readwrite", "rw"):
            print("rbd: --io-type must be read, write, or "
                  "readwrite(rw)", file=sys.stderr)
            return EINVAL
        io_size = n(vm.get("io-size", "4K"))
        io_total = n(vm.get("io-total", "1G")) if "io-total" in vm \
            else (1 << 20)              # liliputian default for tests
        pattern = vm.get("io-pattern", "seq")
        if pattern not in ("seq", "rand"):
            print(f"rbd: --io-pattern must be rand or seq",
                  file=sys.stderr)
            return EINVAL
        img = Image(c.client("client.rbd-bench"), pool, name)
        size = img.size()
        if io_size <= 0 or io_size > size:
            print(f"rbd: --io-size must be > 0 and fit the image "
                  f"({size} bytes)", file=sys.stderr)
            return EINVAL
        ops_total = max(1, io_total // io_size)
        payload = b"\xbe" * io_size
        rng_seed = 0x5eed
        t0 = _time.perf_counter()
        last_tick, ops_done = t0, 0
        print("  SEC       OPS   OPS/SEC   BYTES/SEC")
        for i in range(ops_total):
            if pattern == "rand":
                rng_seed = (rng_seed * 1103515245 + 12345) & 0x7FFFFFFF
                off = (rng_seed * io_size) % max(size - io_size, 1)
                off -= off % io_size
            else:
                off = (i * io_size) % max(size - io_size + 1, 1)
            write_this = io_type in ("write",) or \
                (io_type in ("readwrite", "rw") and i % 2 == 0)
            if write_this:
                img.write(off, payload)
            else:
                img.read(off, io_size)
            ops_done += 1
            now = _time.perf_counter()
            if now - last_tick >= 1.0:
                dt = now - t0
                print(f"{int(dt):5d}  {ops_done:8d}  "
                      f"{ops_done / dt:8.2f}  "
                      f"{ops_done * io_size / dt:.2f}")
                last_tick = now
        dt = max(_time.perf_counter() - t0, 1e-9)
        print(f"elapsed: {int(dt):5d}  ops: {ops_total:8d}  "
              f"ops/sec: {ops_total / dt:8.2f}  "
              f"bytes/sec: {ops_total * io_size / dt:.2f}")
        if io_type != "read":
            c.checkpoint(checkpoint)    # bench writes persist
        return 0
    elif spec == "rename":
        from ..cluster import MiniCluster
        if checkpoint is None:
            print("rbd: error opening cluster (no --checkpoint)",
                  file=sys.stderr)
            return 1
        c = MiniCluster.restore(checkpoint)
        from ..rbd import RBD
        RBD(c.client("client.rbd-shell")).rename(
            pool, name, dest[1])  # type: ignore[index]
        c.checkpoint(checkpoint)
        return 0
    if argv is None:
        print(f"rbd: '{spec}' is not implemented in this build",
              file=sys.stderr)
        return 95                      # EOPNOTSUPP
    if checkpoint is None:
        print("rbd: error opening cluster (no --checkpoint)",
              file=sys.stderr)
        return 1
    from ..cluster import MiniCluster
    c = MiniCluster.restore(checkpoint)
    rc = rbd_cli.run(c, c.client("client.rbd-shell"), argv)
    if rc == 0 and spec not in READONLY_SPECS:
        # rados.py's CLI contract: mutations persist by checkpointing
        # the cluster back to the same directory; reads don't rewrite
        c.checkpoint(checkpoint)
    return rc


def execute(arguments: Sequence[str],
            checkpoint: Optional[str] = None) -> int:
    args = list(arguments)
    words = get_command_spec(args)
    if not words or words == ["help"]:
        sys.stdout.write(print_command_list(
            APP, BANNER,
            [(a.spec, a.alias, a.desc) for a in ACTIONS], GLOBAL_OPTS))
        return 0
    if words[0] == "help":
        action, _, is_alias = find_action(words[1:])
        if action is None:
            sys.stderr.write("error: unknown option '"
                             + " ".join(words[1:]) + "'\n\n")
            sys.stdout.write(print_command_list(
                APP, BANNER,
                [(a.spec, a.alias, a.desc) for a in ACTIONS],
                GLOBAL_OPTS))
            return 1
        shown = action.alias if is_alias and action.alias else action.spec
        sys.stdout.write(print_action_help(
            APP, shown, action.positionals, action.options, action.desc,
            action.help))
        return 0
    action, matched, _ = find_action(words)
    if action is None:
        sys.stderr.write("error: unknown option '"
                         + " ".join(words) + "'\n\n")
        sys.stdout.write(print_command_list(
            APP, BANNER,
            [(a.spec, a.alias, a.desc) for a in ACTIONS], GLOBAL_OPTS))
        return 1
    try:
        vm, pos = parse_arguments(action, matched, args)
        parsed = validate(action, vm, pos)
        return execute_action(action, vm, parsed, checkpoint)
    except ArgvError as e:
        print(f"rbd: {e}", file=sys.stderr)
        return 1
    except ValidationError as e:
        print(f"rbd: {e}", file=sys.stderr)
        return EINVAL


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    checkpoint = None
    if "--checkpoint" in args:
        i = args.index("--checkpoint")
        if i + 1 >= len(args):
            print("rbd: option '--checkpoint' requires an argument",
                  file=sys.stderr)
            return 1
        checkpoint = args[i + 1]
        del args[i:i + 2]
    return execute(args, checkpoint)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
