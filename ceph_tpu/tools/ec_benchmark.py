"""ceph_erasure_code_benchmark equivalent.

CLI mirrors the reference harness (src/test/erasure-code/
ceph_erasure_code_benchmark.cc): `-p <plugin> -P k=K -P m=M -S <size>
-i <iterations> -w encode|decode [-e erasures] [--erasures-generation
exhaustive]`, printing `<seconds>\t<KiB>` like :187.  Extra knob
`--batch S` exercises the batched device path (S objects per device call)
— the TPU-native mode the reference cannot express.
"""
from __future__ import annotations

import argparse
import itertools
import sys
import time

import numpy as np

from ..ec import create_erasure_code


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="ec_benchmark")
    p.add_argument("-p", "--plugin", default="jerasure")
    p.add_argument("-P", "--parameter", action="append", default=[],
                   help="profile parameter k=v")
    p.add_argument("-S", "--size", type=int, default=1 << 20)
    p.add_argument("-i", "--iterations", type=int, default=1)
    p.add_argument("-w", "--workload", choices=("encode", "decode"),
                   default="encode")
    p.add_argument("-e", "--erasures", type=int, default=1)
    p.add_argument("--erasures-generation", default="random",
                   choices=("random", "exhaustive"))
    p.add_argument("--batch", type=int, default=0,
                   help="objects per batched device call (tpu plugin)")
    p.add_argument("--dispatch", type=int, default=0,
                   help="concurrent objects coalesced per flush through "
                        "the dynamic-batching dispatch scheduler "
                        "(docs/DISPATCH.md); 0 = off")
    p.add_argument("--erased", type=int, action="append", default=[])
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    profile = {"plugin": args.plugin}
    for kv in args.parameter:
        k, _, v = kv.partition("=")
        profile[k] = v
    codec = create_erasure_code(profile)
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    size = args.size
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=size, dtype=np.uint8)

    if args.dispatch and args.workload != "encode":
        print("--dispatch only measures the encode workload; refusing "
              "to print an uncoalesced decode as --dispatch output",
              file=sys.stderr)
        return 1

    if args.workload == "encode" and args.dispatch:
        # N concurrent submissions per iteration, coalesced into one
        # padded device call by the scheduler (the cross-PG shape the
        # OSD sees under load; --batch is the within-one-op shape)
        from ..common.config import g_conf
        from ..dispatch import KIND_ENCODE, batchable, g_dispatcher
        from ..osd.ecutil import stripe_info_t
        C = codec.get_chunk_size(size)
        if not batchable(codec, C, KIND_ENCODE):
            print(f"plugin {args.plugin!r} is not dispatch-batchable "
                  f"(no coalescing would happen); refusing to print a "
                  f"serial measurement as --dispatch output",
                  file=sys.stderr)
            return 1
        sinfo = stripe_info_t(k, k * C)
        padded = np.resize(data, k * C)
        want = set(range(n))
        saved = {nm: g_conf.values.get(nm) for nm in
                 ("ec_dispatch_batch_max", "ec_dispatch_batch_window_us")}
        g_conf.set_val("ec_dispatch_batch_max", args.dispatch)
        g_conf.set_val("ec_dispatch_batch_window_us", 10**7)
        try:
            for f in [g_dispatcher.submit_encode(sinfo, codec, padded,
                                                 want)
                      for _ in range(args.dispatch)]:
                f.result()            # warm + compile
            t0 = time.perf_counter()
            for _ in range(args.iterations):
                futs = [g_dispatcher.submit_encode(sinfo, codec, padded,
                                                   want)
                        for _ in range(args.dispatch)]
                for f in futs:
                    f.result()
            dt = time.perf_counter() - t0
        finally:
            for nm, v in saved.items():
                g_conf.rm_val(nm) if v is None else g_conf.set_val(nm, v)
            g_dispatcher.flush()
        kib = args.iterations * args.dispatch * size // 1024
        print(f"{dt:.6f}\t{kib}")
        return 0

    if args.workload == "encode":
        if args.batch and hasattr(codec, "encode_batch"):
            C = codec.get_chunk_size(size)
            stripes = np.ascontiguousarray(
                np.resize(data, (args.batch, k, C)))
            codec.encode_batch(stripes)  # warm + compile
            t0 = time.perf_counter()
            for _ in range(args.iterations):
                codec.encode_batch(stripes)
            dt = time.perf_counter() - t0
            kib = args.iterations * args.batch * size // 1024
        else:
            t0 = time.perf_counter()
            for _ in range(args.iterations):
                codec.encode(set(range(n)), data)
            dt = time.perf_counter() - t0
            kib = args.iterations * size // 1024
        print(f"{dt:.6f}\t{kib}")
        return 0

    # decode workload
    enc = codec.encode(set(range(n)), data)
    if args.erasures_generation == "exhaustive":
        patterns = list(itertools.combinations(range(n), args.erasures))
    elif args.erased:
        patterns = [tuple(args.erased)]
    else:
        patterns = [tuple(sorted(rng.choice(n, size=args.erasures,
                                            replace=False).tolist()))
                    for _ in range(args.iterations)]
    want = set(range(k))
    t0 = time.perf_counter()
    done = 0
    for i in range(args.iterations):
        lost = patterns[i % len(patterns)]
        have = {j: enc[j] for j in range(n) if j not in lost}
        codec.decode(want, have)
        done += 1
    dt = time.perf_counter() - t0
    print(f"{dt:.6f}\t{done * size // 1024}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
