"""ceph — cluster status CLI against a checkpointed mini cluster.

The python `ceph` tool analog (src/ceph.in + mon command surface,
mon/MonCommands.h): status/health/df plus the osd and pg inspection
verbs, driven from a checkpoint directory like tools/rados.py.

  status | health | df
  osd tree           (CrushTreeDumper-style hierarchy with weights)
  osd df             (per-osd object/byte usage from the stores)
  osd pool ls [detail]  (pool names / pg_pool_t summary with flags,
                         quotas, snaps mode, tiering)
  pg stat            (per-state PG counts)
  pg dump            (one line per PG: state, up/acting sets)
  pg <pgid> query    (one pg's peering/log state as json)
  pg scrub|deep-scrub [pool.ps]  (offline consistency pass report)
  log last [n]       (recent cluster-log entries)
  config-key get|ls  (replicated config-key store)
  osd pool create|set|rm         (pool admin; persists to the
                                  checkpoint, rm needs the
                                  double-name + flag confirmation)
  tell <who> injectargs|...      (runtime config, admin socket)

Inspection verbs never write the checkpoint back; the pool-admin
verbs (and tell-driven writes inside bench-style flows) do.
"""
from __future__ import annotations

import argparse
import json
import sys


def _osd_tree(c) -> None:
    cw = c.mon.osdmap.crush
    m = cw.crush

    def walk(item: int, depth: int) -> None:
        indent = "  " * depth
        if item >= 0:
            w = c.mon.osdmap.osd_weight[item] / 0x10000 \
                if item < len(c.mon.osdmap.osd_weight) else 0
            up = "up" if c.mon.osdmap.is_up(item) else "down"
            print(f"{indent}osd.{item}\tweight {w:.3f}\t{up}")
            return
        b = m.bucket(item)
        if b is None:
            return
        name = cw.get_item_name(item) or str(item)
        tname = cw.get_type_name(b.type) or str(b.type)
        print(f"{indent}{tname} {name}")
        for child in b.items:
            walk(child, depth + 1)

    roots = set(b.id for b in m.buckets if b is not None)
    children = set()
    for b in m.buckets:
        if b is None:
            continue
        children.update(i for i in b.items if i < 0)
    for r in sorted(roots - children, reverse=True):
        walk(r, 0)


def _osd_df(c) -> None:
    print("ID\tOBJECTS\tBYTES\tSTATUS")
    for i, osd in sorted(c.osds.items()):
        n_obj = 0
        n_bytes = 0
        for cid in osd.store.list_collections():
            if cid == "meta":
                continue      # map history, not client data
            for ho in osd.store.list_objects(cid):
                n_obj += 1
                n_bytes += osd.store.stat(cid, ho)
        status = "up" if c.mon.osdmap.is_up(i) else "down"
        if i < len(c.mon.osdmap.osd_weight) and \
                c.mon.osdmap.osd_weight[i] == 0:
            status += "+out"
        print(f"{i}\t{n_obj}\t{n_bytes}\t{status}")


def _pg_lines(c):
    return c.primary_pgs()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph")
    p.add_argument("--cluster", required=True,
                   help="checkpoint directory (MiniCluster.checkpoint)")
    p.add_argument("verb", choices=["status", "health", "df", "osd",
                                    "pg", "log", "config-key", "fs",
                                    "mds", "mon", "tell", "daemon"])
    p.add_argument("args", nargs="*")
    # parse_known_args: `tell X injectargs --debug_osd 9` carries
    # dashed tokens that are arguments to injectargs, not to ceph
    a, extra = p.parse_known_args(argv)
    a.args = list(a.args) + list(extra)

    from ..cluster import MiniCluster
    c = MiniCluster.restore(a.cluster)
    v, rest = a.verb, a.args
    if v == "status":
        print(json.dumps({
            "health": c.health(),
            "epoch": c.mon.osdmap.epoch,
            "num_osds": len(c.osds),
            "num_up": sum(1 for i in c.osds
                          if c.mon.osdmap.is_up(i)),
            "pools": len(c.mon.osdmap.pools),
            "pg_states": c.pg_states(),
        }, indent=2))
    elif v == "health":
        print(c.health())
    elif v in ("fs", "mds"):
        # ceph fs status / ceph mds stat (MDSMonitor fsmap surface)
        st = c.mon.fs_status()
        if v == "mds" or rest[:1] == ["stat"]:
            act = ",".join(st["active"]) or "-"
            sby = len(st["standby"])
            print(f"{act} up:active, {sby} up:standby")
        else:
            print(json.dumps(st, indent=2, sort_keys=True))
    elif v == "mon":
        # ceph mon dump: the epoched MonMap (MonMap::print)
        if rest[:1] in ([], ["dump"]):
            for line in c.mon.monmap.print_lines():
                print(line)
        else:
            print(f"unknown: mon {' '.join(rest)}", file=sys.stderr)
            return 1
    elif v == "df":
        for pid, name in sorted(c.mon.osdmap.pool_name.items()):
            pool = c.mon.osdmap.pools[pid]
            kind = "erasure" if pool.is_erasure() else "replicated"
            print(f"{name}\t{kind}\tpg_num={pool.pg_num}")
    elif v == "osd":
        sub = rest[0] if rest else "tree"
        if sub == "tree":
            _osd_tree(c)
        elif sub == "df":
            _osd_df(c)
        elif sub in ("out", "in", "reweight"):
            # ceph osd out/in/reweight <id> [w] (MonCommands.h): mark
            # an osd out/in or set its override weight; commits an
            # epoch and persists like the pool-admin verbs
            ident = rest[1] if len(rest) > 1 else ""
            if ident.startswith("osd."):
                ident = ident[len("osd."):]
            if not ident.isdigit():
                print(f"usage: ceph osd {sub} <id>"
                      + (" <weight 0..1>" if sub == "reweight"
                         else ""), file=sys.stderr)
                return 1
            oid_ = int(ident)
            if not c.mon.osdmap.exists(oid_):
                print(f"osd.{oid_} does not exist", file=sys.stderr)
                return 1
            already = (sub == "out" and not c.mon.osdmap.is_in(oid_)) \
                or (sub == "in" and c.mon.osdmap.is_in(oid_))
            if already:
                # no epoch churn for a no-op, like the reference mon
                print(f"osd.{oid_} is already {sub}")
                return 0
            if sub == "out":
                c.mark_osd_out(oid_)     # the cluster helper bundles
                                         # publish + pump + recovery
            elif sub == "in":
                c.mon.mark_osd_in(oid_)
            else:
                try:
                    w = float(rest[2])
                except (IndexError, ValueError):
                    print("usage: ceph osd reweight <id> "
                          "<weight 0..1>", file=sys.stderr)
                    return 1
                if not 0.0 <= w <= 1.0:
                    print("weight must be in [0, 1]",
                          file=sys.stderr)
                    return 1
                from ..osdmap import Incremental
                inc = Incremental()
                inc.new_weight[oid_] = int(w * 0x10000)
                c.mon.publish(inc)
            if sub != "out":             # out's helper already settled
                c.network.pump()
                c.run_recovery()
            c.checkpoint(a.cluster)
            print(f"osd.{oid_} {sub} done "
                  f"(epoch {c.mon.osdmap.epoch})")
        elif sub == "pool" and rest[1:2] == ["create"]:
            # ceph osd pool create <name> <pg_num>
            #   [replicated | erasure [profile]]   (MonCommands.h)
            if len(rest) < 4 or not rest[3].isdigit() \
                    or int(rest[3]) < 1:
                print("usage: ceph osd pool create <name> <pg_num> "
                      "[replicated|erasure [profile]]  (pg_num >= 1)",
                      file=sys.stderr)
                return 1
            name, pg_num = rest[2], int(rest[3])
            if name in c.mon.osdmap.pool_name.values():
                # the reference treats re-creation as success
                print(f"pool '{name}' already exists")
                return 0
            kind = rest[4] if len(rest) > 4 else "replicated"
            try:
                if kind == "replicated":
                    c.create_replicated_pool(name, pg_num=pg_num)
                elif kind == "erasure":
                    profile = rest[5] if len(rest) > 5 else None
                    if profile:
                        if profile not in \
                                c.mon.osdmap.erasure_code_profiles:
                            print(f"unknown ec profile '{profile}'",
                                  file=sys.stderr)
                            return 1
                        # the mon's own path honors EVERY profile key
                        # (failure domain, stripe_unit, technique...)
                        c.mon.create_ec_pool(name, profile,
                                             pg_num=pg_num)
                        c.mon.publish()
                        c.network.pump()
                        c.run_recovery()
                    else:
                        c.create_ec_pool(name, pg_num=pg_num)
                else:
                    print(f"unknown pool type '{kind}'",
                          file=sys.stderr)
                    return 1
            except (ValueError, KeyError, RuntimeError) as e:
                print(f"pool create failed: {e}", file=sys.stderr)
                return 1
            c.checkpoint(a.cluster)
            print(f"pool '{name}' created")
        elif sub == "pool" and rest[1:2] == ["rm"]:
            # the reference's double-name + flag confirmation
            if len(rest) < 4 or rest[2] != rest[3] or \
                    "--yes-i-really-really-mean-it" not in rest:
                print("Error EPERM: WARNING: this will *PERMANENTLY "
                      "DESTROY* all data stored in pool. If you are "
                      "ABSOLUTELY CERTAIN that is what you want, pass "
                      "the pool name *twice*, followed by "
                      "--yes-i-really-really-mean-it.",
                      file=sys.stderr)
                return 1
            try:
                c.delete_pool(rest[2])
            except (KeyError, ValueError) as e:
                print(f"pool rm failed: {e}", file=sys.stderr)
                return 1
            c.checkpoint(a.cluster)
            print(f"pool '{rest[2]}' removed")
        elif sub == "pool" and rest[1:2] == ["set"]:
            # ceph osd pool set <name> <var> <val>
            if len(rest) < 5:
                print("usage: ceph osd pool set <name> <var> <val>",
                      file=sys.stderr)
                return 1
            name, var, val = rest[2], rest[3], rest[4]
            try:
                if var in ("pg_num", "pgp_num", "quota_max_objects",
                           "quota_max_bytes"):
                    if var == "pg_num":
                        c.mon.set_pool_pg_num(name, int(val))
                    elif var == "pgp_num":
                        c.mon.set_pool_pgp_num(name, int(val))
                    elif var == "quota_max_objects":
                        c.mon.set_pool_quota(name,
                                             max_objects=int(val))
                    else:
                        c.mon.set_pool_quota(name, max_bytes=int(val))
                    # the setters stage into the working map; COMMIT
                    # an epoch so OSDs (and restores, which rebuild
                    # from incrementals) actually see it
                    c.mon.publish()
                elif var in ("size", "min_size"):
                    pid = c.mon.osdmap.lookup_pg_pool_name(name)
                    if pid < 0:
                        raise KeyError(name)
                    from ..osdmap import Incremental
                    inc = Incremental()
                    import copy
                    pool = copy.deepcopy(c.mon.osdmap.pools[pid])
                    v = int(val)
                    new_size = v if var == "size" else pool.size
                    new_min = v if var == "min_size" else pool.min_size
                    if new_size < 1 or new_min < 1 or \
                            new_min > new_size:
                        raise ValueError(
                            f"size {new_size} / min_size {new_min} "
                            "out of range")
                    setattr(pool, var, v)
                    inc.new_pools[pid] = pool
                    inc.new_pool_names[pid] = name
                    c.mon.publish(inc)
                else:
                    print(f"unknown variable '{var}'",
                          file=sys.stderr)
                    return 1
            except (KeyError, ValueError) as e:
                print(f"pool set failed: {e!r}", file=sys.stderr)
                return 1
            c.network.pump()
            c.run_recovery()
            c.checkpoint(a.cluster)
            print(f"set pool '{name}' {var} to {val}")
        elif sub == "pool" and rest[1:2] == ["ls"]:
            # ceph osd pool ls [detail] (MonCommands.h)
            if rest[2:] not in ([], ["detail"]):
                print(f"unknown: osd pool ls {' '.join(rest[2:])}",
                      file=sys.stderr)
                return 1
            detail = rest[2:3] == ["detail"]
            from ..osdmap.types import (
                FLAG_EC_OVERWRITES, FLAG_FULL, FLAG_FULL_QUOTA,
                FLAG_NEARFULL,
            )
            for pid, name in sorted(c.mon.osdmap.pool_name.items()):
                if not detail:
                    print(name)
                    continue
                pool = c.mon.osdmap.pools[pid]
                kind = "erasure" if pool.is_erasure() else "replicated"
                flags = [fname for bit, fname in [
                    (FLAG_FULL, "full"),
                    (FLAG_FULL_QUOTA, "full_quota"),
                    (FLAG_NEARFULL, "nearfull"),
                    (FLAG_EC_OVERWRITES, "ec_overwrites"),
                ] if pool.has_flag(bit)]
                parts = [f"pool {pid} '{name}' {kind}",
                         f"size {pool.size}",
                         f"min_size {pool.min_size}",
                         f"crush_rule {pool.crush_rule}",
                         f"pg_num {pool.pg_num}",
                         f"pgp_num {pool.pgp_num}"]
                if pool.erasure_code_profile:
                    parts.append(
                        f"profile {pool.erasure_code_profile}")
                if flags:
                    parts.append("flags " + "+".join(flags))
                if pool.quota_max_objects:
                    parts.append(
                        f"max_objects {pool.quota_max_objects}")
                if pool.quota_max_bytes:
                    parts.append(f"max_bytes {pool.quota_max_bytes}")
                if pool.selfmanaged:
                    parts.append("selfmanaged_snaps")
                elif pool.snaps:
                    parts.append(f"snaps {len(pool.snaps)}")
                if pool.read_tier >= 0:
                    parts.append(f"read_tier {pool.read_tier}")
                if pool.tier_of >= 0:
                    parts.append(f"tier_of {pool.tier_of}")
                print(" ".join(parts))
        else:
            print(f"unknown: osd {sub}", file=sys.stderr)
            return 1
    elif v == "pg":
        sub = rest[0] if rest else "stat"
        if sub == "stat":
            counts = {}
            for _pgid, pg in _pg_lines(c):
                counts[pg.state] = counts.get(pg.state, 0) + 1
            print(json.dumps(counts))
        elif sub == "dump":
            for pgid, pg in sorted(_pg_lines(c)):
                print(f"{pgid[0]}.{pgid[1]:x}\t{pg.state}"
                      f"\tup={pg.up}\tacting={pg.acting}"
                      f"\tlast_scrub={pg.last_scrub_stamp:.0f}"
                      f"\tlast_deep_scrub={pg.last_deep_scrub_stamp:.0f}")
        elif sub == "query" or (len(rest) > 1 and rest[1] == "query"):
            # ceph pg <pgid> query (PG::Query role): one pg's peering
            # and log state as json.  pgids are the canonical pg_t
            # rendering (HEX ps) only — accepting decimal too would
            # make ids like 0.10 ambiguous
            if len(rest) < 2:
                print("usage: ceph pg <pgid> query", file=sys.stderr)
                return 1
            want = rest[1] if sub == "query" else rest[0]
            from ..os_store import parse_pg_from_cid
            for pgid, pg in _pg_lines(c):
                if f"{pgid[0]}.{pgid[1]:x}" == want:
                    n_obj = 0
                    for cid in pg.osd.store.list_collections():
                        if parse_pg_from_cid(cid) == pgid \
                                and not cid.endswith("_meta"):
                            n_obj += len(
                                pg.osd.store.list_objects(cid))
                    print(json.dumps({
                        "pgid": f"{pgid[0]}.{pgid[1]:x}",
                        "state": pg.state,
                        "up": list(pg.up),
                        "acting": list(pg.acting),
                        "acting_primary": pg.acting_primary,
                        "last_update": pg.pg_log.head,
                        "log_tail": pg.pg_log.tail,
                        "log_entries": len(pg.pg_log.entries),
                        "objects_on_primary": n_obj,
                        "last_scrub_stamp": pg.last_scrub_stamp,
                        "last_deep_scrub_stamp":
                            pg.last_deep_scrub_stamp,
                    }, indent=2, sort_keys=True))
                    break
            else:
                print(f"pg {want} does not exist", file=sys.stderr)
                return 1
        elif sub in ("scrub", "deep-scrub"):
            # ceph pg scrub/deep-scrub <pool.ps> (MonCommands.h role);
            # the restored cluster is ephemeral, so this reports what
            # the pass found rather than mutating daemon state
            want = rest[1] if len(rest) > 1 else None
            ran, matched = 0, 0
            for pgid, pg in _pg_lines(c):
                # canonical pg_t rendering only (hex ps)
                if want and want != f"{pgid[0]}.{pgid[1]:x}":
                    continue
                matched += 1
                if pg.start_scrub(deep=(sub == "deep-scrub")):
                    ran += 1
            if want and not matched:
                print(f"pg {want} does not exist", file=sys.stderr)
                return 1
            c.network.pump()
            print(json.dumps({"scrubbed": ran,
                              "declined": matched - ran, "deep":
                              sub == "deep-scrub",
                              "pg_states": c.pg_states()}))
        else:
            print(f"unknown: pg {sub}", file=sys.stderr)
            return 1
    elif v == "log":
        # ceph log last [n] (LogMonitor history)
        sub = rest[0] if rest else "last"
        if sub != "last":
            print(f"unknown: log {sub}", file=sys.stderr)
            return 1
        try:
            n = int(rest[1]) if len(rest) > 1 else 20
        except ValueError:
            print(f"log last: not a count: {rest[1]!r}", file=sys.stderr)
            return 1
        for stamp, who, level, text in c.mon.log_last(n):
            print(f"{stamp:.1f} {who} {level}: {text}")
    elif v in ("tell", "daemon"):
        # `ceph tell <who> injectargs --opt val ...` and
        # `ceph daemon <who> <asok command> [k=v ...]` — runtime
        # reconfiguration/introspection over the admin socket.  Like
        # the reference, injectargs is NOT durable: it mutates the
        # running process only (checkpoints don't carry it).
        if len(rest) < 2:
            print(f"usage: ceph {v} <who> <command> [args...]",
                  file=sys.stderr)
            return 1
        who, cmd, cargs = rest[0], rest[1], rest[2:]
        if cmd == "injectargs":
            if len(cargs) == 1 and " " in cargs[0]:
                # the reference's quoted form:
                # ceph tell osd.0 injectargs '--debug-osd 20'
                cargs = cargs[0].split()
            changed = {}
            i = 0
            while i < len(cargs):
                tok = cargs[i]
                if not tok.startswith("--"):
                    print(f"injectargs: expected --option, got "
                          f"'{tok}'", file=sys.stderr)
                    return 1
                name, eq, val = tok[2:].partition("=")
                name = name.replace("-", "_")
                if not eq:
                    if i + 1 >= len(cargs):
                        print(f"injectargs: missing value for "
                              f"--{name}", file=sys.stderr)
                        return 1
                    i += 1
                    val = cargs[i]
                # ONE set path: the asok 'config set' hook owns
                # validation + observer notification
                try:
                    out = c.admin_socket.execute(
                        "config set", {"name": name, "value": val})
                except ValueError as e:
                    print(f"injectargs: {e}", file=sys.stderr)
                    return 1
                changed[name] = out[name]
                i += 1
            print(json.dumps(changed, sort_keys=True))
        else:
            # multi-word asok commands may arrive as separate shell
            # words (`daemon mon.a config show`): everything up to the
            # first k=v token is the command
            words = [cmd]
            kv = {}
            for t in cargs:
                if "=" in t:
                    k, _, vv = t.partition("=")
                    kv[k] = vv
                else:
                    words.append(t)
            try:
                out = c.admin_socket.execute(" ".join(words), kv)
            except (KeyError, ValueError) as e:
                print(f"admin socket: {e}", file=sys.stderr)
                return 1
            print(json.dumps(out, indent=2, sort_keys=True,
                             default=repr))
    elif v == "config-key":
        sub = rest[0] if rest else "dump"
        if sub == "dump":
            print(json.dumps(c.mon.config_key_dump(), indent=2,
                             sort_keys=True))
        elif sub == "get" and len(rest) > 1:
            val = c.mon.config_key_get(rest[1])
            if val is None:
                print(f"no such key {rest[1]!r}", file=sys.stderr)
                return 1
            print(val)
        elif sub == "exists" and len(rest) > 1:
            ok = c.mon.config_key_get(rest[1]) is not None
            print(json.dumps({"exists": ok}))
            return 0 if ok else 1
        else:
            print(f"unknown: config-key {sub}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
