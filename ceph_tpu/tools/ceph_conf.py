"""ceph-conf: the configuration query tool (src/tools/ceph_conf.cc),
byte-exact against the reference's recorded transcripts
(src/test/cli/ceph-conf/*.t).

Semantics replicated from the reference:
  - ``--lookup KEY`` (the default action when a bare key is given)
    searches the CONF FILE sections in order — the ``-s`` list if
    given, else ``[<type>.<id>] [<type>] [global]`` derived from
    ``--name`` (md_config_t::get_val_from_conf_file); silent exit 1
    when absent.
  - ``--show-config-value KEY`` resolves a REGISTERED option
    (override -> file -> default) and errors with "option not found"
    for unknown keys (md_config_t::get_val).
  - ``$metavariable`` expansion ($cluster/$type/$id/$name/$host and
    config-key references) with the reference's loop-detection
    report (md_config_t::expand_meta).
  - ``CEPH_CONF``/``CEPH_ARGS`` environment handling, including the
    "did not load config file, using default settings" soft-failure
    path vs the hard ``global_init`` failure for an explicit ``-c``.
"""
from __future__ import annotations

import configparser
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

from ..common.config import ConfigProxy

VALID_TYPES = ("auth", "mon", "osd", "mds", "mgr", "client")

USAGE = """Ceph configuration query tool

USAGE
ceph-conf <flags> <action>

ACTIONS
  -L|--list-all-sections          List all sections
  -l|--list-sections <prefix>     List sections with the given prefix
  --filter-key <key>              Filter section list to only include sections
                                  with given key defined.
  --filter-key-value <key>=<val>  Filter section list to only include sections
                                  with given key/value pair.
  --lookup <key>                  Print a configuration setting to stdout.
                                  Returns 0 (success) if the configuration setting is
                                  found; 1 otherwise.
  -r|--resolve-search             search for the first file that exists and
                                  can be opened in the resulted comma
                                  delimited search list.
  -D|--dump-all                   dump all variables.

FLAGS
  --name name                     Set type.id
  [-s <section>]                  Add to list of sections to search
  [--format plain|json|json-pretty]
                                  dump variables in plain text, json or pretty
                                  json

If there is no action given, the action will default to --lookup.

EXAMPLES
$ ceph-conf --name mon.0 -c /etc/ceph/ceph.conf 'mon addr'
Find out what the value of 'mon addr' is for monitor 0.

$ ceph-conf -l mon
List sections beginning with 'mon'.

RETURN CODE
Return code will be 0 on success; error code otherwise.
"""

NO_ACTION = ("You must give an action, such as --lookup or "
             "--list-all-sections.\nPass --help for more help.")


def _norm_key(k: str) -> str:
    return k.replace(" ", "_").replace("-", "_")


class ConfFile:
    """Parsed ceph.conf: ordered sections of normalized key/value."""

    def __init__(self) -> None:
        self.sections: Dict[str, Dict[str, str]] = {}

    @classmethod
    def parse(cls, path: str) -> "ConfFile":
        cp = configparser.ConfigParser(interpolation=None, strict=False,
                                       delimiters=("=",),
                                       comment_prefixes=(";", "#"))
        cp.optionxform = _norm_key  # type: ignore[assignment]
        with open(path) as f:
            cp.read_string(f.read())
        out = cls()
        for sec in cp.sections():
            out.sections[sec] = dict(cp.items(sec))
        return out

    def get(self, section: str, key: str) -> Optional[str]:
        return self.sections.get(section, {}).get(key)

    def names(self) -> List[str]:
        ns = set(self.sections) | {"global"}
        return sorted(ns)


class Expander:
    """$var expansion with the reference's loop report."""

    META = ("cluster", "type", "id", "name", "host", "pid")
    TOKEN = re.compile(r"\$(\w+)")

    def __init__(self, meta: Dict[str, str], resolver) -> None:
        self.meta = meta
        self.resolver = resolver       # key -> raw value or None

    def expand(self, value: str,
               stack: Optional[List[Tuple[str, str]]] = None) -> str:
        stack = stack or []

        def sub(m: "re.Match[str]") -> str:
            var = m.group(1)
            if var in self.META:
                return self.meta.get(var, "")
            if any(k == var for k, _ in stack):
                frame_key, frame_raw = stack[-1]
                sys.stdout.write(
                    f"variable expansion loop at "
                    f"{frame_key}={frame_raw}\n")
                sys.stdout.write("expansion stack: \n")
                for k, raw in stack:
                    sys.stdout.write(f"{k}={raw}\n")
                return m.group(0)
            raw = self.resolver(var)
            if raw is None:
                return m.group(0)
            return self.expand(raw, stack + [(var, raw)])

        return self.TOKEN.sub(sub, value)


def _parse_name(name: str) -> Tuple[str, str]:
    type_, dot, id_ = name.partition(".")
    if not dot or type_ not in VALID_TYPES:
        print(f"error parsing '{name}': expected string of the form "
              f"TYPE.ID, valid types are: {', '.join(VALID_TYPES)}")
        raise SystemExit(1)
    return type_, id_


def _soft_parse_failure(path: str) -> None:
    ts = time.strftime("%Y-%m-%d %H:%M:%S.000000")
    tid = "7f%010x" % (os.getpid() & 0xFFFFFFFFFF)
    err = (f"{ts} {tid} -1 ")
    sys.stderr.write(err + "did not load config file, using default "
                     "settings.\n")
    for _ in range(2):
        sys.stderr.write(err + "Errors while parsing config file!\n")
        sys.stderr.write(err + f"parse_file: cannot open {path}: (2) "
                         "No such file or directory\n")


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    # CEPH_ARGS tokens are prepended, exactly like global_init
    env_args = os.environ.get("CEPH_ARGS", "")
    if env_args:
        args = env_args.split() + args

    conf_path: Optional[str] = None
    conf_explicit = False
    name = "client.admin"
    cluster = "ceph"
    sections: List[str] = []
    action: Optional[Tuple[str, str]] = None
    overrides: Dict[str, str] = {}
    lookup_key: Optional[str] = None
    dump_format = "plain"

    def norm_flag(a: str) -> str:
        return a.replace("_", "-")

    i = 0
    while i < len(args):
        a = args[i]
        na = norm_flag(a) if a.startswith("--") else a
        val = None
        if a.startswith("--") and "=" in a:
            na, _, val = norm_flag(a.split("=", 1)[0]), "=", \
                a.split("=", 1)[1]

        def need() -> str:
            nonlocal i
            if val is not None:
                return val
            i += 1
            if i >= len(args):
                print(NO_ACTION)
                raise SystemExit(1)
            return args[i]

        if na in ("-h", "--help"):
            sys.stdout.write(USAGE)
            return 1
        elif na in ("-c", "--conf"):
            conf_path = need()
            conf_explicit = True
        elif na in ("-n", "--name"):
            name = need()
        elif na == "--cluster":
            cluster = need()
        elif na in ("-s", "--section"):
            sections.append(need())
        elif na in ("-L", "--list-all-sections"):
            action = ("list-sections", "")
        elif na in ("-l", "--list-sections"):
            action = ("list-sections", need())
        elif na == "--lookup":
            lookup_key = need()
        elif na == "--show-config-value":
            action = ("show-config-value", need())
        elif na in ("-D", "--dump-all", "--show-config"):
            action = ("dump", "")
        elif na == "--filter-key":
            action = ("filter-key", need())
        elif na == "--filter-key-value":
            action = ("filter-key-value", need())
        elif na in ("-r", "--resolve-search"):
            action = ("resolve-search", "")
        elif na == "--format":
            # validated only when a dump actually runs (the reference
            # checks via Formatter::create inside dump_all)
            dump_format = need()
        elif a.startswith("-"):
            # registered-option override, e.g. CEPH_ARGS="--fsid ..."
            # (na already has any "=value" split off)
            overrides[_norm_key(na.lstrip("-"))] = need()
        else:
            lookup_key = a
        i += 1

    # global_init order: name validation and conf-file loading happen
    # before the action check (invalid-args.t / env-vs-args.t pin this)
    type_, id_ = _parse_name(name)
    meta = {"cluster": cluster, "type": type_, "id": id_, "name": name,
            "host": "", "pid": str(os.getpid())}

    # conf file: explicit -c is a hard failure when unreadable
    # (global_init); CEPH_CONF degrades to defaults with the dout-style
    # complaint lines
    conf = ConfFile()
    env_conf = os.environ.get("CEPH_CONF")
    if conf_path is None and env_conf:
        conf_path = env_conf
        conf_explicit = False
    if conf_path:
        # -c/CEPH_CONF is a comma-delimited SEARCH LIST: the first
        # openable entry wins (global_init's conf_files handling)
        loaded = False
        for entry in conf_path.split(","):
            try:
                conf = ConfFile.parse(entry)
                loaded = True
                break
            except OSError:
                continue
        if not loaded:
            if conf_explicit:
                print(f"global_init: unable to open config file from "
                      f"search list {conf_path}")
                return 1
            _soft_parse_failure(conf_path)

    if lookup_key is not None and action is None:
        action = ("lookup", lookup_key)
    if action is None:
        print(NO_ACTION)
        return 1

    search = sections if sections else [name, type_, "global"]

    def file_resolver(key: str) -> Optional[str]:
        for sec in search:
            v = conf.get(sec, key)
            if v is not None:
                return v
        return None

    g = ConfigProxy()

    def resolved(key: str) -> Optional[str]:
        """registered option: override -> conf file -> default."""
        if key in overrides:
            return overrides[key]
        v = file_resolver(key)
        if v is not None:
            return v
        if key in g.schema:
            return str(g.schema[key].default)
        return None

    exp = Expander(meta, resolved)

    kind, arg = action
    if kind == "lookup":
        key = _norm_key(arg)
        raw = file_resolver(key)
        if raw is None:
            return 1
        print(exp.expand(raw, [(key, raw)]))
        return 0
    if kind == "show-config-value":
        key = _norm_key(arg)
        if key not in g.schema and key not in overrides \
                and file_resolver(key) is None:
            print(f"failed to get config option '{arg}': option not "
                  "found")
            return 1
        raw = resolved(key) or ""
        print(exp.expand(raw, [(key, raw)]))
        return 0
    if kind == "dump":
        known = ("", "plain", "json", "json-pretty", "xml",
                 "xml-pretty", "table", "table-kv", "html",
                 "html-pretty")
        if dump_format not in known:
            # Formatter::create's refusal shape: stderr + usage
            sys.stderr.write(f"format '{dump_format}' not "
                             "recognized.\n")
            sys.stderr.write(USAGE)
            return 1
        vals = {}
        for key in sorted(g.schema):
            raw = str(resolved(key) or "")
            vals[key] = exp.expand(raw, [(key, raw)])
        # _show_config emits the identity keys first
        doc = {"name": name, "cluster": cluster, **vals}
        if dump_format == "json":
            print(json.dumps(doc, separators=(",", ":")))
        elif dump_format == "json-pretty":
            print(json.dumps(doc, indent=4))
        elif dump_format in ("xml", "xml-pretty"):
            from xml.sax.saxutils import escape as _esc
            nl = "\n" if dump_format == "xml-pretty" else ""
            pad = "    " if dump_format == "xml-pretty" else ""
            out = ["<config>" + nl]
            for k, v in doc.items():
                out.append(f"{pad}<{k}>{_esc(v)}</{k}>{nl}")
            out.append("</config>")
            print("".join(out))
        elif dump_format in ("table", "table-kv"):
            sep = ": " if dump_format == "table-kv" else "  "
            width = max(len(k) for k in doc)
            for k, v in doc.items():
                left = k if dump_format == "table-kv" \
                    else k.ljust(width)
                print(f"{left}{sep}{v}")
        elif dump_format in ("html", "html-pretty"):
            from xml.sax.saxutils import escape as _esc
            nl = "\n" if dump_format == "html-pretty" else ""
            items = "".join(f"<li>{_esc(k)}: {_esc(v)}</li>{nl}"
                            for k, v in doc.items())
            print(f"<ul>{nl}{items}</ul>")
        else:
            for key, v in vals.items():
                print(f"{key} = {v}")
        return 0
    if kind == "list-sections":
        for sec in conf.names():
            if sec.startswith(arg):
                print(sec)
        return 0
    if kind in ("filter-key", "filter-key-value"):
        want_key, _, want_val = arg.partition("=")
        want_key = _norm_key(want_key)
        for sec in conf.names():
            v = conf.get(sec, want_key)
            if v is None:
                continue
            if kind == "filter-key-value" and v != want_val:
                continue
            print(sec)
        return 0
    if kind == "resolve-search":
        for path in (conf_path or "").split(","):
            if path and os.path.exists(path):
                print(path)
                return 0
        return 1
    print(NO_ACTION)
    return 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
