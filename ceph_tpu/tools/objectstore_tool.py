"""objectstore-tool — offline object-store surgery on saved stores.

The ceph-objectstore-tool analog (src/tools/ceph_objectstore_tool.cc):
operate on an unmounted OSD store (here: a ``MemStore.save`` file, the
checkpoint format MiniCluster.checkpoint writes) without a running
cluster.  Supported ops mirror the reference's most-used surface:

  --op list                      list (collection, object) pairs
  --op info                      store summary (collections/objects/txns)
  --op get-bytes  --cid C --oid O [--shard S] [--out FILE]
  --op list-attrs --cid C --oid O [--shard S]
  --op get-omap   --cid C --oid O [--shard S]
  --op remove     --cid C --oid O [--shard S]   (rewrites the store)
  --op export     --cid C --out FILE            (one collection, portable)
  --op import     --in FILE                     (merge an exported coll)
  --op list-pgs                                 distinct pg ids on the store
  --op get-attr   --cid C --oid O --key K       (hex to stdout)
  --op set-bytes  --cid C --oid O --in FILE     (replace payload)
  --op set-attr/rm-attr --key K [--value HEX]
  --op set-omap/rm-omap --key K [--value HEX]

Exit status 0 on success, 1 on usage/lookup errors.
"""
from __future__ import annotations

import argparse
import json
import struct
import sys

from ..os_store import MemStore, Transaction, hobject_t

_EXPORT_MAGIC = b"CTOSEXP1"


def _find(store: MemStore, cid: str, oid: str, shard: int):
    ho = hobject_t(oid, shard)
    if not store.collection_exists(cid) or not store.exists(cid, ho):
        return None
    return ho


def _op_list(store: MemStore, out) -> int:
    for cid in sorted(store.list_collections()):
        for ho in sorted(store.list_objects(cid)):
            print(json.dumps({"cid": cid, "oid": ho.oid,
                              "shard": ho.shard,
                              "size": store.stat(cid, ho)}), file=out)
    return 0


def _op_info(store: MemStore, out) -> int:
    n_obj = sum(len(store.list_objects(c))
                for c in store.list_collections())
    print(json.dumps({"collections": len(store.list_collections()),
                      "objects": n_obj,
                      "committed_txns": store.committed_txns}), file=out)
    return 0


def _op_export(store: MemStore, cid: str, path: str) -> int:
    if not store.collection_exists(cid):
        print(f"no collection {cid!r}", file=sys.stderr)
        return 1
    sub = MemStore()
    t = Transaction()
    t.create_collection(cid)
    sub.queue_transaction(t)
    for ho in store.list_objects(cid):
        t = Transaction()
        t.touch(cid, ho)
        t.write(cid, ho, 0, store.read(cid, ho))
        for k, v in store.getattrs(cid, ho).items():
            t.setattr(cid, ho, k, v)
        om = store.omap_get(cid, ho)
        if om:
            t.omap_setkeys(cid, ho, om)
        sub.queue_transaction(t)
    sub_path = path + ".body"
    sub.save(sub_path)
    with open(sub_path, "rb") as f:
        body = f.read()
    import os
    os.remove(sub_path)
    with open(path, "wb") as f:
        f.write(_EXPORT_MAGIC + struct.pack("<I", len(body)) + body)
    return 0


def _op_import(store: MemStore, store_path: str, path: str) -> int:
    with open(path, "rb") as f:
        blob = f.read()
    if blob[:8] != _EXPORT_MAGIC:
        print(f"{path}: not an objectstore export", file=sys.stderr)
        return 1
    import os
    body_path = path + ".body"
    try:
        (n,) = struct.unpack_from("<I", blob, 8)
        if len(blob) < 12 + n:
            raise ValueError("truncated export body")
        with open(body_path, "wb") as f:
            f.write(blob[12:12 + n])
        sub = MemStore.load(body_path)
    except (struct.error, ValueError) as e:
        print(f"{path}: corrupt export ({e})", file=sys.stderr)
        return 1
    finally:
        if os.path.exists(body_path):
            os.remove(body_path)
    for cid in sub.list_collections():
        t = Transaction()
        if not store.collection_exists(cid):
            t.create_collection(cid)
        for ho in sub.list_objects(cid):
            t.touch(cid, ho)
            t.truncate(cid, ho, 0)
            t.write(cid, ho, 0, sub.read(cid, ho))
            for k, v in sub.getattrs(cid, ho).items():
                t.setattr(cid, ho, k, v)
            om = sub.omap_get(cid, ho)
            if om:
                t.omap_setkeys(cid, ho, om)
        store.queue_transaction(t)
    store.save(store_path)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="objectstore-tool")
    p.add_argument("--data-path", required=True,
                   help="MemStore.save file (osd.N.store)")
    p.add_argument("--op", required=True,
                   choices=["list", "info", "get-bytes", "list-attrs",
                            "get-omap", "remove", "export", "import",
                            "set-bytes", "get-attr", "set-attr",
                            "rm-attr", "set-omap", "rm-omap",
                            "list-pgs"])
    p.add_argument("--cid")
    p.add_argument("--oid")
    p.add_argument("--shard", type=int, default=-1)
    p.add_argument("--key", help="attr/omap key (get/set/rm-attr, "
                                 "set/rm-omap)")
    p.add_argument("--value", help="hex value (set-attr/set-omap)")
    p.add_argument("--out", help="output file (get-bytes/export)")
    p.add_argument("--in", dest="infile",
                   help="input file (import/set-bytes)")
    a = p.parse_args(argv)

    store = MemStore.load(a.data_path)
    if a.op == "list":
        return _op_list(store, sys.stdout)
    if a.op == "list-pgs":
        # distinct pg ids parsed from collection names, rendered like
        # pg_t ("pool.ps" with HEX ps, matching ceph pg dump)
        from ..os_store import parse_pg_from_cid
        pgs = {p for p in map(parse_pg_from_cid,
                              store.list_collections())
               if p is not None}
        for pool, ps in sorted(pgs):
            print(f"{pool}.{ps:x}")
        return 0
    if a.op == "info":
        return _op_info(store, sys.stdout)
    if a.op == "export":
        if not a.cid or not a.out:
            p.error("export needs --cid and --out")
        return _op_export(store, a.cid, a.out)
    if a.op == "import":
        if not a.infile:
            p.error("import needs --in")
        return _op_import(store, a.data_path, a.infile)

    if not a.cid or not a.oid:
        p.error(f"{a.op} needs --cid and --oid")
    ho = _find(store, a.cid, a.oid, a.shard)
    if ho is None:
        print(f"object {a.oid!r} (shard {a.shard}) not in {a.cid!r}",
              file=sys.stderr)
        return 1
    if a.op == "get-bytes":
        data = store.read(a.cid, ho)
        if a.out:
            with open(a.out, "wb") as f:
                f.write(data)
        else:
            sys.stdout.buffer.write(data)
        return 0
    if a.op == "list-attrs":
        attrs = store.getattrs(a.cid, ho)
        print(json.dumps({k: v.hex() for k, v in sorted(attrs.items())}))
        return 0
    if a.op == "get-omap":
        om = store.omap_get(a.cid, ho)
        print(json.dumps({k: v.hex() for k, v in sorted(om.items())}))
        return 0
    if a.op == "get-attr":
        if not a.key:
            p.error("get-attr needs --key")
        attrs = store.getattrs(a.cid, ho)
        if a.key not in attrs:
            print(f"no attr {a.key!r}", file=sys.stderr)
            return 1
        sys.stdout.write(attrs[a.key].hex() + "\n")
        return 0
    # write-side surgery: every mutation goes through a transaction
    # and rewrites the store file (the offline-store contract); the
    # else branch is `remove`, so an op missing from this chain can
    # never silently fall through to a delete

    def hexval():
        if not a.key or a.value is None:
            p.error(f"{a.op} needs --key and --value (hex)")
        try:
            return bytes.fromhex(a.value)
        except ValueError:
            print(f"--value {a.value!r} is not hex", file=sys.stderr)
            return None

    t = Transaction()
    if a.op == "set-bytes":
        if not a.infile:
            p.error("set-bytes needs --in")
        try:
            with open(a.infile, "rb") as f:
                data = f.read()
        except OSError as e:
            print(f"cannot read {a.infile}: {e.strerror}",
                  file=sys.stderr)
            return 1
        t.truncate(a.cid, ho, 0)
        t.write(a.cid, ho, 0, data)
    elif a.op == "set-attr":
        v = hexval()
        if v is None:
            return 1
        t.setattr(a.cid, ho, a.key, v)
    elif a.op == "rm-attr":
        if not a.key:
            p.error("rm-attr needs --key")
        if a.key not in store.getattrs(a.cid, ho):
            print(f"no attr {a.key!r}", file=sys.stderr)
            return 1
        t.rmattr(a.cid, ho, a.key)
    elif a.op == "set-omap":
        v = hexval()
        if v is None:
            return 1
        t.omap_setkeys(a.cid, ho, {a.key: v})
    elif a.op == "rm-omap":
        if not a.key:
            p.error("rm-omap needs --key")
        if a.key not in store.omap_get(a.cid, ho):
            print(f"no omap key {a.key!r}", file=sys.stderr)
            return 1
        t.omap_rmkeys(a.cid, ho, [a.key])
    else:
        assert a.op == "remove", a.op
        t.remove(a.cid, ho)
    store.queue_transaction(t)
    store.save(a.data_path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
