"""ceph-monstore-tool: offline monitor-store surgery
(src/tools/ceph_monstore_tool.cc role) over the mon store the
framework persists (mon.json — Monitor.save's authoritative map +
full incremental history + MonMap).

The extraction commands emit artifacts the sibling tools consume
directly: ``get crushmap`` writes the reference-compatible crushmap
binary (crushtool -d readable), ``get monmap`` writes monmaptool's
binary format, ``get osdmap`` writes osdmaptool's map-file format.
``get osdmap --version V`` rebuilds epoch V by replaying the stored
incremental history from scratch (MonitorDBStore's per-version
osdmap keys, reconstructed instead of stored).
"""
from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

USAGE = """usage: ceph-monstore-tool <store-path> <cmd> [args|options]

Commands:
  store-copy PATH                 copies store to PATH
  compact                         compacts the store
  get monmap [-o FILE]            get monmap (last committed)
  get osdmap [-v VER] [-o FILE]   get osdmap (version VER if specified)
                                  (default: last committed)
  get crushmap [-v VER] [-o FILE] get crushmap from that osdmap
  get mdsmap [-o FILE]            get the fsmap (json)
  show-versions                   show the first&last committed version of map
  dump-keys                       dumps store keys to stdout
  dump-paxos [-v VER]             dump committed transactions (json)
  rewrite-crush --crush FILE      add a commit replacing the crush map
"""


class MonStore:
    """One loaded mon store (a mon.json file or a checkpoint dir
    containing one)."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            path = os.path.join(path, "mon.json")
        self.path = path
        with open(path) as f:
            self.state = json.load(f)

    def save(self, path: Optional[str] = None) -> None:
        out = path or self.path
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.state, f)
        os.replace(tmp, out)

    # ---- map accessors -----------------------------------------------------
    def latest_osdmap(self):
        from ..osdmap.encoding import osdmap_from_dict
        return osdmap_from_dict(self.state["osdmap"])

    def incrementals(self) -> List:
        from ..osdmap.encoding import incremental_from_dict
        return [incremental_from_dict(d)
                for d in self.state["incrementals"]]

    def osdmap_at(self, version: Optional[int]):
        """Rebuild epoch ``version`` by replay; None = last
        committed (served from the stored full map, no history
        decode)."""
        if version is None:
            return self.latest_osdmap()
        incs = self.incrementals()
        last = incs[-1].epoch if incs else 0
        if version < 1 or version > last:
            raise ValueError(f"no osdmap version {version} in store "
                             f"(have 1..{last})")
        if version == last:
            return self.latest_osdmap()
        from ..osdmap.osdmap import OSDMap
        m = OSDMap()
        for inc in incs:
            if inc.epoch > version:
                break
            m.apply_incremental(inc)
        if m.epoch != version:
            raise ValueError(f"no osdmap version {version} in store "
                             f"(have 1..{last})")
        return m

    def monmap(self):
        from ..mon.monmap import MonMap
        return MonMap.from_bytes(
            self.state["monmap"].encode("latin1"))

    def versions(self):
        incs = self.state["incrementals"]
        first = incs[0]["epoch"] if incs else 0
        last = incs[-1]["epoch"] if incs else \
            self.state["osdmap"]["epoch"]
        return first, last


def _write(data: bytes, out: Optional[str], what: str) -> None:
    if out:
        with open(out, "wb") as f:
            f.write(data)
        print(f"wrote {what} ({len(data)} bytes) to {out}")
    else:
        sys.stdout.buffer.write(data)


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if len(args) < 2 or args[0] in ("-h", "--help"):
        sys.stderr.write(USAGE)
        return 1
    store_path, cmd, rest = args[0], args[1], args[2:]
    try:
        st = MonStore(store_path)
    except (OSError, ValueError, KeyError) as e:
        sys.stderr.write(f"error opening store '{store_path}': "
                         f"{e!r}\n")
        return 1

    def opt(name: str, short: str) -> Optional[str]:
        for flag in (name, short):
            if flag in rest:
                i = rest.index(flag)
                if i + 1 < len(rest):
                    return rest[i + 1]
        return None

    ver = opt("--version", "-v")
    out = opt("--out", "-o")

    if cmd == "store-copy":
        if not rest:
            sys.stderr.write(USAGE)
            return 1
        dst = rest[0]
        if os.path.isdir(dst):
            # copying into a directory produces a store the tool can
            # itself reopen (mon.json inside it)
            dst = os.path.join(dst, "mon.json")
        try:
            st.save(dst)
        except OSError as e:
            sys.stderr.write(f"error writing {dst}: {e.strerror}\n")
            return 1
        print(f"copied store to {dst}")
        return 0
    if cmd == "compact":
        st.save()
        return 0
    if cmd == "get":
        if not rest:
            sys.stderr.write(USAGE)
            return 1
        what = rest[0]
        try:
            if what == "monmap":
                _write(st.monmap().to_bytes(), out, "monmap")
            elif what == "osdmap":
                import pickle
                m = st.osdmap_at(int(ver) if ver else None)
                _write(pickle.dumps(m), out, f"osdmap epoch {m.epoch}")
            elif what == "crushmap":
                from ..crush.binfmt import encode_crushmap
                m = st.osdmap_at(int(ver) if ver else None)
                _write(encode_crushmap(m.crush), out,
                       f"crushmap of epoch {m.epoch}")
            elif what == "mdsmap":
                # the fsmap rides the config-kv incrementals; take the
                # last one seen in the history
                fsmap = None
                for d in st.state["incrementals"]:
                    kv = d.get("service_config_kv") or {}
                    if "fsmap" in kv:
                        fsmap = kv["fsmap"]
                if fsmap is None:
                    sys.stderr.write("no fsmap in store\n")
                    return 1
                _write((fsmap + "\n").encode(), out, "fsmap")
            else:
                sys.stderr.write(f"unknown map '{what}'\n")
                return 1
        except ValueError as e:
            sys.stderr.write(f"{e}\n")
            return 1
        return 0
    if cmd == "show-versions":
        first, last = st.versions()
        print(f"first committed:\t{first}")
        print(f"last  committed:\t{last}")
        return 0
    if cmd == "dump-keys":
        for d in st.state["incrementals"]:
            print(f"osdmap\t{d['epoch']}")
        print(f"osdmap\tfull_{st.state['osdmap']['epoch']}")
        print("monmap\tlatest")
        return 0
    if cmd == "dump-paxos":
        incs = st.state["incrementals"]
        if ver:
            if not ver.isdigit():
                sys.stderr.write("dump-paxos: -v requires a numeric "
                                 "version\n")
                return 1
            incs = [d for d in incs if d["epoch"] == int(ver)]
        json.dump(incs, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    if cmd == "rewrite-crush":
        crush_file = opt("--crush", "-c")
        if not crush_file:
            sys.stderr.write("rewrite-crush requires --crush FILE\n")
            return 1
        from ..crush.binfmt import decode_crushmap
        from ..osdmap.encoding import incremental_to_dict, \
            osdmap_to_dict
        from ..osdmap.osdmap import Incremental
        with open(crush_file, "rb") as f:
            cw = decode_crushmap(f.read())
        m = st.latest_osdmap()
        inc = Incremental()
        inc.epoch = m.epoch + 1
        inc.crush = cw
        m.apply_incremental(inc)
        st.state["incrementals"].append(incremental_to_dict(inc))
        st.state["osdmap"] = osdmap_to_dict(m)
        st.save()
        print(f"committed epoch {m.epoch} with the new crush map")
        return 0
    sys.stderr.write(f"unknown command '{cmd}'\n")
    sys.stderr.write(USAGE)
    return 1


if __name__ == "__main__":  # pragma: no cover
    try:
        sys.exit(main())
    except BrokenPipeError:
        sys.exit(0)
