"""crushtool — compile/decompile/test crush maps.

CLI surface mirrors the reference tool (src/tools/crushtool.cc): -c compile
text → binary map, -d decompile, -i map --test with
--num-rep/--min-x/--max-x/--show-statistics/--show-mappings/
--show-bad-mappings/--weight/--set-*-tunables, and --build for quick
hierarchies.  The --test engine is CrushTester (crush/CrushTester.cc:472),
running the device mapper when eligible.

Maps are stored in the reference's binary crushmap format
(crush/binfmt.py ≙ CrushWrapper::encode/decode), so this tool reads maps
produced by the reference crushtool and vice versa.
"""
from __future__ import annotations

import argparse
import sys

from ..crush.binfmt import decode_crushmap, encode_crushmap
from ..crush.compiler import CrushCompiler
from ..crush.tester import CrushTester
from ..crush.wrapper import CrushWrapper


def load_map(path: str) -> CrushWrapper:
    with open(path, "rb") as f:
        return decode_crushmap(f.read())


def save_map(cw: CrushWrapper, path: str) -> None:
    with open(path, "wb") as f:
        f.write(encode_crushmap(cw))


def _check_name_maps(cw, max_id: int) -> bool:
    """CrushTester::check_name_maps: walk the tree from the roots (the
    'ceph osd tree' walk) and verify every bucket has a name, every
    node's type has a name, and no device id reaches max_id; also
    probe the stray-device path with item 0."""
    def fail(msg, item):
        print(f"{msg}: item#{item}")
        return False

    def visit(item):
        if item < 0:
            b = cw.crush.bucket(item)
            if item not in cw.name_map:
                return fail("unknown item name", item)
            t = b.type
        else:
            if max_id > 0 and item >= max_id:
                return fail("item id too large", item)
            t = 0
        if t not in cw.type_map:
            return fail("unknown type name", item)
        if item < 0:
            for it in cw.crush.bucket(item).items:
                if not visit(it):
                    return False
        return True

    roots = [b.id for b in cw.crush.buckets if b is not None
             and cw._parent_of(b.id) is None]
    for r in sorted(roots):
        if not visit(r):
            return False
    # straying osd probe (id 0 need not be in the map)
    if 0 not in cw.type_map:
        return fail("unknown type name", 0)
    if max_id > 0 and 0 >= max_id:
        return fail("item id too large", 0)
    return True


def _check_overlapped_rules(cw) -> None:
    """CrushTester::check_overlapped_rules: rules sharing a (ruleset,
    type) whose [min_size, max_size] ranges overlap print per merged
    sub-interval, names sorted (the boost interval_map shape)."""
    groups: dict = {}
    for rno, r in enumerate(cw.crush.rules):
        if r is None:
            continue
        name = cw.rule_name_map.get(rno, f"rule{rno}")
        groups.setdefault((r.ruleset, r.type), []).append(
            (r.min_size, r.max_size, name))
    for (ruleset, _t), rules in sorted(groups.items()):
        points = sorted({p for lo, hi, _ in rules
                         for p in (lo, hi + 1)})
        prev = None
        for a, b in zip(points, points[1:]):
            names = sorted({n for lo, hi, n in rules
                            if lo <= a and a <= hi})
            if len(names) > 1 and names != prev:
                print(f"overlapped rules in ruleset {ruleset}: "
                      + ", ".join(names))
            prev = names if len(names) > 1 else None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="crushtool")
    p.add_argument("-i", "--infn", help="input map file")
    p.add_argument("-o", "--outfn", help="output file")
    p.add_argument("-c", "--compile", dest="srcfn",
                   help="compile text map to binary")
    p.add_argument("-d", "--decompile", dest="decompile",
                   help="decompile map to text", nargs="?", const="",
                   default=None)
    p.add_argument("-t", "--test", action="store_true",
                   help="test a range of inputs on the map")
    p.add_argument("--num-rep", type=int, default=-1)
    p.add_argument("--min-x", type=int, default=-1)
    p.add_argument("--max-x", type=int, default=-1)
    p.add_argument("--rule", type=int, default=-1)
    p.add_argument("--show-statistics", action="store_true")
    p.add_argument("--show-mappings", action="store_true")
    p.add_argument("--show-bad-mappings", action="store_true")
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--weight", nargs=2, action="append", default=[],
                   metavar=("DEVNO", "WEIGHT"))
    # runtime tunable overrides (reference --set-* flags)
    p.add_argument("--set-choose-local-tries", type=int, default=None)
    p.add_argument("--set-choose-local-fallback-tries", type=int,
                   default=None)
    p.add_argument("--set-choose-total-tries", type=int, default=None)
    p.add_argument("--set-chooseleaf-descend-once", type=int, default=None)
    p.add_argument("--set-chooseleaf-vary-r", type=int, default=None)
    p.add_argument("--set-chooseleaf-stable", type=int, default=None)
    p.add_argument("--set-straw-calc-version", type=int, default=None)
    p.add_argument("--add-item", nargs=3, metavar=("ID", "W", "NAME"))
    p.add_argument("--loc", nargs=2, action="append", default=[],
                   metavar=("TYPE", "NAME"))
    p.add_argument("--update-item", nargs=3,
                   metavar=("ID", "W", "NAME"))
    p.add_argument("--reweight-item", nargs=2, metavar=("NAME", "W"))
    p.add_argument("--remove-item", metavar="NAME")
    p.add_argument("--create-simple-rule", nargs=4,
                   metavar=("NAME", "ROOT", "TYPE", "MODE"))
    p.add_argument("--create-replicated-rule", nargs=3,
                   metavar=("NAME", "ROOT", "TYPE"))
    p.add_argument("--device-class", default="")
    p.add_argument("--build", action="store_true",
                   help="build a layered map: --num_osds N "
                        "(name alg size)...")
    p.add_argument("--num_osds", type=int, default=0)
    p.add_argument("layers", nargs="*",
                   help="--build layer triples: name alg size")
    p.add_argument("--show-location", type=int, default=None,
                   metavar="ID")
    p.add_argument("--check", nargs="?", const=-1, type=int,
                   default=None, metavar="MAX_ID")
    p.add_argument("--dump", action="store_true",
                   help="dump the map as reference-format JSON")
    p.add_argument("--host-mapper", action="store_true",
                   help="force the host interpreter (no device batch)")
    args = p.parse_args(argv)

    def apply_tunable_flags(m) -> None:
        for attr, val in [
                ("choose_local_tries", args.set_choose_local_tries),
                ("choose_local_fallback_tries",
                 args.set_choose_local_fallback_tries),
                ("choose_total_tries", args.set_choose_total_tries),
                ("chooseleaf_descend_once",
                 args.set_chooseleaf_descend_once),
                ("chooseleaf_vary_r", args.set_chooseleaf_vary_r),
                ("chooseleaf_stable", args.set_chooseleaf_stable),
                ("straw_calc_version", args.set_straw_calc_version)]:
            if val is not None:
                setattr(m, attr, val)

    if args.build:
        # crushtool --build --num_osds N name alg size ...
        # (src/tools/crushtool.cc): stack layers bottom-up, each layer
        # packing the previous one's items into buckets of `size`
        # (0 = everything into one bucket), named name<i> (bare name
        # for size 0); then build_simple_crush_rules over the top root.
        from ..crush.constants import (
            CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
            CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM)
        algs = {"uniform": CRUSH_BUCKET_UNIFORM,
                "list": CRUSH_BUCKET_LIST, "tree": CRUSH_BUCKET_TREE,
                "straw": CRUSH_BUCKET_STRAW,
                "straw2": CRUSH_BUCKET_STRAW2}
        if len(args.layers) % 3 or not args.layers:
            print("--build needs (name alg size) triples",
                  file=sys.stderr)
            return 1
        for li in range(0, len(args.layers), 3):
            lname, lalg, lsize = args.layers[li:li + 3]
            if lalg not in algs:
                print(f"unknown bucket type '{lalg}'", file=sys.stderr)
                return 1
            if not lsize.lstrip("-").isdigit() or int(lsize) < 0:
                print(f"invalid layer size '{lsize}'", file=sys.stderr)
                return 1
        cw = CrushWrapper()
        cw.set_tunables_profile("jewel")
        cw.set_type_name(0, "osd")
        cw.set_max_devices(args.num_osds)
        lower = [(i, 0x10000) for i in range(args.num_osds)]
        for i in range(args.num_osds):
            cw.set_item_name(i, f"osd.{i}")
        t = 0
        lname = "osd"
        for li in range(0, len(args.layers), 3):
            lname, lalg, lsize = args.layers[li:li + 3]
            t += 1
            size = int(lsize)
            cw.set_type_name(t, lname)
            pos, idx = 0, 0
            cur = []
            while pos < len(lower):
                chunk = lower[pos:pos + size] if size else lower[pos:]
                pos += len(chunk)
                bid = cw.add_bucket(
                    algs[lalg], t,
                    f"{lname}{idx}" if size else lname,
                    [c for c, _ in chunk], [w for _, w in chunk])
                cur.append((bid, sum(w for _, w in chunk)))
                idx += 1
            lower = cur
        root = lname if int(args.layers[-1]) == 0 else f"{lname}0"
        cw.add_simple_rule("replicated_rule", root_name=root,
                           failure_domain_name=cw.get_type_name(1),
                           mode="firstn", ruleno=0)
        out = args.outfn or "crushmap"
        save_map(cw, out)
        return 0

    if args.add_item or args.update_item or args.reweight_item \
            or args.remove_item or args.create_simple_rule \
            or args.create_replicated_rule:
        # map-editing verbs (crushtool.cc --add-item/--reweight-item/
        # --remove-item/--create-simple-rule)
        if args.srcfn and args.infn:
            print("give either -c <text> or -i <map>, not both",
                  file=sys.stderr)
            return 1
        if args.srcfn:
            # the reference accepts -c source + edit verbs in one run
            with open(args.srcfn) as f:
                cw = CrushCompiler().compile(f.read())
            apply_tunable_flags(cw.crush)
        elif args.infn:
            cw = load_map(args.infn)
        else:
            print("map edits require -i <map> or -c <text>",
                  file=sys.stderr)
            return 1
        if args.add_item:
            from ..osdmap.simple_build import insert_item
            dev, w, name = args.add_item
            loc = {t: n for t, n in args.loc}
            insert_item(cw, int(dev),
                        int(round(float(w) * 0x10000)), name, loc)
        if args.update_item:
            # CrushWrapper::update_item: adjust IN THE GIVEN LOCATION
            # only when the item already lives there; insert otherwise
            from ..osdmap.simple_build import insert_item
            dev, w, name = args.update_item
            dev = int(dev)
            w16 = int(round(float(w) * 0x10000))
            loc = {t: n for t, n in args.loc}
            placed = False
            for t in sorted(cw.type_map):
                bname = loc.get(cw.type_map[t])
                if t == 0 or bname is None:
                    continue
                if not cw.name_exists(bname):
                    break
                bid = cw.get_item_id(bname)
                if dev in cw.crush.bucket(bid).items:
                    delta = cw._set_item_weight_in(bid, dev, w16)
                    cw._propagate_above(bid, delta)
                    cw.set_item_name(dev, name)
                    if cw.item_class:
                        cw.rebuild_roots_with_classes()
                    placed = True
                break
            if not placed:
                insert_item(cw, dev, w16, name, loc)
        if args.reweight_item:
            name, w = args.reweight_item
            print(f"crushtool reweighting item {name} to "
                  f"{float(w):g}")
            if not cw.name_exists(name):
                print(f" name {name} dne", file=sys.stderr)
                return 1
            r = cw.adjust_item_weight(cw.get_item_id(name),
                                      int(round(float(w) * 0x10000)))
            if r < 0:        # named but linked into no bucket
                print("crushtool (2) No such file or directory",
                      file=sys.stderr)
                return 1
        if args.remove_item:
            cw.remove_item(cw.get_item_id(args.remove_item))
        if args.create_simple_rule:
            rname, root, ftype, mode = args.create_simple_rule
            cw.add_simple_rule(rname, root_name=root,
                               failure_domain_name=ftype, mode=mode)
        if args.create_replicated_rule:
            rname, root, ftype = args.create_replicated_rule
            r = cw.add_simple_rule(rname, root_name=root,
                                   failure_domain_name=ftype,
                                   device_class=args.device_class,
                                   mode="firstn")
            if r < 0:
                print(f"create-replicated-rule failed: {r}",
                      file=sys.stderr)
                return 1
        if not args.outfn:
            # the reference never writes edits in place
            # (crushtool.cc: "use -o <file> to write it out")
            print("edited map not written; use -o <file> to write "
                  "it out", file=sys.stderr)
            return 0
        save_map(cw, args.outfn)
        return 0

    if args.srcfn:
        with open(args.srcfn) as f:
            text = f.read()
        try:
            cw = CrushCompiler().compile(text)
        except ValueError as e:
            print(e)
            return 1
        apply_tunable_flags(cw.crush)  # reference applies --set-* at -c too
        out = args.outfn or "crushmap"
        save_map(cw, out)
        if args.dump:
            from ..crush.dumpfmt import dump_json
            sys.stdout.write(dump_json(cw))
        return 0

    if args.decompile is not None:
        path = args.decompile or args.infn
        if not path:
            print("decompile requires a map file", file=sys.stderr)
            return 1
        try:
            cw = load_map(path)
        except Exception:
            print(f"crushtool: unable to decode {path}")
            return 1
        text = CrushCompiler(cw).decompile()
        if args.outfn:
            with open(args.outfn, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0

    if args.show_location is not None:
        if not args.infn:
            print("--show-location requires -i <map>", file=sys.stderr)
            return 1
        cw = load_map(args.infn)
        loc = cw.get_full_location(args.show_location)
        for k in sorted(loc):        # std::map: alphabetical by type
            print(f"{k}\t{loc[k]}")
        return 0

    if args.check is not None:
        if not args.infn:
            print("--check requires -i <map>", file=sys.stderr)
            return 1
        cw = load_map(args.infn)
        _check_overlapped_rules(cw)
        if args.check >= 0 and not _check_name_maps(cw, args.check):
            return 1
        return 0

    if args.dump:
        if not args.infn:
            print("--dump requires -i <map>", file=sys.stderr)
            return 1
        from ..crush.dumpfmt import dump_json
        cw = load_map(args.infn)
        apply_tunable_flags(cw.crush)   # the reference mutates first
        sys.stdout.write(dump_json(cw))
        return 0

    if args.test:
        if not args.infn:
            print("--test requires -i <map>", file=sys.stderr)
            return 1
        cw = load_map(args.infn)
        apply_tunable_flags(cw.crush)
        t = CrushTester(cw)
        if args.num_rep >= 0:
            t.set_num_rep(args.num_rep)
        if args.min_x >= 0:
            t.set_min_x(args.min_x)
        if args.max_x >= 0:
            t.set_max_x(args.max_x)
        if args.rule >= 0:
            t.set_rule(args.rule)
        t.set_output_statistics(args.show_statistics)
        t.set_output_mappings(args.show_mappings)
        t.set_output_bad_mappings(args.show_bad_mappings)
        t.set_output_utilization(args.show_utilization)
        t.use_device = not args.host_mapper
        for dev, w in args.weight:
            t.set_device_weight(int(dev), float(w))
        return t.test()

    p.print_help()
    return 1


if __name__ == "__main__":
    # die silently on a closed pipe (`tool ... | head`), like the
    # C++ tools' default SIGPIPE disposition
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
