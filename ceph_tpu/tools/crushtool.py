"""crushtool — compile/decompile/test crush maps.

CLI surface mirrors the reference tool (src/tools/crushtool.cc): -c compile
text → binary map, -d decompile, -i map --test with
--num-rep/--min-x/--max-x/--show-statistics/--show-mappings/
--show-bad-mappings/--weight/--set-*-tunables, and --build for quick
hierarchies.  The --test engine is CrushTester (crush/CrushTester.cc:472),
running the device mapper when eligible.

Maps are stored in the reference's binary crushmap format
(crush/binfmt.py ≙ CrushWrapper::encode/decode), so this tool reads maps
produced by the reference crushtool and vice versa.
"""
from __future__ import annotations

import argparse
import sys

from ..crush.binfmt import decode_crushmap, encode_crushmap
from ..crush.compiler import CrushCompiler
from ..crush.tester import CrushTester
from ..crush.wrapper import CrushWrapper


USAGE = """usage: crushtool ...

Display, modify and test a crush map

There are five stages, running one after the other:

 - input/build
 - tunables adjustments
 - modifications
 - display/test
 - output

Options that are not specific to a stage.

   [--infn|-i infile]
                         read the crush map from infile

Options for the input/build stage

   --decompile|-d map    decompile a crush map to source
   [--outfn|-o outfile]
                         specify output for for (de)compilation
   --compile|-c map.txt  compile a map from source
   --enable-unsafe-tunables
                         compile with unsafe tunables
   --build --num_osds N layer1 ...
                         build a new map, where each 'layer' is
                         'name (uniform|straw2|straw|list|tree) size'

Options for the tunables adjustments stage

   --set-choose-local-tries N
                         set choose local retries before re-descent
   --set-choose-local-fallback-tries N
                         set choose local retries using fallback
                         permutation before re-descent
   --set-choose-total-tries N
                         set choose total descent attempts
   --set-chooseleaf-descend-once <0|1>
                         set chooseleaf to (not) retry the recursive descent
   --set-chooseleaf-vary-r <0|1>
                         set chooseleaf to (not) vary r based on parent
   --set-chooseleaf-stable <0|1>
                         set chooseleaf firstn to (not) return stable results

Options for the modifications stage

   -i mapfn --add-item id weight name [--loc type name ...]
                         insert an item into the hierarchy at the
                         given location
   -i mapfn --update-item id weight name [--loc type name ...]
                         insert or move an item into the hierarchy at the
                         given location
   -i mapfn --remove-item name
                         remove the given item
   -i mapfn --reweight-item name weight
                         reweight a given item (and adjust ancestor
                         weights as needed)
   -i mapfn --reweight   recalculate all bucket weights
   -i mapfn --create-simple-rule name root type mode
                         create crush rule <name> to start from <root>,
                         replicate across buckets of type <type>, using
                         a choose mode of <firstn|indep>
   -i mapfn --create-replicated-rule name root type
                         create crush rule <name> to start from <root>,
                         replicate across buckets of type <type>
   --device-class <class>
                         use device class <class> for new rule
   -i mapfn --remove-rule name
                         remove the specified crush rule

Options for the display/test stage

   -f --format           the format of --dump, defaults to json-pretty
                         can be one of json, json-pretty, xml, xml-pretty,
                         table, table-kv, html, html-pretty
   --dump                dump the crush map
   --tree                print map summary as a tree
   --check [max_id]      check if any item is referencing an unknown name/type
   -i mapfn --show-location id
                         show location for given device id
   -i mapfn --test       test a range of inputs on the map
      [--min-x x] [--max-x x] [--x x]
      [--min-rule r] [--max-rule r] [--rule r] [--ruleset rs]
      [--num-rep n]
      [--pool-id n]      specifies pool id
      [--batches b]      split the CRUSH mapping into b > 1 rounds
      [--weight|-w devno weight]
                         where weight is 0 to 1.0
      [--simulate]       simulate placements using a random
                         number generator in place of the CRUSH
                         algorithm
   --show-utilization    show OSD usage
   --show-utilization-all
                         include zero weight items
   --show-statistics     show chi squared statistics
   --show-mappings       show mappings
   --show-bad-mappings   show bad mappings
   --show-choose-tries   show choose tries histogram
   --output-name name
                         prepend the data file(s) generated during the
                         testing routine with name
   --output-csv
                         export select data generated during testing routine
                         to CSV files for off-line post-processing
                         use --help-output for more information

Options for the output stage

   [--outfn|-o outfile]
                         specify output for modified crush map"""

HELP_OUTPUT = """data output from testing routine ...
           absolute_weights
                  the decimal weight of each OSD
                  data layout: ROW MAJOR
                               OSD id (int), weight (int)
           batch_device_expected_utilization_all
                  the expected number of objects each OSD should receive per placement batch
                  which may be a decimal value
                  data layout: COLUMN MAJOR
                               round (int), objects expected on OSD 0...OSD n (float)
           batch_device_utilization_all
                  the number of objects stored on each OSD during each placement round
                  data layout: COLUMN MAJOR
                               round (int), objects stored on OSD 0...OSD n (int)
           device_utilization_all
                  the number of objects stored on each OSD at the end of placements
                  data_layout: ROW MAJOR
                               OSD id (int), objects stored (int), objects expected (float)
           device_utilization
                  the number of objects stored on each OSD marked 'up' at the end of placements
                  data_layout: ROW MAJOR
                               OSD id (int), objects stored (int), objects expected (float)
           placement_information
                  the map of input -> OSD
                  data_layout: ROW MAJOR
                               input (int), OSD's mapped (int)
           proportional_weights_all
                  the proportional weight of each OSD specified in the CRUSH map
                  data_layout: ROW MAJOR
                               OSD id (int), proportional weight (float)
           proportional_weights
                  the proportional weight of each 'up' OSD specified in the CRUSH map
                  data_layout: ROW MAJOR
                               OSD id (int), proportional weight (float)"""


def load_map(path: str) -> CrushWrapper:
    with open(path, "rb") as f:
        return decode_crushmap(f.read())


def save_map(cw: CrushWrapper, path: str) -> None:
    with open(path, "wb") as f:
        f.write(encode_crushmap(cw))


def _check_name_maps(cw, max_id: int) -> bool:
    """CrushTester::check_name_maps: walk the tree from the roots (the
    'ceph osd tree' walk) and verify every bucket has a name, every
    node's type has a name, and no device id reaches max_id; also
    probe the stray-device path with item 0."""
    def fail(msg, item):
        print(f"{msg}: item#{item}")
        return False

    def visit(item):
        if item < 0:
            b = cw.crush.bucket(item)
            if item not in cw.name_map:
                return fail("unknown item name", item)
            t = b.type
        else:
            if max_id > 0 and item >= max_id:
                return fail("item id too large", item)
            t = 0
        if t not in cw.type_map:
            return fail("unknown type name", item)
        if item < 0:
            for it in cw.crush.bucket(item).items:
                if not visit(it):
                    return False
        return True

    roots = [b.id for b in cw.crush.buckets if b is not None
             and cw._parent_of(b.id) is None]
    for r in sorted(roots):
        if not visit(r):
            return False
    # straying osd probe (id 0 need not be in the map)
    if 0 not in cw.type_map:
        return fail("unknown type name", 0)
    if max_id > 0 and 0 >= max_id:
        return fail("item id too large", 0)
    return True


def _check_overlapped_rules(cw) -> None:
    """CrushTester::check_overlapped_rules: rules sharing a (ruleset,
    type) whose [min_size, max_size] ranges overlap print per merged
    sub-interval, names sorted (the boost interval_map shape)."""
    groups: dict = {}
    for rno, r in enumerate(cw.crush.rules):
        if r is None:
            continue
        name = cw.rule_name_map.get(rno, f"rule{rno}")
        groups.setdefault((r.ruleset, r.type), []).append(
            (r.min_size, r.max_size, name))
    for (ruleset, _t), rules in sorted(groups.items()):
        points = sorted({p for lo, hi, _ in rules
                         for p in (lo, hi + 1)})
        prev = None
        for a, b in zip(points, points[1:]):
            names = sorted({n for lo, hi, n in rules
                            if lo <= a and a <= hi})
            if len(names) > 1 and names != prev:
                print(f"overlapped rules in ruleset {ruleset}: "
                      + ", ".join(names))
            prev = names if len(names) > 1 else None


def main(argv=None) -> int:
    import os

    argv = list(sys.argv[1:] if argv is None else argv)
    # CEPH_ARGS is consumed by global_init; --debug-* flags there are
    # swallowed (our tools don't emit debug chatter).  A --debug-crush
    # ON the command line is NOT special — it falls through to the
    # remaining-args handling exactly like the reference (build.t
    # records the resulting 'remaining args: [...]' error)
    env_args = os.environ.get("CEPH_ARGS", "").split()
    filtered = []
    skip = False
    for a in env_args:
        if skip:
            skip = False
            continue
        if a == "--debug-crush":
            skip = True
            continue
        if a.startswith("--debug-crush="):
            continue
        filtered.append(a)
    argv = filtered + argv
    if "--help" in argv or "-h" in argv:
        print(USAGE)
        print("")
        return 0
    if "--help-output" in argv:
        print(HELP_OUTPUT)
        return 0

    p = argparse.ArgumentParser(prog="crushtool", add_help=False)
    p.add_argument("-i", "--infn", help="input map file")
    p.add_argument("-o", "--outfn", help="output file")
    p.add_argument("-c", "--compile", dest="srcfn",
                   help="compile text map to binary")
    p.add_argument("-d", "--decompile", dest="decompile",
                   help="decompile map to text", nargs="?", const="",
                   default=None)
    p.add_argument("-t", "--test", action="store_true",
                   help="test a range of inputs on the map")
    p.add_argument("--num-rep", "--num_rep", type=int, default=-1)
    p.add_argument("--min-x", "--min_x", type=int, default=-1)
    p.add_argument("--max-x", "--max_x", type=int, default=-1)
    p.add_argument("-x", "--x", dest="one_x", type=int, default=None)
    p.add_argument("--rule", type=int, default=-1)
    p.add_argument("--min-rule", type=int, default=-1)
    p.add_argument("--max-rule", type=int, default=-1)
    p.add_argument("--ruleset", type=int, default=-1)
    p.add_argument("--pool-id", type=int, default=-1)
    p.add_argument("--batches", type=int, default=1)
    p.add_argument("--simulate", action="store_true")
    p.add_argument("--show-statistics", action="store_true")
    p.add_argument("--show-mappings", action="store_true")
    p.add_argument("--show-bad-mappings", action="store_true")
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--show-utilization-all", action="store_true")
    p.add_argument("--show-choose-tries", action="store_true")
    p.add_argument("--output-name", default="")
    p.add_argument("--output-csv", action="store_true")
    p.add_argument("-w", "--weight", nargs=2, action="append",
                   default=[], metavar=("DEVNO", "WEIGHT"))
    # runtime tunable overrides (reference --set-* flags)
    p.add_argument("--set-choose-local-tries", type=int, default=None)
    p.add_argument("--set-choose-local-fallback-tries", type=int,
                   default=None)
    p.add_argument("--set-choose-total-tries", type=int, default=None)
    p.add_argument("--set-chooseleaf-descend-once", type=int, default=None)
    p.add_argument("--set-chooseleaf-vary-r", type=int, default=None)
    p.add_argument("--set-chooseleaf-stable", type=int, default=None)
    p.add_argument("--set-straw-calc-version", type=int, default=None)
    p.add_argument("--enable-unsafe-tunables", action="store_true")
    p.add_argument("--add-item", nargs=3, metavar=("ID", "W", "NAME"))
    p.add_argument("--loc", nargs=2, action="append", default=[],
                   metavar=("TYPE", "NAME"))
    p.add_argument("--update-item", nargs=3,
                   metavar=("ID", "W", "NAME"))
    p.add_argument("--reweight-item", nargs=2, metavar=("NAME", "W"))
    p.add_argument("--reweight", action="store_true")
    p.add_argument("--remove-item", metavar="NAME")
    p.add_argument("--remove-rule", metavar="NAME")
    p.add_argument("--create-simple-rule", nargs=4,
                   metavar=("NAME", "ROOT", "TYPE", "MODE"))
    p.add_argument("--create-replicated-rule", nargs=3,
                   metavar=("NAME", "ROOT", "TYPE"))
    p.add_argument("--device-class", default="")
    p.add_argument("--build", action="store_true",
                   help="build a layered map: --num_osds N "
                        "(name alg size)...")
    p.add_argument("--num_osds", type=int, default=0)
    p.add_argument("layers", nargs="*",
                   help="--build layer triples: name alg size")
    p.add_argument("--show-location", type=int, default=None,
                   metavar="ID")
    p.add_argument("--check", nargs="?", const=-1, type=int,
                   default=None, metavar="MAX_ID")
    p.add_argument("--dump", action="store_true",
                   help="dump the map as reference-format JSON")
    p.add_argument("-f", "--format", default="json-pretty")
    p.add_argument("--tree", action="store_true")
    p.add_argument("--host-mapper", action="store_true",
                   help="force the host interpreter (no device batch)")
    args, _unknown = p.parse_known_args(argv)
    # the reference's leftover-args pool: scan argv skipping every
    # known option (and its operands) — what's left, in ORIGINAL
    # order, is --build's layer list; anything else rejects it
    # (ceph_argparse leaves exactly these behind)
    nargs_of = {}
    optional_val = set()
    for act in p._actions:
        for s in act.option_strings:
            if isinstance(act, argparse._StoreTrueAction):
                nargs_of[s] = 0
            elif act.nargs in (None, 1):
                nargs_of[s] = 1
            elif act.nargs == "?":
                nargs_of[s] = 1
                optional_val.add(s)
            elif isinstance(act.nargs, int):
                nargs_of[s] = act.nargs
            else:
                nargs_of[s] = 0
    remaining = []
    i = 0
    while i < len(argv):
        tok = argv[i]
        base = tok.split("=", 1)[0]
        if base in nargs_of:
            n = 0 if "=" in tok else nargs_of[base]
            if base in optional_val and n:
                nxt = argv[i + 1] if i + 1 < len(argv) else "-"
                if nxt.startswith("-") and not \
                        nxt.lstrip("-").isdigit():
                    n = 0
            i += 1 + n
        else:
            remaining.append(tok)
            i += 1
    args.layers = remaining
    if remaining and not args.build:
        print(f"unrecognized arguments: [{','.join(remaining)}]",
              file=sys.stderr)
        return 1

    def apply_tunable_flags(m) -> bool:
        changed = False
        for attr, val in [
                ("choose_local_tries", args.set_choose_local_tries),
                ("choose_local_fallback_tries",
                 args.set_choose_local_fallback_tries),
                ("choose_total_tries", args.set_choose_total_tries),
                ("chooseleaf_descend_once",
                 args.set_chooseleaf_descend_once),
                ("chooseleaf_vary_r", args.set_chooseleaf_vary_r),
                ("chooseleaf_stable", args.set_chooseleaf_stable),
                ("straw_calc_version", args.set_straw_calc_version)]:
            if val is not None:
                setattr(m, attr, val)
                changed = True
        return changed

    # ---- stage 1: input/build (crushtool.cc:744-846) -----------------------
    modified = False
    cw = None
    if args.build:
        cw = _do_build(args)
        if cw is None:
            return 1
        modified = True
    elif args.srcfn:
        with open(args.srcfn) as f:
            text = f.read()
        try:
            cw = CrushCompiler().compile(text)
        except ValueError as e:
            print(e)
            return 1
        modified = True
    else:
        infn = args.infn or (args.decompile or None)
        if infn:
            try:
                cw = load_map(infn)
            except FileNotFoundError:
                print(f"crushtool: error reading \'{infn}\': "
                      f"(2) No such file or directory", file=sys.stderr)
                return 1
            except Exception:
                print(f"crushtool: unable to decode {infn}")
                return 1
    adjust = any(v is not None for v in (
        args.set_choose_local_tries,
        args.set_choose_local_fallback_tries,
        args.set_choose_total_tries,
        args.set_chooseleaf_descend_once,
        args.set_chooseleaf_vary_r, args.set_chooseleaf_stable,
        args.set_straw_calc_version))
    no_action = not (args.build or args.srcfn or args.decompile
                     is not None or args.test or args.check is not None
                     or args.dump or args.tree or adjust
                     or args.show_location is not None
                     or args.add_item or args.update_item
                     or args.reweight_item or args.reweight
                     or args.remove_item or args.remove_rule
                     or args.create_simple_rule
                     or args.create_replicated_rule)
    if no_action:
        # --set-* flags count as an action (crushtool.cc:640 !adjust)
        print("no action specified; -h for help", file=sys.stderr)
        return 1
    if cw is None:
        print("crushtool: no input map specified", file=sys.stderr)
        return 1

    # ---- stage 2: tunables (crushtool.cc:848-880) --------------------------
    if apply_tunable_flags(cw.crush):
        modified = True

    # ---- stage 3: modifications (crushtool.cc:882-990) ---------------------
    if args.reweight_item:
        name, w = args.reweight_item
        print(f"crushtool reweighting item {name} to {float(w):g}")
        if not cw.name_exists(name):
            print(f" name {name} dne", file=sys.stderr)
            return 1
        r = cw.adjust_item_weight(cw.get_item_id(name),
                                  int(round(float(w) * 0x10000)))
        if r < 0:            # named but linked into no bucket
            print("crushtool (2) No such file or directory",
                  file=sys.stderr)
            return 1
        modified = True
    if args.remove_item:
        print(f"crushtool removing item {args.remove_item}")
        if not cw.name_exists(args.remove_item):
            print(f" name {args.remove_item} dne", file=sys.stderr)
            return 1
        cw.remove_item(cw.get_item_id(args.remove_item))
        modified = True
    if args.add_item or args.update_item:
        from ..osdmap.simple_build import insert_item
        if args.add_item:
            dev, w, name = args.add_item
            loc = {t: n for t, n in args.loc}
            try:
                insert_item(cw, int(dev),
                            int(round(float(w) * 0x10000)), name, loc)
            except ValueError as e:
                print(f"crushtool {e}", file=sys.stderr)
                return 1
        else:
            # CrushWrapper::update_item: adjust in place when the
            # item already sits at the given location; otherwise
            # UNLINK it from wherever it lives and re-insert at the
            # new location under the (possibly new) name
            dev, w, name = args.update_item
            dev = int(dev)
            w16 = int(round(float(w) * 0x10000))
            loc = {t: n for t, n in args.loc}
            placed = False
            for t in sorted(cw.type_map):
                bname = loc.get(cw.type_map[t])
                if t == 0 or bname is None:
                    continue
                if not cw.name_exists(bname):
                    break
                bid = cw.get_item_id(bname)
                if dev in cw.crush.bucket(bid).items:
                    delta = cw._set_item_weight_in(bid, dev, w16)
                    cw._propagate_above(bid, delta)
                    cw.set_item_name(dev, name)
                    if cw.item_class:
                        cw.rebuild_roots_with_classes()
                    placed = True
                break
            if not placed:
                if cw._parent_of(dev) is not None:
                    cw.remove_item(dev)
                insert_item(cw, dev, w16, name, loc)
        modified = True
    if args.create_simple_rule:
        rname, root, ftype, mode = args.create_simple_rule
        cw.add_simple_rule(rname, root_name=root,
                           failure_domain_name=ftype, mode=mode)
        modified = True
    if args.create_replicated_rule:
        rname, root, ftype = args.create_replicated_rule
        r = cw.add_simple_rule(rname, root_name=root,
                               failure_domain_name=ftype,
                               device_class=args.device_class,
                               mode="firstn")
        if r < 0:
            print(f"create-replicated-rule failed: {r}",
                  file=sys.stderr)
            return 1
        modified = True
    if args.remove_rule:
        if not cw.rule_exists(args.remove_rule):
            print(f"rule {args.remove_rule} does not exist",
                  file=sys.stderr)
            return 0
        cw.remove_rule(cw.get_rule_id(args.remove_rule))
        modified = True
    if args.reweight:
        cw.reweight()
        modified = True

    # ---- stage 4: display/test (crushtool.cc:992-1028) ---------------------
    if args.show_location is not None:
        loc = cw.get_full_location(args.show_location)
        for k in sorted(loc):        # std::map: alphabetical by type
            print(f"{k}\t{loc[k]}")
    if args.tree:
        from ..crush.treedump import crush_tree_lines
        for line in crush_tree_lines(cw):
            print(line)
    if args.dump:
        from ..crush.dumpfmt import dump_json
        sys.stdout.write(dump_json(cw))
    if args.decompile is not None:
        text = CrushCompiler(cw).decompile()
        if args.outfn:
            with open(args.outfn, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        modified = False         # -o was consumed for the text
    if args.check is not None:
        _check_overlapped_rules(cw)
        if args.check >= 0 and not _check_name_maps(cw, args.check):
            return 1
    if args.test:
        t = CrushTester(cw)
        if args.num_rep >= 0:
            t.set_num_rep(args.num_rep)
        min_x, max_x = args.min_x, args.max_x
        if args.one_x is not None:
            min_x = max_x = args.one_x
        if min_x >= 0:
            t.set_min_x(min_x)
        if max_x >= 0:
            t.set_max_x(max_x)
        if args.rule >= 0:
            t.set_rule(args.rule)
        if args.min_rule >= 0:
            t.set_min_rule(args.min_rule)
        if args.max_rule >= 0:
            t.set_max_rule(args.max_rule)
        if args.ruleset >= 0:
            t.set_ruleset(args.ruleset)
        # --show-utilization[-all] implies statistics
        # (crushtool.cc:1017-1019)
        t.set_output_statistics(args.show_statistics
                                or args.show_utilization
                                or args.show_utilization_all)
        t.set_output_mappings(args.show_mappings)
        t.set_output_bad_mappings(args.show_bad_mappings)
        t.set_output_utilization(args.show_utilization)
        t.set_output_utilization_all(args.show_utilization_all)
        t.set_output_choose_tries(args.show_choose_tries)
        t.set_output_csv(args.output_csv, args.output_name)
        t.set_pool_id(args.pool_id)
        t.set_batches(args.batches)
        t.set_simulate(args.simulate)
        t.use_device = not args.host_mapper and \
            not args.show_choose_tries and args.pool_id < 0 and \
            not args.simulate
        for dev, w in args.weight:
            t.set_device_weight(int(dev), float(w))
        r = t.test()
        if r != 0:
            return r

    # ---- stage 5: output (crushtool.cc:1030-1047) --------------------------
    if modified:
        if not args.outfn:
            print("crushtool successfully built or modified map.  "
                  "Use \'-o <file>\' to write it out.")
        else:
            save_map(cw, args.outfn)
    return 0


def _do_build(args):
    """crushtool --build --num_osds N name alg size ...
    (src/tools/crushtool.cc:744): stack layers bottom-up, each layer
    packing the previous one\'s items into buckets of `size` (0 =
    everything into one bucket), named name<i> (bare name for size
    0); then build_simple_crush_rules over the top root, warning when
    several roots remain."""
    from ..crush.constants import (
        CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
        CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM)
    algs = {"uniform": CRUSH_BUCKET_UNIFORM,
            "list": CRUSH_BUCKET_LIST, "tree": CRUSH_BUCKET_TREE,
            "straw": CRUSH_BUCKET_STRAW,
            "straw2": CRUSH_BUCKET_STRAW2}
    if len(args.layers) % 3 or not args.layers:
        if args.layers:
            print(f"remaining args: [{','.join(args.layers)}]",
                  file=sys.stderr)
        print("layers must be specified with 3-tuples of "
              "(name, buckettype, size)", file=sys.stderr)
        return None
    for li in range(0, len(args.layers), 3):
        lname, lalg, lsize = args.layers[li:li + 3]
        if lalg not in algs:
            print(f"unknown bucket type \'{lalg}\'", file=sys.stderr)
            return None
        if not lsize.lstrip("-").isdigit() or int(lsize) < 0:
            print(f"invalid layer size \'{lsize}\'", file=sys.stderr)
            return None
    cw = CrushWrapper()
    cw.set_tunables_profile("jewel")
    cw.set_type_name(0, "osd")
    cw.set_max_devices(args.num_osds)
    lower = [(i, 0x10000) for i in range(args.num_osds)]
    for i in range(args.num_osds):
        cw.set_item_name(i, f"osd.{i}")
    t = 0
    lname = "osd"
    for li in range(0, len(args.layers), 3):
        lname, lalg, lsize = args.layers[li:li + 3]
        t += 1
        size = int(lsize)
        cw.set_type_name(t, lname)
        pos, idx = 0, 0
        cur = []
        while pos < len(lower):
            chunk = lower[pos:pos + size] if size else lower[pos:]
            pos += len(chunk)
            bid = cw.add_bucket(
                algs[lalg], t,
                f"{lname}{idx}" if size else lname,
                [c for c, _ in chunk], [w for _, w in chunk])
            cur.append((bid, sum(w for _, w in chunk)))
            idx += 1
        lower = cur
    root = lname if int(args.layers[-1]) == 0 else f"{lname}0"
    roots = [b.id for b in cw.crush.buckets
             if b is not None and cw._parent_of(b.id) is None]
    if len(roots) > 1:
        # crushtool.cc:832-838 (note the blank trailing line from the
        # final std::endl after the embedded newline)
        print(f"The crush rulesets will use the root {root}\n"
              "and ignore the others.\n"
              f"There are {len(roots)} roots, they can be\n"
              "grouped into a single root by appending something "
              "like:\n"
              "  root straw 0\n", file=sys.stderr)
    cw.add_simple_rule("replicated_rule", root_name=root,
                       failure_domain_name=cw.get_type_name(1),
                       mode="firstn", ruleno=0)
    return cw


if __name__ == "__main__":
    # die silently on a closed pipe (`tool ... | head`), like the
    # C++ tools\' default SIGPIPE disposition
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
