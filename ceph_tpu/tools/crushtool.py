"""crushtool — compile/decompile/test crush maps.

CLI surface mirrors the reference tool (src/tools/crushtool.cc): -c compile
text → map (pickled), -d decompile, -i map --test with
--num-rep/--min-x/--max-x/--show-statistics/--show-mappings/
--show-bad-mappings/--weight, and --build for quick hierarchies.  The
--test engine is CrushTester (crush/CrushTester.cc:472), running the
device mapper when eligible.

Maps are stored as python pickles of CrushWrapper (the reference's binary
encoding is a C++ serialization detail, not part of the compute contract).
"""
from __future__ import annotations

import argparse
import pickle
import sys

from ..crush.compiler import CrushCompiler
from ..crush.tester import CrushTester
from ..crush.wrapper import CrushWrapper


def load_map(path: str) -> CrushWrapper:
    with open(path, "rb") as f:
        return pickle.load(f)


def save_map(cw: CrushWrapper, path: str) -> None:
    with open(path, "wb") as f:
        pickle.dump(cw, f)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="crushtool")
    p.add_argument("-i", "--infn", help="input map file")
    p.add_argument("-o", "--outfn", help="output file")
    p.add_argument("-c", "--compile", dest="srcfn",
                   help="compile text map to binary")
    p.add_argument("-d", "--decompile", dest="decompile",
                   help="decompile map to text", nargs="?", const="",
                   default=None)
    p.add_argument("-t", "--test", action="store_true",
                   help="test a range of inputs on the map")
    p.add_argument("--num-rep", type=int, default=-1)
    p.add_argument("--min-x", type=int, default=-1)
    p.add_argument("--max-x", type=int, default=-1)
    p.add_argument("--rule", type=int, default=-1)
    p.add_argument("--show-statistics", action="store_true")
    p.add_argument("--show-mappings", action="store_true")
    p.add_argument("--show-bad-mappings", action="store_true")
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--weight", nargs=2, action="append", default=[],
                   metavar=("DEVNO", "WEIGHT"))
    p.add_argument("--host-mapper", action="store_true",
                   help="force the host interpreter (no device batch)")
    args = p.parse_args(argv)

    if args.srcfn:
        with open(args.srcfn) as f:
            text = f.read()
        cw = CrushCompiler().compile(text)
        out = args.outfn or "crushmap"
        save_map(cw, out)
        return 0

    if args.decompile is not None:
        path = args.decompile or args.infn
        if not path:
            print("decompile requires a map file", file=sys.stderr)
            return 1
        cw = load_map(path)
        text = CrushCompiler(cw).decompile()
        if args.outfn:
            with open(args.outfn, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0

    if args.test:
        if not args.infn:
            print("--test requires -i <map>", file=sys.stderr)
            return 1
        cw = load_map(args.infn)
        t = CrushTester(cw)
        if args.num_rep >= 0:
            t.set_num_rep(args.num_rep)
        if args.min_x >= 0:
            t.set_min_x(args.min_x)
        if args.max_x >= 0:
            t.set_max_x(args.max_x)
        if args.rule >= 0:
            t.set_rule(args.rule)
        t.set_output_statistics(args.show_statistics)
        t.set_output_mappings(args.show_mappings)
        t.set_output_bad_mappings(args.show_bad_mappings)
        t.set_output_utilization(args.show_utilization)
        t.use_device = not args.host_mapper
        for dev, w in args.weight:
            t.set_device_weight(int(dev), float(w))
        return t.test()

    p.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
