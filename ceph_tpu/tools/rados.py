"""rados — the object CLI against a checkpointed mini cluster.

The reference's `rados` tool (src/tools/rados/rados.cc) talks to a live
cluster; this framework's clusters are in-process, so the CLI operates
on a checkpoint directory (MiniCluster.checkpoint): restore, run the
op, checkpoint back for mutations.  Supported verbs mirror the
everyday reference surface:

  ls POOL | put POOL OID FILE | get POOL OID [FILE] | rm POOL OID
  stat POOL OID | setxattr POOL OID NAME VALUE | getxattr POOL OID NAME
  listxattr POOL OID | mksnap POOL SNAP | rmsnap POOL SNAP
  rollback POOL OID SNAP | lssnap POOL | df

Exit 0 on success, 1 on errors.
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rados")
    p.add_argument("--cluster", required=True,
                   help="checkpoint directory (MiniCluster.checkpoint)")
    p.add_argument("verb", choices=[
        "ls", "put", "get", "rm", "stat", "setxattr", "getxattr",
        "listxattr", "mksnap", "rmsnap", "rollback", "lssnap", "df"])
    p.add_argument("args", nargs="*")
    a = p.parse_args(argv)

    from ..cluster import MiniCluster
    c = MiniCluster.restore(a.cluster)
    cl = c.client("client.rados-cli")
    mutated = False
    try:
        v, rest = a.verb, a.args
        if v == "df":
            for pid, name in sorted(c.mon.osdmap.pool_name.items()):
                pool = c.mon.osdmap.pools[pid]
                kind = "erasure" if pool.is_erasure() else "replicated"
                print(f"{name}\t{kind}\tpg_num={pool.pg_num}"
                      f"\tsnaps={len(pool.snaps)}")
        elif v == "ls":
            (pool,) = rest
            # real client listing (rados_nobjects_list -> PGLS ops per
            # PG), not a store scan — exactly what the reference CLI does
            for o in sorted(cl.list_objects(pool)):
                print(o)
        elif v == "put":
            pool, oid, path = rest
            with open(path, "rb") as f:
                data = f.read()
            r = cl.write_full(pool, oid, data)
            if r < 0:
                print(f"put failed: {r}", file=sys.stderr)
                return 1
            mutated = True
        elif v == "get":
            if len(rest) < 2:
                print("get needs POOL OID [FILE]", file=sys.stderr)
                return 1
            pool, oid = rest[0], rest[1]
            data = cl.read(pool, oid)
            if len(rest) > 2:
                with open(rest[2], "wb") as f:
                    f.write(data)
            else:
                sys.stdout.buffer.write(data)
        elif v == "rm":
            pool, oid = rest
            if cl.remove(pool, oid) < 0:
                return 1
            mutated = True
        elif v == "stat":
            pool, oid = rest
            print(json.dumps({"oid": oid, "size": cl.stat(pool, oid)}))
        elif v == "setxattr":
            pool, oid, name, value = rest
            cl.setxattr(pool, oid, name, value.encode())
            mutated = True
        elif v == "getxattr":
            pool, oid, name = rest
            sys.stdout.buffer.write(cl.getxattr(pool, oid, name))
        elif v == "listxattr":
            pool, oid = rest
            for k in sorted(cl.getxattrs(pool, oid)):
                print(k)
        elif v == "mksnap":
            pool, snap = rest
            print(f"created pool {pool} snap {snap} "
                  f"id {cl.snap_create(pool, snap)}")
            mutated = True
        elif v == "rmsnap":
            pool, snap = rest
            cl.snap_remove(pool, snap)
            mutated = True
        elif v == "rollback":
            pool, oid, snap = rest
            if cl.rollback(pool, oid, snap) < 0:
                return 1
            mutated = True
        elif v == "lssnap":
            (pool,) = rest
            for sid, name in sorted(cl.snap_list(pool).items()):
                print(f"{sid}\t{name}")
    except (IOError, KeyError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if mutated:
        c.checkpoint(a.cluster)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
