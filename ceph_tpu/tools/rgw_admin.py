"""radosgw-admin CLI (src/rgw/rgw_admin.cc in the reference): user and
bucket administration against a MiniCluster checkpoint.

Verbs mirror the reference's common surface: user create/info/rm/list,
bucket list/stats/rm, and object listing within a bucket.
"""
from __future__ import annotations

import argparse
import json
import sys

from ..rgw import RGWError, RGWLite


def run(cluster, client, argv, meta_pool: str = "rgwmeta",
        data_pool: str = "rgwdata") -> int:
    ap = argparse.ArgumentParser(prog="radosgw-admin")
    ap.add_argument("--meta-pool", default=meta_pool)
    ap.add_argument("--data-pool", default=data_pool)
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("user")
    s.add_argument("verb", choices=["create", "info", "rm", "list",
                                    "modify", "suspend", "enable",
                                    "stats", "check"])
    s.add_argument("--uid", default=None)
    s.add_argument("--display-name", default="")
    s.add_argument("--max-buckets", type=int, default=None)
    s = sub.add_parser("key")
    s.add_argument("verb", choices=["create", "rm"])
    s.add_argument("--uid", default=None)
    s.add_argument("--access-key", default=None)
    s = sub.add_parser("caps")
    s.add_argument("verb", choices=["add", "rm"])
    s.add_argument("--uid", default=None)
    s.add_argument("--caps", default="")
    s = sub.add_parser("quota")
    s.add_argument("verb", choices=["set", "enable", "disable",
                                    "get"])
    s.add_argument("--uid", default=None)
    s.add_argument("--max-size", type=int, default=None)
    s.add_argument("--max-objects", type=int, default=None)
    s.add_argument("--quota-scope", default="user")
    s = sub.add_parser("bucket")
    s.add_argument("verb", choices=["list", "stats", "rm", "link",
                                    "unlink"])
    s.add_argument("--bucket", default=None)
    s.add_argument("--uid", default=None)
    s = sub.add_parser("gc")
    s.add_argument("verb", choices=["list", "process"])
    s = sub.add_parser("lc")
    s.add_argument("verb", choices=["list", "process"])
    s.add_argument("--bucket", default=None)
    args = ap.parse_args(argv)

    g = RGWLite(client, args.meta_pool, args.data_pool)
    out = sys.stdout
    try:
        return _dispatch(g, client, args, out)
    except RGWError as e:
        print(f"{args.cmd} {args.verb} failed: {e}", file=sys.stderr)
        return 1


def _dispatch(g, client, args, out) -> int:
    if args.cmd == "user":
        if args.verb == "create":
            u = g.create_user(args.uid, args.display_name)
            json.dump(u, out, indent=2, sort_keys=True)
            print(file=out)
        elif args.verb == "info":
            json.dump(g.get_user(args.uid), out, indent=2,
                      sort_keys=True)
            print(file=out)
        elif args.verb == "rm":
            g.delete_user(args.uid)
        elif args.verb == "list":
            for uid in g.list_users():
                print(uid, file=out)
        elif args.verb == "modify":
            u = g.modify_user(args.uid,
                              display_name=args.display_name or None,
                              max_buckets=args.max_buckets)
            json.dump(u, out, indent=2, sort_keys=True)
            print(file=out)
        elif args.verb in ("suspend", "enable"):
            u = g.modify_user(args.uid,
                              suspended=(args.verb == "suspend"))
            json.dump({"uid": u["uid"],
                       "suspended": u.get("suspended", False)},
                      out, indent=2, sort_keys=True)
            print(file=out)
        elif args.verb in ("stats", "check"):
            json.dump(g.user_stats(args.uid), out, indent=2,
                      sort_keys=True)
            print(file=out)
    elif args.cmd == "key":
        if args.verb == "create":
            json.dump(g.user_add_key(args.uid), out, indent=2,
                      sort_keys=True)
            print(file=out)
        else:
            g.user_rm_key(args.uid, args.access_key or "")
    elif args.cmd == "caps":
        caps = g.user_caps(args.uid,
                           add=args.caps if args.verb == "add"
                           else None,
                           rm=args.caps if args.verb == "rm"
                           else None)
        json.dump(caps, out, indent=2, sort_keys=True)
        print(file=out)
    elif args.cmd == "quota":
        if args.quota_scope != "user":
            print("quota: only --quota-scope=user is implemented",
                  file=sys.stderr)
            return 1
        if args.verb == "set":
            q = g.set_user_quota(args.uid, max_size=args.max_size,
                                 max_objects=args.max_objects)
        elif args.verb in ("enable", "disable"):
            q = g.set_user_quota(args.uid,
                                 enabled=(args.verb == "enable"))
        else:
            q = g.get_user(args.uid).get("quota", {})
        json.dump(q, out, indent=2, sort_keys=True)
        print(file=out)
    elif args.cmd == "gc":
        report = g.gc(repair=(args.verb == "process"))
        json.dump(report, out, indent=2, sort_keys=True)
        print(file=out)
    elif args.cmd == "lc":
        if args.verb == "list":
            json.dump(g.get_bucket_lifecycle(args.bucket), out,
                      indent=2, sort_keys=True)
        else:
            json.dump(g.lc_process(), out, indent=2, sort_keys=True)
        print(file=out)
    elif args.cmd == "bucket":
        if args.verb == "list":
            if args.uid:
                for b in g.list_buckets(args.uid):
                    print(b, file=out)
            elif args.bucket:
                for e in g.list_objects(args.bucket)["contents"]:
                    print(e["name"], file=out)
        elif args.verb == "stats":
            json.dump(g.bucket_stats(args.bucket), out, indent=2,
                      sort_keys=True)
            print(file=out)
        elif args.verb == "rm":
            g.delete_bucket(args.bucket)
        elif args.verb == "link":
            g.link_bucket(args.bucket, args.uid)
        elif args.verb == "unlink":
            g.unlink_bucket(args.bucket, args.uid)
    return 0


def main(argv=None) -> int:  # pragma: no cover - thin shell wrapper
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or "--help" in argv or "-h" in argv:
        # the reference prints its full usage and exits 1
        # (src/rgw/rgw_admin.cc usage(); src/test/cli/radosgw-admin/
        # help.t pins the bytes)
        from .rgw_admin_usage import USAGE
        sys.stdout.write(USAGE)
        return 1
    ap = argparse.ArgumentParser(prog="radosgw-admin", add_help=False)
    ap.add_argument("--checkpoint", required=True)
    ns, rest = ap.parse_known_args(argv)
    from ..cluster import MiniCluster
    c = MiniCluster.restore(ns.checkpoint)
    rc = run(c, c.client("client.rgw-admin"), rest)
    # rados.py's CLI contract: persist mutations back; reads don't
    # rewrite the checkpoint
    toks = [t for t in rest if not t.startswith("-")]
    mutating = (len(toks) >= 2 and
                (toks[0], toks[1]) in {
                    ("user", "create"), ("user", "rm"),
                    ("user", "modify"), ("user", "suspend"),
                    ("user", "enable"), ("key", "create"),
                    ("key", "rm"), ("caps", "add"), ("caps", "rm"),
                    ("quota", "set"), ("quota", "enable"),
                    ("quota", "disable"), ("bucket", "rm"),
                    ("bucket", "link"), ("bucket", "unlink"),
                    ("gc", "process"), ("lc", "process")})
    if rc == 0 and mutating:
        c.checkpoint(ns.checkpoint)
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
