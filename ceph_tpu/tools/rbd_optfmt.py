"""Help/usage formatting for the rbd CLI frontend.

Reproduces the reference rbd shell's help layout byte-exact
(src/tools/rbd/OptionPrinter.{h,cc} column algorithm and
src/tools/rbd/IndentStream.{h,cc} wrap semantics, plus the
boost::program_options two-column rendering used for the global
options) so the recorded CLI transcripts (src/test/cli/rbd/*.t)
replay verbatim.  The wrap algorithm is necessarily the same —
byte parity pins every break point — but the implementation is a
small string builder, not a streambuf.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

LINE_WIDTH = 80
MIN_NAME_WIDTH = 20
MAX_DESCRIPTION_OFFSET = LINE_WIDTH // 2


class IndentWriter:
    """Word-wrapping writer: continuation lines are indented to
    ``indent``; the first flush pads from ``initial_offset`` (the text
    already on the line) up to ``indent``.  ``delim`` is the break
    character set (OptionPrinter uses "[" for usage option lists and
    " " everywhere else)."""

    def __init__(self, indent: int, initial_offset: int,
                 line_length: int = LINE_WIDTH):
        self.indent = indent
        self.initial_offset = initial_offset
        self.line_length = line_length
        self.delim = " "
        self._buf = ""
        self._out: List[str] = []

    def set_delimiter(self, delim: str) -> None:
        self.delim = delim

    def _flush_line(self) -> None:
        if self.initial_offset >= self.indent:
            self.initial_offset = 0
            self._out.append("\n")
        self._out.append(" " * (self.indent - self.initial_offset))
        self.initial_offset = 0

    def write(self, text: str) -> None:
        for c in text:
            if c == "\n":
                self._buf += c
                self._flush_line()
                self._out.append(self._buf)
                self._buf = ""
                continue
            if c == "\t":
                c = " "
            if self.indent + len(self._buf) >= self.line_length:
                space_delim = self.delim == " "
                off = self._buf.rfind(self.delim)
                if off < 0 and not space_delim:
                    off = self._buf.rfind(" ")
                if off >= 0:
                    self._flush_line()
                    self._out.append(self._buf[:off])
                    self._buf = self._buf[off + (1 if space_delim else 0):]
                else:
                    self._flush_line()
                    self._out.append(self._buf)
                    self._buf = ""
                self._out.append("\n")
            self._buf += c

    def endl(self) -> None:
        self.write("\n")

    def text(self) -> str:
        return "".join(self._out)


class Opt:
    """One command option: ``short`` like "p" or None, ``long`` like
    "pool", ``has_arg``, ``required`` (rendered unbracketed in the
    usage line), ``desc`` (may contain explicit newlines at the
    reference's own break points)."""

    def __init__(self, long: str, desc: str, short: Optional[str] = None,
                 has_arg: bool = True, required: bool = False):
        self.short = short
        self.long = long
        self.has_arg = has_arg
        self.required = required
        self.desc = desc

    def format_name(self) -> str:
        if self.short:
            return f"-{self.short} [ --{self.long} ]"
        return f"--{self.long}"

    def format_parameter(self) -> str:
        return "arg" if self.has_arg else ""


class Positional:
    """One positional argument: displayed ``<name>``; ``variadic``
    renders ``[<name> ...]`` in the usage line and lifts the
    positional-count cap."""

    def __init__(self, name: str, desc: str, variadic: bool = False):
        self.name = name
        self.desc = desc
        self.variadic = variadic

    # column math counts the same width the reference does for an
    # option-styled entry ("--name" == "<name>" in length, no arg)
    def format_name(self) -> str:
        return "--" + self.name

    def format_parameter(self) -> str:
        return ""


def compute_name_width(positionals: Sequence[Positional],
                       options: Sequence[Opt], indent: int = 2) -> int:
    width = MIN_NAME_WIDTH
    for ent in list(positionals) + list(options):
        width = max(width, len(ent.format_name())
                    + len(ent.format_parameter()) + 1)
    width += indent
    return min(width, MAX_DESCRIPTION_OFFSET) + 1


def print_short(usage_prefix: str, positionals: Sequence[Positional],
                options: Sequence[Opt]) -> str:
    """The wrapped ``usage:`` block, starting after ``usage_prefix``
    (which the caller has already emitted)."""
    initial = len(usage_prefix)
    name_width = min(initial, MAX_DESCRIPTION_OFFSET) + 1
    w = IndentWriter(name_width, initial)
    w.set_delimiter("[")
    for o in options:
        if not o.required:
            w.write("[")
        w.write("--" + o.long)
        if o.has_arg:
            w.write(f" <{o.long}>")
        if not o.required:
            w.write("]")
        w.write(" ")
    w.endl()
    if positionals:
        w.set_delimiter(" ")
        for p in positionals:
            w.write(f"<{p.name}> ")
            if p.variadic:
                w.write(f"[<{p.name}> ...]")
                break
        w.endl()
    return w.text()


def print_detailed(positionals: Sequence[Positional],
                   options: Sequence[Opt]) -> str:
    out: List[str] = []
    name_width = compute_name_width(positionals, options)
    if positionals:
        out.append("Positional arguments\n")
        for p in positionals:
            left = f"  <{p.name}>"
            out.append(left)
            w = IndentWriter(name_width, len(left))
            w.write(p.desc)
            w.endl()
            out.append(w.text())
        out.append("\n")
    if options:
        out.append("Optional arguments\n")
        for o in options:
            left = "  " + o.format_name() + " " + o.format_parameter()
            out.append(left)
            w = IndentWriter(name_width, len(left))
            w.write(o.desc)
            w.endl()
            out.append(w.text())
        out.append("\n")
    return "".join(out)


def print_action_help(app: str, spec: Sequence[str],
                      positionals: Sequence[Positional],
                      options: Sequence[Opt], description: str,
                      extra_help: str = "") -> str:
    prefix = f"usage: {app} " + " ".join(spec)
    out = prefix + print_short(prefix, positionals, options)
    if description:
        out += "\n" + description + "\n"
    out += "\n" + print_detailed(positionals, options)
    if extra_help:
        out += extra_help + "\n\n"
    return out


def format_command_name(spec: Sequence[str],
                        alias: Optional[Sequence[str]]) -> str:
    name = " ".join(spec)
    if alias:
        name += " (" + " ".join(alias) + ")"
    return name


def print_command_list(app: str, banner: str,
                       commands: Sequence[Tuple[Sequence[str],
                                                Optional[Sequence[str]],
                                                str]],
                       global_opts: Sequence[Opt],
                       ) -> str:
    """The full ``rbd --help`` page: sorted command list with wrapped
    one-line descriptions, then the boost-rendered global options."""
    out = [f"usage: {app} <command> ...\n\n{banner}\n\n"]
    out.append("Positional arguments:\n  <command>\n")
    cmds = sorted(commands, key=lambda c: list(c[0]))
    indent = 4
    name_width = MIN_NAME_WIDTH
    for spec, alias, _ in cmds:
        name_width = max(name_width, len(format_command_name(spec, alias)))
    name_width = min(name_width + indent, MAX_DESCRIPTION_OFFSET) + 1
    for spec, alias, desc in cmds:
        left = " " * indent + format_command_name(spec, alias)
        out.append(left)
        w = IndentWriter(name_width, len(left))
        w.write(desc)
        w.endl()
        out.append(w.text())
    out.append("\n")
    out.append(boost_options_block("Optional arguments", global_opts))
    out.append(f"\nSee '{app} help <command>' for help on a specific "
               "command.\n")
    return "".join(out)


def boost_options_block(caption: str, options: Sequence[Opt]) -> str:
    """boost::program_options options_description rendering (caption +
    ':' header, two columns, description column = longest entry + 1)."""
    out = [caption + ":\n"]
    width = 0
    for o in options:
        left = "  " + o.format_name()
        if o.has_arg:
            left += " " + o.format_parameter()
        width = max(width, len(left) + 1)
    for o in options:
        left = "  " + o.format_name()
        if o.has_arg:
            left += " " + o.format_parameter()
        out.append(left + " " * (width - len(left)) + o.desc + "\n")
    return "".join(out)
