"""cephfs CLI — a cephfs-shell-style tool (src/tools/cephfs-shell in
later reference versions; the mount-and-poke role of qa workunits).

Verbs: mkfs, ls, mkdir, put/get (local file <-> fs file), cat, rm,
rmdir, mv, ln, stat, tree.
"""
from __future__ import annotations

import argparse
import json
import sys

from ..cephfs import CephFS


def run(cluster, client, argv, meta_pool: str = "fsmeta",
        data_pool: str = "fsdata") -> int:
    ap = argparse.ArgumentParser(prog="cephfs")
    ap.add_argument("--meta-pool", default=meta_pool)
    ap.add_argument("--data-pool", default=data_pool)
    ap.add_argument("verb", choices=[
        "mkfs", "ls", "mkdir", "put", "get", "cat", "rm", "rmdir",
        "mv", "ln", "stat", "tree", "fsck", "chmod", "chown"])
    ap.add_argument("--repair", action="store_true")
    ap.add_argument("args", nargs="*")
    a = ap.parse_args(argv)
    fs = CephFS(client, a.meta_pool, a.data_pool)
    v, rest = a.verb, a.args
    if v == "mkfs":
        fs.mkfs()
    elif v == "ls":
        (path,) = rest or ["/"]
        for name, ino in sorted(fs.listdir(path).items()):
            kind = {"dir": "d", "symlink": "l",
                    "remote": "h"}.get(ino.get("type"), "-")
            print(f"{kind} {ino.get('size', 0):>10} {name}")
    elif v == "mkdir":
        (path,) = rest
        fs.mkdir(path)
    elif v == "put":
        local, remote = rest
        with open(local, "rb") as f:
            data = f.read()
        if not fs.exists(remote):
            fs.create(remote)
        fs.truncate(remote, 0)
        fs.write(remote, data)
    elif v == "get":
        remote, local = rest
        with open(local, "wb") as f:
            f.write(fs.read(remote))
    elif v == "cat":
        (path,) = rest
        sys.stdout.buffer.write(fs.read(path))
    elif v == "rm":
        (path,) = rest
        fs.unlink(path)
    elif v == "rmdir":
        (path,) = rest
        fs.rmdir(path)
    elif v == "mv":
        src, dst = rest
        fs.rename(src, dst)
    elif v == "ln":
        target, link = rest
        fs.symlink(link, target)
    elif v == "stat":
        (path,) = rest
        json.dump(fs.stat(path), sys.stdout, indent=2, sort_keys=True)
        print()
    elif v == "chmod":
        mode, path = rest
        fs.chmod(path, int(mode, 8))
    elif v == "chown":
        owner, path = rest
        uid, gid = owner.split(":")
        fs.chown(path, int(uid), int(gid))
    elif v == "fsck":
        json.dump(fs.fsck(repair=a.repair), sys.stdout, indent=2,
                  sort_keys=True)
        print()
    elif v == "tree":
        (path,) = rest or ["/"]
        for dirpath, dirs, files in fs.walk(path):
            print(dirpath)
            for f in files:
                print(f"  {f}")
    return 0


def main(argv=None) -> int:  # pragma: no cover - thin shell wrapper
    argv = list(sys.argv[1:] if argv is None else argv)
    ap = argparse.ArgumentParser(prog="cephfs", add_help=False)
    ap.add_argument("--checkpoint", required=True)
    ns, rest = ap.parse_known_args(argv)
    from ..cluster import MiniCluster
    c = MiniCluster.restore(ns.checkpoint)
    return run(c, c.client("client.fs-cli"), rest)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
