"""psim — placement distribution simulator (src/tools/psim.cc analog).

Builds (or loads) an OSDMap, maps every PG of every pool, and prints the
per-OSD object count histogram — the quick eyeball check for CRUSH weight
fairness the reference ships as a standalone binary.
"""
from __future__ import annotations

import argparse
import pickle
import sys

import numpy as np

from ..crush.constants import CRUSH_ITEM_NONE
from ..osdmap import OSDMapMapping


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="psim")
    p.add_argument("mapfn", help="osdmap file (pickle)")
    p.add_argument("--objects", type=int, default=1024,
                   help="simulated objects per pool")
    p.add_argument("--host-mapper", action="store_true")
    args = p.parse_args(argv)

    with open(args.mapfn, "rb") as f:
        m = pickle.load(f)
    mapping = OSDMapMapping(use_device=not args.host_mapper)
    mapping.update(m)
    count = np.zeros(m.max_osd, dtype=np.int64)
    for pid, pool in m.pools.items():
        pm = mapping.pools[pid]
        for obj in range(args.objects):
            ps = obj % pool.pg_num
            for o in pm.acting[ps]:
                if o != CRUSH_ITEM_NONE:
                    count[o] += 1
    for o in range(m.max_osd):
        bar = "*" * int(60 * count[o] / max(1, count.max()))
        print(f"osd.{o}\t{count[o]}\t{bar}")
    used = count[count > 0]
    if len(used):
        print(f"avg {used.mean():.1f}  min {used.min()}  max {used.max()}  "
              f"spread {(used.max() - used.min()) / max(1, used.mean()):.2%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
