"""vstart-lite: a single-process mini cluster.

The reference's qa tiers spin real daemons on localhost (src/vstart.sh,
qa/standalone/ceph-helpers.sh); the TPU-native equivalent is one process
wiring mon + N OSDs + clients over the deterministic messenger fabric, with
the Thrasher controls (qa/tasks/ceph_manager.py:195 kill_osd, :373
revive_osd, :360 blackhole) as first-class methods.  All EC compute inside
the OSDs runs through the device codec.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .client import RadosClient
from .common import AdminSocket, PerfCountersCollection
from .common.config import g_conf
from .mon import Monitor
from .msg import Network
from .osd.osd import OSD


class MiniCluster:
    def __init__(self, n_osds: int = 6, osds_per_host: int = 1,
                 n_mons: int = 1,
                 _stores: Optional[Dict[int, object]] = None,
                 _bootstrap: bool = True):
        self.network = Network()
        if n_mons == 1:
            self.mons = [Monitor(self.network)]
        else:
            names = [f"mon.{r}" for r in range(n_mons)]
            self.mons = [
                Monitor(self.network, name=names[r], rank=r,
                        peers=[n for n in names if n != names[r]])
                for r in range(n_mons)]
        if _bootstrap:
            self.mons[0].bootstrap(n_osds, osds_per_host)
        if n_mons > 1:
            # initial election: rank 0 wins; recovery syncs the quorum
            self.mons[0].start_election()
            self.network.pump()
            if _bootstrap:
                # commit the bootstrap topology as epoch 1 so it is
                # replicated — a leader failover before the first pool
                # creation must not lose the cluster topology
                self.mons[0].publish()
                self.network.pump()
        self.osds: Dict[int, OSD] = {}
        self.perf_collection = PerfCountersCollection()
        mon_names = [m.name for m in self.mons]
        for i in range(n_osds):
            store = _stores.get(i) if _stores else None
            osd = OSD(self.network, i, store=store,
                      mon_name=mon_names[0], mon_names=mon_names)
            self.osds[i] = osd
            for m in self.mons:
                m.subscribe(osd.name)
            self.perf_collection.add(osd.perf_counters)
        if n_mons > 1 and _bootstrap:
            # osds subscribed after the bootstrap epoch: catch them up
            for osd in self.osds.values():
                self.mons[0].send_full_map(osd.name)
            self.network.pump()
        self.clock = 0.0
        from .mgr import Manager
        # the mgr always talks to the CURRENT leader (failover-safe)
        self.mgr = Manager(self.network, lambda: self.mon,
                           all_mons=self.mons)
        self.admin_socket = AdminSocket()
        self._register_admin_commands()
        # deterministic-fabric idle kick: once the message queue drains,
        # (1) flush encodes the async EC write pipeline parked in the
        # dispatch scheduler's collection window — their continuations
        # fan out the sub-op writes pump() then delivers — and (2)
        # resend unacked sub-writes (quiescence proves the message or
        # its ack was dropped).  Both are bounded, so pump terminates.
        self.network.add_idle_hook(self._idle_kick)

    def _idle_kick(self) -> bool:
        from .dispatch import g_dispatcher
        did = bool(g_dispatcher.pending_count() and g_dispatcher.flush())
        # threaded op queues defer pipeline continuations back through
        # the sharded wq — flush the pools so their fan-out reaches the
        # wire before pump decides the fabric is quiescent.  With
        # osd_op_queue_batch_intake, synchronous OSDs also leave intake
        # bursts queued until quiescence: drain them here so the mClock
        # tiers arbitrate the whole pump's burst at once (docs/QOS.md)
        for osd in self.osds.values():
            if osd.name in self.network.down:
                continue
            if len(osd.op_wq):
                if osd.op_tp is not None:
                    osd.drain_ops()
                    did = True
                else:
                    # wall-mode rate-blocked ops stay queued (the tick
                    # re-drives them); a zero-handled drain must not
                    # report progress or pump would spin
                    did = bool(osd.drain_ops()) or did
        if did:
            return True     # let pump drain the fan-out first
        for osd in self.osds.values():
            if osd.name in self.network.down:
                continue
            for pg in osd.pgs.values():
                be = pg.backend
                if be is not None and be.inflight_writes:
                    did = bool(be.sweep_inflight(idle=True)) or did
        return did

    @property
    def mon(self) -> Monitor:
        """The current live leader — the mon everything talks to;
        single-mon clusters return the only monitor.  During a failover
        window (no live leader yet) this returns a live mon for reads;
        mutations on it raise until a quorum re-forms (Monitor.publish
        guards), matching the reference's commands-stall-without-quorum
        behavior."""
        live = [m for m in self.mons if m.name not in self.network.down]
        for m in live:
            if m.is_leader():
                return m
        return live[0] if live else self.mons[0]

    # ---- checkpoint / resume (OSD.cc:2469+ init/resume model) --------------
    def checkpoint(self, directory: str) -> None:
        """Persist the whole cluster: mon store + every OSD's object
        store.  Resume with ``MiniCluster.restore``."""
        import os
        os.makedirs(directory, exist_ok=True)
        self.mon.save(os.path.join(directory, "mon.json"))
        meta = {"n_osds": len(self.osds), "n_mons": len(self.mons)}
        for i, osd in self.osds.items():
            osd.store.save(os.path.join(directory, f"osd.{i}.store"))
        import json
        with open(os.path.join(directory, "cluster.json"), "w") as f:
            json.dump(meta, f)

    @classmethod
    def restore(cls, directory: str) -> "MiniCluster":
        """Cold-start from a checkpoint: mount every store, load the mon
        map history, replay to the current epoch, re-peer; objects come
        back byte-exact."""
        import json
        import os
        from .os_store import MemStore
        with open(os.path.join(directory, "cluster.json")) as f:
            meta = json.load(f)
        n = meta["n_osds"]
        n_mons = meta.get("n_mons", 1)
        stores = {i: MemStore.load(os.path.join(directory, f"osd.{i}.store"))
                  for i in range(n)}
        c = cls(n_osds=n, n_mons=n_mons, _stores=stores, _bootstrap=False)
        c.mons[0].load(os.path.join(directory, "mon.json"))
        if n_mons > 1:
            # re-elect so the collect/last recovery replays the loaded
            # history onto the (empty) peons
            c.mons[0].start_election()
            c.network.pump()
        # boot: every osd catches up on the full map history and re-peers
        for osd in c.osds.values():
            c.mons[0].send_full_map(osd.name)
        c.network.pump()
        c.run_recovery()
        return c

    def _register_admin_commands(self) -> None:
        asok = self.admin_socket
        asok.register("perf dump",
                      lambda c, a: self.perf_collection.dump(
                          a.get("logger", ""), a.get("counter", "")),
                      "dump perfcounters")
        asok.register("config show", lambda c, a: g_conf.show_config(),
                      "show config values")

        def _config_set(c, a):
            # runtime reconfiguration with observer notification — the
            # `ceph daemon X config set` / `ceph tell ... injectargs`
            # role (md_config_t::set_val + apply_changes); validation
            # lives in ConfigProxy.set_checked, shared with the OSD's
            # wire MCommand handler
            out = g_conf.set_checked(a.get("name", ""),
                                     a.get("value", ""))
            out["success"] = True
            return out

        asok.register("config set", _config_set,
                      "set a config option at runtime")
        asok.register("config get",
                      lambda c, a: g_conf.get_checked(
                          a.get("name", "")),
                      "get one config value")
        asok.register("status",
                      lambda c, a: {"health": self.health(),
                                    "epoch": self.mon.osdmap.epoch,
                                    "num_osds": len(self.osds),
                                    "pg_states": self.pg_states()},
                      "cluster status")
        asok.register(
            "dump_historic_ops",
            lambda c, a: {o.name: o.op_tracker.dump_historic_ops()
                          for o in self.osds.values()},
            "recent completed ops with event timelines")
        asok.register(
            "dump_historic_slow_ops",
            lambda c, a: {o.name: o.op_tracker.dump_historic_slow_ops()
                          for o in self.osds.values()},
            "ops over complaint_time, with flight-recorded span trees")
        asok.register(
            "dump_ops_in_flight",
            lambda c, a: {o.name: o.op_tracker.dump_ops_in_flight()
                          for o in self.osds.values()},
            "in-flight ops")
        asok.register("mgr status", lambda c, a: self.mgr.status(),
                      "manager module status")
        asok.register(
            "balancer optimize",
            lambda c, a: {"changes": self.mgr.balancer_optimize()},
            "run one upmap balancer pass")
        from .common import g_kernel_timer
        from .trace import (devprof_perf_counters, g_devprof,
                            g_flight_recorder, g_perf_histograms,
                            g_tracer)
        def _prometheus(c, a):
            from .fault import g_breakers as _breakers
            self.mgr.check_degraded_codecs()   # fresh breaker -> check
            # refresh the devprof device-memory high-water gauge so
            # the scrape carries a current sample (scrape-time only —
            # never on the op path)
            g_devprof.sample_device_mem()
            return self.mgr.prometheus_metrics(
                self.perf_collection,
                histograms=g_perf_histograms,
                kernel_timer=g_kernel_timer,
                slow_ops={o.name: o.op_tracker.num_slow_ops
                          for o in self.osds.values()},
                breakers=_breakers)

        asok.register("prometheus metrics", _prometheus,
                      "prometheus text exposition")
        asok.register(
            "perf histogram dump",
            lambda c, a: g_perf_histograms.dump(
                a.get("logger", ""), a.get("name", "")),
            "dump 1D/2D perf histograms (axes + count grids)")
        asok.register(
            "dump_tracing",
            lambda c, a: {"enabled": g_tracer.enabled,
                          "spans": g_tracer.collector.dump(
                              a.get("daemon", "")),
                          "flight_recorder": g_flight_recorder.dump()},
            "recent spans per daemon + slow-op flight recorder")
        asok.register(
            "span tracing",
            lambda c, a: (g_tracer.enable(
                str(a.get("on", "1")).lower() in ("1", "true", "on")),
                {"enabled": g_tracer.enabled})[1],
            "enable/disable span tracing (host-side; zero device syncs)")
        asok.register(
            "pg_autoscale status",
            lambda c, a: self.mgr.pg_autoscale(apply=False),
            "per-pool pg_num recommendations (dry run)")
        from .common import g_kernel_timer, get_log, \
            register_config_observers
        register_config_observers(g_conf)
        asok.register(
            "log dump",
            lambda c, a: {"lines": get_log().dump_recent(
                int(a.get("n", 0) or 0), a.get("subsys", ""))},
            "dump the recent in-memory log ring")
        asok.register(
            "log set",
            lambda c, a: (g_conf.set_val(f"debug_{a['subsys']}",
                                         a["level"]),
                          {"ok": True})[1],
            "set debug_<subsys> level (log/gather)")
        asok.register(
            "kernel timings",
            lambda c, a: g_kernel_timer.dump(),
            "cumulative per-kernel device dispatch timings")
        asok.register(
            "kernel tracing",
            lambda c, a: (g_kernel_timer.enable(
                str(a.get("on", "1")).lower() in ("1", "true", "on")),
                {"enabled": g_kernel_timer.enabled})[1],
            "enable/disable per-kernel timing (adds a sync per call)")
        asok.register(
            "dump_op_pq_state",
            lambda c, a: {o.name: o.op_wq.dump()
                          for o in self.osds.values()},
            "per-shard op queue sizes and mclock tags")
        from .dispatch import dispatch_perf_counters, g_dispatcher
        self.perf_collection.add(dispatch_perf_counters())
        from .mesh import (g_chipstat, membership_perf_counters,
                           mesh_chip_perf_counters,
                           mesh_decode_perf_counters, mesh_perf_counters,
                           rateless_perf_counters)
        self.perf_collection.add(mesh_perf_counters())
        self.perf_collection.add(mesh_chip_perf_counters())
        self.perf_collection.add(rateless_perf_counters())
        self.perf_collection.add(membership_perf_counters())
        self.perf_collection.add(mesh_decode_perf_counters())
        asok.register(
            "mesh skew dump",
            lambda c, a: g_chipstat.dump(),
            "mesh chip-health scoreboard: per-chip probe EWMAs, skew "
            "ratios, suspects, per-chip latency percentiles")
        asok.register(
            "mesh skew reset",
            lambda c, a: (g_chipstat.reset(), {"reset": True})[1],
            "zero the chip-health scoreboard, its per-chip latency "
            "histogram and counters")
        from .osd.ec_backend import pipeline_perf_counters
        self.perf_collection.add(pipeline_perf_counters())
        from .common.work_queue import qos_perf_counters
        self.perf_collection.add(qos_perf_counters())
        asok.register(
            "dispatch dump",
            lambda c, a: g_dispatcher.dump(),
            "EC dispatch scheduler state: options, per-signature "
            "queues, counters, batch-occupancy histogram")
        asok.register(
            "dispatch flush",
            lambda c, a: {"flushed": g_dispatcher.flush()},
            "flush every pending EC dispatch queue now")
        from .trace import g_oplat, oplat_perf_counters
        self.perf_collection.add(oplat_perf_counters())
        asok.register(
            "latency dump",
            lambda c, a: g_oplat.dump(a.get("daemon", "")),
            "stage-latency ledger: per-daemon per-stage time "
            "attribution (count/total/share/p50/p99) for every op")
        asok.register(
            "latency reset",
            lambda c, a: (g_oplat.reset(), {"reset": True})[1],
            "zero the stage-latency ledger's histograms and counters")
        self.perf_collection.add(devprof_perf_counters())
        from .os_store import memstore_device_perf_counters
        self.perf_collection.add(memstore_device_perf_counters())
        asok.register(
            "prof dump",
            lambda c, a: g_devprof.dump(),
            "device-flow profiler: per-call-site host<->device "
            "transfers, compiles, host staging copies, device-memory "
            "high-water")
        asok.register(
            "prof reset",
            lambda c, a: (g_devprof.reset(), {"reset": True})[1],
            "zero the device-flow profiler's sites, counters and "
            "transfer-size histogram")
        from .recovery import aggregate_families, recovery_perf_counters
        self.perf_collection.add(recovery_perf_counters())
        asok.register(
            "recovery dump",
            lambda c, a: {
                "counters": recovery_perf_counters().dump(),
                "families": aggregate_families(self.osds.values()),
                "per_osd": {o.name: o.recovery_sched.dump()
                            for o in self.osds.values()},
            },
            "recovery scheduler state: pacing, per-codec-family "
            "bytes-moved-per-repaired-shard, repair vs full-stripe "
            "accounting")
        from .fault import fault_perf_counters, g_breakers, g_faults
        self.perf_collection.add(fault_perf_counters())

        def _fault_inject(c, a):
            # arm a site: fault inject name=<site> mode=prob|nth|once|
            # always [p=] [n=] [seed=] [count=] [error=device|timeout]
            # [match=]; validation errors surface as JSON like
            # every other asok hook
            casts = (("mode", str), ("p", float), ("n", int),
                     ("seed", int), ("count", int), ("error", str),
                     ("match", str), ("delay_us", int))
            unknown = set(a) - {"name"} - {k for k, _ in casts}
            if unknown:
                # a typo'd trigger key must not silently arm a very
                # different fault (mdoe=prob -> mode=always)
                raise ValueError(
                    f"unknown argument(s) {sorted(unknown)}; expected "
                    f"name, mode, p, n, seed, count, error, match, "
                    f"delay_us")
            kw = {}
            for key, cast in casts:
                if key in a:
                    try:
                        kw[key] = cast(a[key])
                    except (TypeError, ValueError):
                        raise ValueError(
                            f"invalid value '{a[key]}' for '{key}'")
            spec = g_faults.inject(a.get("name", ""), **kw)
            return {"site": spec.site, "armed": spec.dump()}

        asok.register(
            "fault inject", _fault_inject,
            "arm a fault-injection site (mode=prob|nth|once|always, "
            "p=, n=, seed=, count=, error=, match=, delay_us=)")
        asok.register(
            "fault list",
            lambda c, a: g_faults.list_sites()
            if a.get("format") == "json" else g_faults.dump(),
            "fault-injection site catalog + armed triggers "
            "(format=json for the machine-readable site list)")
        asok.register(
            "fault clear",
            lambda c, a: {"cleared": g_faults.clear(a.get("name", ""))},
            "disarm one site (name=) or every armed site")
        asok.register(
            "breaker dump",
            lambda c, a: g_breakers.dump(),
            "per-codec-signature circuit breaker states")
        asok.register(
            "tpu status", lambda c, a: self.tpu_status(),
            "single-pane cluster status: health, cluster-merged "
            "per-stage p99s, rates, open breakers, SLO state")
        asok.register(
            "telemetry dump",
            lambda c, a: self.mgr.telemetry.dump(),
            "mgr telemetry rollup: cluster-merged family percentiles, "
            "rates and SLO burn state over the fast window")
        asok.register(
            "telemetry reset",
            lambda c, a: (self.mgr.telemetry.reset(),
                          {"reset": True})[1],
            "drop the telemetry rings and SLO streaks (per-daemon "
            "histograms/counters untouched)")
        from .control import control_perf_counters
        self.perf_collection.add(control_perf_counters())
        asok.register(
            "tpu control dump",
            lambda c, a: self.mgr.control.dump(),
            "control-plane pane: enable/bounds/cooldown state per "
            "knob, the active abuser, and the actuation ledger")

        def _control_enable(c, a, value):
            from .common.config import g_conf
            g_conf.set_checked("mgr_control_enable", value)
            if not value:
                # a disable must tear the episode down NOW, not at
                # the next tick (no half-applied knob survives)
                self.mgr.control.teardown(
                    self.mgr, reason="control disable")
            return {"enabled": value}

        asok.register(
            "control enable",
            lambda c, a: _control_enable(c, a, True),
            "turn the mgr feedback controller on "
            "(mgr_control_enable = true)")
        asok.register(
            "control disable",
            lambda c, a: _control_enable(c, a, False),
            "turn the controller off and restore every engaged knob "
            "to its episode baseline immediately")
        asok.register(
            "control reset",
            lambda c, a: {"reset": True,
                          "restored": self.mgr.control.reset(self.mgr)},
            "tear down any episode, then drop the ledger, tick count "
            "and sense caches")
        from .mgr.incident import incident_perf_counters
        from .trace.journal import g_journal, journal_perf_counters
        self.perf_collection.add(journal_perf_counters())
        self.perf_collection.add(incident_perf_counters())
        # the mgr is a map subscriber with no daemon references; the
        # cluster wires the forensic slow-op source where the OSDs live
        self.mgr.incident.slow_ops_source = lambda: {
            o.name: o.op_tracker.dump_historic_slow_ops()
            for o in self.osds.values()}
        asok.register(
            "journal dump",
            lambda c, a: g_journal.dump(a.get("daemon", "")),
            "cluster event journal: bounded per-daemon rings of typed "
            "events on the deterministic clock (daemon= for one ring)")
        asok.register(
            "journal reset",
            lambda c, a: g_journal.reset(),
            "drop every journal ring (per-daemon sequence numbers "
            "keep counting)")
        asok.register(
            "tpu incident list",
            lambda c, a: self.mgr.incident.list(),
            "archived incident bundles: id, clock, state, trigger, "
            "timeline size")
        asok.register(
            "tpu incident dump",
            lambda c, a: self.mgr.incident.dump(
                int(a.get("id", 0) or 0)),
            "one incident bundle in full (newest unless id=)")

        def _incident_capture(c, a):
            bundle = self.mgr.incident.capture(
                "operator", "operator-requested capture",
                reason="operator")
            if bundle is None:
                return {"captured": False}
            return {"captured": True, "id": bundle["id"],
                    "events": len(bundle["timeline"])}

        asok.register(
            "tpu incident capture", _incident_capture,
            "snapshot an incident bundle now (same payload as an "
            "auto-capture; drops, never fails, under an injected "
            "mgr.incident_capture fault)")

        from .chaos import chaos_perf_counters
        self.perf_collection.add(chaos_perf_counters())

        def _chaos_compose(c, a):
            # compose-only: sample the storyline a seed deterministically
            # maps to, without executing it (legs= narrows the catalog:
            # comma-separated leg names)
            from .chaos import compose_scenario
            try:
                seed = int(a.get("seed", ""))
            except (TypeError, ValueError):
                raise ValueError("chaos compose requires seed=<int>")
            legs = None
            if a.get("legs"):
                legs = tuple(
                    s for s in str(a["legs"]).split(",") if s)
            return compose_scenario(seed, legs=legs).dump()

        asok.register(
            "chaos compose", _chaos_compose,
            "deterministically sample the composed-chaos storyline for "
            "seed=<int> (legs= to force the leg set) without running it")

        def _chaos_dump(c, a):
            from .chaos import engine_dump
            return engine_dump()

        asok.register(
            "chaos dump", _chaos_dump,
            "chaos engine pane: leg catalog, fault-site inventory, "
            "composer options, scenario counters")
        asok.register(
            "arch probe",
            lambda c, a: __import__("ceph_tpu.arch", fromlist=["probe"])
            .probe(),
            "accelerator/host feature probe")

    # ---- pools ------------------------------------------------------------
    def create_ec_pool(self, name: str, k: int = 4, m: int = 2,
                       pg_num: int = 32, plugin: str = "tpu",
                       extra_profile: Optional[Dict[str, str]] = None,
                       failure_domain: str = "host",
                       ec_overwrites: bool = True) -> int:
        profile = {"plugin": plugin, "k": str(k), "m": str(m),
                   "crush-failure-domain": failure_domain}
        if extra_profile:
            profile.update(extra_profile)
        pname = f"{name}_profile"
        self.mon.create_ec_profile(pname, profile)
        pid = self.mon.create_ec_pool(name, pname, pg_num,
                                      ec_overwrites=ec_overwrites)
        self.publish()
        return pid

    def pool_snap_create(self, pool: str, snap: str) -> int:
        sid = self.mon.pool_snap_create(pool, snap)
        self.publish()
        return sid

    def pool_snap_rm(self, pool: str, snap: str) -> int:
        sid = self.mon.pool_snap_rm(pool, snap)
        self.publish()
        return sid

    def create_replicated_pool(self, name: str, size: int = 3,
                               pg_num: int = 32) -> int:
        pid = self.mon.create_replicated_pool(name, size, pg_num)
        self.publish()
        return pid

    def delete_pool(self, name: str) -> int:
        pid = self.mon.delete_pool(name)
        self.publish()
        return pid

    # ---- control ----------------------------------------------------------
    def publish(self) -> None:
        self.mon.publish()
        self.network.pump()
        self.run_recovery()

    def client(self, name: str = "client.0") -> RadosClient:
        return RadosClient(self.network, self.mon, name)

    def tick(self, dt: float = 1.0, rounds: int = 1) -> None:
        """Advance time: heartbeats fire, failures get detected, mon
        elections resolve."""
        for _ in range(rounds):
            self.clock += dt
            for m in self.mons:
                if m.name not in self.network.down:
                    m.tick(self.clock)
            for i, osd in self.osds.items():
                if osd.name not in self.network.down:
                    osd.tick(self.clock)
            self.network.pump()
            self.mgr.tick(self.clock)
        self.run_recovery()

    # ---- mon thrashing ------------------------------------------------------
    def kill_mon(self, rank: int) -> None:
        self.network.set_down(self.mons[rank].name, True)

    def revive_mon(self, rank: int) -> None:
        mon = self.mons[rank]
        self.network.set_down(mon.name, False)
        mon.start_election()  # rejoin: triggers re-election + catch-up
        self.network.pump()

    def scrub(self, deep: bool = True) -> None:
        """Background consistency pass over every PG (qa deep-scrub
        role): primaries collect shard scrub maps, inconsistencies become
        missing entries, recovery repairs them by decode — no client
        reads involved.  deep=False runs the metadata-only shallow
        variant (sizes + attr/omap digests, no data reads)."""
        for osd in self.osds.values():
            if osd.name in self.network.down:
                continue
            for pg in osd.pgs.values():
                if pg.is_primary():
                    pg.start_scrub(deep=deep)
        self.network.pump()
        self.run_recovery()

    def run_recovery(self, max_rounds: int = 4) -> int:
        total = 0
        for _ in range(max_rounds):
            pushed = 0
            for osd in self.osds.values():
                if osd.name not in self.network.down:
                    pushed += osd.run_recovery()
            self.network.pump()
            total += pushed
            if not pushed:
                break
        return total

    def restart_osd(self, osd_id: int) -> None:
        """Simulate a daemon restart: a fresh OSD process mounts the same
        object store — in-memory state (pg logs, inflight ops) must come
        back from disk (OSD::init, OSD.cc:2469+)."""
        old = self.osds[osd_id]
        old.shutdown()
        self.network.set_down(old.name, False)
        osd = OSD(self.network, osd_id, store=old.store,
                  mon_name=old.mon_name, mon_names=old.mon_names)
        self.osds[osd_id] = osd
        self.perf_collection.add(osd.perf_counters)  # replaces by name
        if not self.mon.osdmap.is_up(osd_id):
            self.mon.mark_osd_up(osd_id)
        self.mon.send_full_map(osd.name)
        self.network.pump()
        self.run_recovery()

    # ---- thrasher API ------------------------------------------------------
    def kill_osd(self, osd_id: int) -> None:
        """Hard-kill: the daemon stops answering anything
        (ceph_manager.py:195)."""
        self.network.set_down(f"osd.{osd_id}", True)

    def revive_osd(self, osd_id: int) -> None:
        """Bring the daemon back and let it catch up on maps
        (ceph_manager.py:373)."""
        self.network.set_down(f"osd.{osd_id}", False)
        osd = self.osds[osd_id]
        self.mon.mark_osd_up(osd_id)
        self.mon.send_full_map(osd.name)
        self.network.pump()
        self.run_recovery()

    def blackhole_osd(self, osd_id: int, on: bool = True) -> None:
        """Drop all traffic to the osd without killing it
        (ceph_manager.py:360)."""
        for name in list(self.network.endpoints):
            self.network.blackhole(name, f"osd.{osd_id}", on)

    def mark_osd_down(self, osd_id: int) -> None:
        self.mon.mark_osd_down(osd_id)
        self.network.pump()
        self.run_recovery()

    def mark_osd_out(self, osd_id: int) -> None:
        self.mon.mark_osd_out(osd_id)
        self.network.pump()
        self.run_recovery()

    def mark_osd_in(self, osd_id: int) -> None:
        self.mon.mark_osd_in(osd_id)
        self.network.pump()
        self.run_recovery()

    # ---- introspection -----------------------------------------------------
    def pg_states(self) -> Dict[str, str]:
        return {f"{pgid[0]}.{pgid[1]:x}": pg.state
                for pgid, pg in self.primary_pgs()}

    def primary_pgs(self):
        """(pgid, pg) for each PG's live primary — THE pg scan used by
        pg_states/health/CLIs so their accounting cannot drift."""
        seen = set()
        for osd in self.osds.values():
            if osd.name in self.network.down:
                continue
            for pgid, pg in osd.pgs.items():
                if pgid in seen or not pg.is_primary():
                    continue
                seen.add(pgid)
                yield pgid, pg

    def tpu_status(self) -> Dict:
        """The ``tpu status`` single pane (admin socket / ``ceph
        daemon``): one answer to "is the fleet inside its latency
        budget right now" — health (TPU_SLO_* checks included), the
        cluster-merged per-stage p99s, rates, open circuit breakers
        and SLO burn state, all from the mgr telemetry rollup's
        shared snapshot (telemetry.rollup) so this pane, ``telemetry
        dump`` and the Prometheus scrape cannot disagree."""
        from .fault import g_breakers
        from .mesh import g_chipstat
        tel = self.mgr.telemetry
        # freshen if the clock moved since the last mgr tick (a stale
        # or equal clock is a no-op, so this never skews rate windows)
        tel.tick(self.mgr, self.clock)
        roll = tel.rollup()
        skew = g_chipstat.summary()
        return {
            "health": self.health(),
            "samples": roll["samples"],
            "window_s": roll["window_s"],
            "cluster_p99_usec": roll["oplat_p99_usec"],
            "rates": roll["rates"],
            "copies_per_op": roll["copies_per_op"],
            "breakers_open": ["/".join(d["signature"][:4])
                              for d in g_breakers.degraded()],
            "slo": {check: st["state"]
                    for check, st in roll["slo"].items()},
            # the chip-health scoreboard's verdict pane: suspects name
            # the chip and its skew ratio (TPU_MESH_SKEW's figures)
            "mesh_skew": {"probes": skew["probes"],
                          "suspects": skew["suspects"]},
            "objectives": roll["objectives"],
        }

    def health(self) -> str:
        """HEALTH_OK / HEALTH_WARN with reasons (mon health checks):
        down osds, degraded/peering pgs, pinned pg_temp remaps,
        degraded codec signatures (TPU_CODEC_DEGRADED)."""
        # refresh breaker-derived checks so health() is current even
        # between mgr ticks (tests and CLIs call it directly); the
        # chip-skew check refreshes the same way (its hysteresis lives
        # in the scoreboard, so re-reading it never flaps)
        self.mgr.check_degraded_codecs()
        self.mgr.check_mesh_skew()
        reasons = []
        n_down = sum(1 for o in range(self.mon.osdmap.max_osd)
                     if not self.mon.osdmap.is_up(o))
        if n_down:
            reasons.append(f"{n_down} osds down")
        states = {}
        for _pgid, pg in self.primary_pgs():
            states[pg.state] = states.get(pg.state, 0) + 1
        bad = {st: n for st, n in states.items() if st != "active"}
        if bad:
            reasons.append("pgs " + ", ".join(
                f"{n} {st}" for st, n in sorted(bad.items())))
        if self.mon.osdmap.pg_temp:
            reasons.append(
                f"{len(self.mon.osdmap.pg_temp)} pgs remapped (pg_temp)")
        from .osdmap.osdmap import CEPH_OSDMAP_FULL, CEPH_OSDMAP_NEARFULL
        if self.mon.osdmap.flags & CEPH_OSDMAP_FULL:
            reasons.append("cluster is FULL; writes blocked")
        elif self.mon.osdmap.flags & CEPH_OSDMAP_NEARFULL:
            reasons.append("cluster is nearfull")
        for check, msg in sorted(self.mgr.health_checks.items()):
            reasons.append(f"{check}: {msg}")
        return "HEALTH_OK" if not reasons else \
            "HEALTH_WARN " + "; ".join(reasons)
