"""``python -m ceph_tpu.bench`` — run the fenced harness standalone.

Modes:
  --smoke       CPU, tiny shapes, seconds-fast: proves the harness
                itself (fence, stats, roofline, schema, gate) end to
                end.  Wired into the test suite so every PR regression-
                tests the measurement machinery.  The CRUSH remap
                workload is excluded here — its XLA compiles alone blow
                a seconds-scale budget on CPU; the survivability driver
                (repo-root bench.py) owns it.
  (default)     full fenced EC encode/decode + parity on whatever
                backend jax selects.

  --gate off|warn|fail   compare fenced metrics against the archived
                BENCH_r*.json trajectory (regress.py); "fail" exits 2
                on a regression beyond --tolerance.

Output: ONE JSON line on stdout carrying schema-valid metrics; human
progress goes to stderr.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m ceph_tpu.bench")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU, tiny shapes, seconds-fast harness check")
    ap.add_argument("--gate", choices=("off", "warn", "fail"),
                    default="warn")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="relative regression tolerance (default 0.30)")
    ap.add_argument("--repeats", type=int, default=None)
    ap.add_argument("--root", default=None,
                    help="repo root holding BENCH_r*.json (default: "
                         "two levels above this package)")
    args = ap.parse_args(argv)

    if args.smoke:
        # must land before any jax import in this process
        os.environ["JAX_PLATFORMS"] = "cpu"
        # the mesh workload needs a multi-chip topology: the virtual
        # host platform provides 8 CPU devices for the smoke tier
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    t0 = time.monotonic()
    import numpy as np
    import jax
    if args.smoke:
        jax.config.update("jax_platforms", "cpu")

    from . import regress
    from .workloads import (bench_perf_counters,
                            measure_composed_chaos, measure_decode,
                            measure_degraded_read,
                            measure_dispatch_coalesce,
                            measure_ec_mesh, measure_ec_pipeline,
                            measure_ec_write_zero_copy,
                            measure_encode, measure_host_native,
                            measure_mesh_skew, measure_mesh_straggler,
                            measure_recovery_storm,
                            measure_slo_autotune, measure_traffic,
                            parity_check)
    from ..gf.matrices import gf_gen_rs_matrix

    K, M = 8, 4
    if args.smoke:
        batch_s, chunk = 2, 8192
        target_s, repeats, warmup = 0.3, (args.repeats or 2), 1
    else:
        batch_s, chunk = 64, 1 << 17
        target_s, repeats, warmup = 3.0, (args.repeats or 3), 1

    rng = np.random.default_rng(1234)
    matrix = gf_gen_rs_matrix(K + M, K)
    batch = rng.integers(0, 256, size=(batch_s, K, chunk),
                         dtype=np.uint8)

    result = {
        "schema_version": 1,
        "mode": "smoke" if args.smoke else "full",
        "platform": jax.devices()[0].platform,
        "metrics": [],
    }

    def progress(msg: str) -> None:
        print(f"[bench] {msg}", file=sys.stderr)

    progress(f"platform={result['platform']} "
             f"batch=({batch_s},{K},{chunk})")
    rc = 0
    try:
        m = measure_encode(matrix, batch, target_seconds=target_s,
                           repeats=repeats, warmup=warmup)
        result["metrics"].append(m)
        progress(f"encode {m['value']} GiB/s fenced "
                 f"(roofline: {m['roofline']['verdict']})")
        m = measure_decode(matrix, batch, target_seconds=target_s,
                           repeats=repeats, warmup=warmup)
        result["metrics"].append(m)
        progress(f"decode {m['value']} GiB/s fenced "
                 f"(roofline: {m['roofline']['verdict']})")
        mc, ms = measure_dispatch_coalesce(
            n_requests=8 if args.smoke else 32,
            target_seconds=0.3 if args.smoke else 2.0,
            repeats=repeats, warmup=warmup)
        result["metrics"] += [mc, ms]
        progress(f"dispatch_coalesce {mc['value']} GiB/s coalesced vs "
                 f"{ms['value']} serial (x{mc['speedup']}, "
                 f"occupancy {mc['batch_occupancy']})")
        mp, mp1 = measure_ec_pipeline(
            n_requests=16 if args.smoke else 64,
            target_seconds=0.3 if args.smoke else 2.0,
            repeats=repeats, warmup=warmup)
        result["metrics"] += [mp, mp1]
        progress(f"ec_pipeline {mp['value']} GiB/s depth-8 vs "
                 f"{mp1['value']} depth-1 (x{mp['speedup']}, occupancy "
                 f"{mp['mean_batch_occupancy']}, identical "
                 f"{mp['identical']})")
        # mesh runtime (ceph_tpu/mesh): the same salted k8m4 encode
        # step across the batch-axis mesh vs one device, drained per
        # shard, plus the dispatch-path identity/occupancy receipt
        mm, mm1 = measure_ec_mesh(
            matrix, mesh_chips=8 if args.smoke else -1,
            target_seconds=0.3 if args.smoke else 2.0,
            repeats=repeats, warmup=warmup,
            n_steps=3 if args.smoke else None)
        result["metrics"] += [mm, mm1]
        occupied = sum(1 for v in mm["per_chip_stripes"].values()
                       if v > 0)
        progress(f"ec_mesh {mm['value']} GiB/s over {mm['mesh_chips']} "
                 f"chips vs {mm1['value']} single (x{mm['speedup']}, "
                 f"identical {mm['identical']}, "
                 f"chips occupied {occupied}/{mm['mesh_chips']})")
        # the straggler ruler (ceph_tpu/mesh/chipstat): mesh twin
        # healthy vs one-chip-slowed, scoreboard detection latency +
        # TPU_MESH_SKEW raise/clear gated by regress.py's SKEW GATE
        msk = measure_mesh_skew()
        result["metrics"].append(msk)
        sk = msk["skew"]
        progress(f"mesh_skew chip {sk['detected_chip']} at "
                 f"x{sk['skew_ratio_detected']} detected in "
                 f"{sk['detection_probes']} probes (healthy false "
                 f"suspects {sk['healthy_false_suspects']}, raised "
                 f"{sk['raised']}, cleared {sk['cleared']}, identical "
                 f"{msk['identical']})")
        # the straggler-proof encode A/B (ceph_tpu/mesh/rateless):
        # rateless-coded mesh healthy vs one chip slowed 10x, the
        # protected p999 ratio + byte-identity + bandwidth overhead
        # gated by regress.py's STRAGGLER GATE
        mst = measure_mesh_straggler(
            n_flushes=24 if args.smoke else 48)
        result["metrics"].append(mst)
        st = mst["straggler"]
        progress(f"mesh_straggler protected p999 "
                 f"x{st['protected_p999_ratio']} rollup / "
                 f"x{st['protected_p999_wall_ratio']} wall of healthy "
                 f"(unprotected x{st['unprotected_p999_wall_ratio']}, "
                 f"detected in {st['detection_probes']} probes, "
                 f"bw overhead x{st['bandwidth_overhead']}, "
                 f"subset completions {st['subset_completions']}, "
                 f"identical {mst['identical']})")
        # traffic harness (ceph_tpu/load): ≥8 concurrent synthetic
        # clients over the real client stack; the smoke shape is <10 s
        # on CPU, the full mode drives a deeper closed loop
        mt = measure_traffic(
            n_clients=8,
            ops_per_client=32 if args.smoke else 256,
            name="traffic_harness_smoke" if args.smoke
            else "traffic_harness")
        result["metrics"].append(mt)
        progress(f"traffic {mt['value']} ops/s over "
                 f"{mt['n_clients']} clients ({mt['total_ops']} ops, "
                 f"byte_exact {mt['byte_exact']}, agg p99 "
                 f"{mt['aggregate'].get('p99')}us)")
        roll = mt["cluster_rollup"]
        progress(f"cluster rollup: reply p99 "
                 f"{roll['oplat_p99_usec'].get('reply')}us, "
                 f"{roll['rates'].get('ops')} ops/s, slo {roll['slo']}")
        # zero-copy write path (docs/DISPATCH.md): device-resident
        # shard store + fused encode+crc vs the host-bytes twin, the
        # devflow A/B judged by regress.py's ZERO-COPY gate
        mz = measure_ec_write_zero_copy(
            n_objects=6 if args.smoke else 24)
        result["metrics"].append(mz)
        zc = mz["zero_copy"]
        progress(f"ec_write_zero_copy {mz['value']} ops/s resident vs "
                 f"{mz['twin_ops_per_sec']} bytes-twin "
                 f"(copies/op {zc['resident_copies_per_op']} vs "
                 f"{zc['twin_copies_per_op']}, resident d2h "
                 f"{zc['resident_d2h_bytes_per_op']} B/op, "
                 f"{zc['resident_shards']} shards resident, "
                 f"byte_exact {zc['byte_exact']})")
        # recovery storm (ceph_tpu/recovery, docs/RECOVERY.md): kill
        # an OSD under open-loop traffic, gate bytes-moved-per-
        # repaired-shard for the regenerating family vs RS full-stripe
        mr = measure_recovery_storm(
            n_objects=6 if args.smoke else 24,
            ops_per_client=8 if args.smoke else 48)
        result["metrics"].append(mr)
        rec = mr["recovery"]
        progress(f"recovery_storm {rec['bytes_per_repaired_shard_regen']}"
                 f" B/shard regen vs {rec['bytes_per_repaired_shard_rs']}"
                 f" RS (ratio {rec['regen_vs_rs_ratio']}, identical "
                 f"{mr['identical']}, slo {mr['slo']})")
        # degraded-read A/B (ceph_tpu/mesh, docs/DISPATCH.md): shard
        # kill under open-loop traffic, then meshed rateless decode
        # healthy vs one chip slowed 10x vs the mesh-off single-device
        # twin — the read-side STRAGGLER GATE receipt
        md = measure_degraded_read(
            n_batches=10 if args.smoke else 32,
            ops_per_client=6 if args.smoke else 32)
        result["metrics"].append(md)
        sd = md["straggler"]
        progress(f"degraded_read protected p999 "
                 f"x{sd['protected_p999_ratio']} rollup / "
                 f"x{sd['protected_p999_wall_ratio']} wall of healthy "
                 f"(detected in {sd['detection_probes']} probes, "
                 f"bw overhead x{sd['bandwidth_overhead']}, "
                 f"subset completions {sd['subset_completions']}, "
                 f"fallbacks {sd['single_device_fallbacks']}, "
                 f"identical {md['identical']})")
        # self-tuning control plane (ceph_tpu/control, docs/CONTROL.md):
        # the three closed-loop scenarios on real clusters, the
        # actuation receipts gated by regress.py's CONTROL GATE
        # self-tuning control plane (ceph_tpu/control, docs/CONTROL.md):
        # the three closed-loop scenarios on real clusters, the
        # actuation receipts gated by regress.py's CONTROL GATE
        ma = measure_slo_autotune()
        result["metrics"].append(ma)
        ctrl = ma["control"]
        scen = ctrl["scenarios"]
        progress(f"slo_autotune worst converge {ma['value']} ticks "
                 f"(admission {scen['admission']['converge_ticks']}, "
                 f"recovery {scen['recovery']['converge_ticks']}, "
                 f"straggler {scen['straggler']['converge_ticks']}; "
                 f"disabled twin moves {ctrl['disabled_moves']}, "
                 f"byte_exact {ctrl['byte_exact']})")
        # composed chaos (ceph_tpu/chaos, docs/CHAOS.md): pinned
        # seeded storylines end to end, every receipt re-judged by
        # regress.py's CHAOS GATE as absolute invariants.  Smoke runs
        # ONE storyline (seed 24 exercises straggler + chip-fail +
        # elastic membership) to stay inside the seconds-scale budget;
        # both pinned seeds run in tier-1, all four in the full mode
        mx = measure_composed_chaos(
            seeds=(24,) if args.smoke
            else (24, 103, 196, 20260807))
        result["metrics"].append(mx)
        chb = mx["chaos"]
        progress(f"composed_chaos {mx['value']} ops/s over "
                 f"{len(chb['receipts'])} storylines (accepted "
                 f"{chb['accepted']}, wedges "
                 f"{sum(1 for r in chb['receipts'] if r['wedged'])}, "
                 f"byte_exact "
                 f"{all(r['byte_exact'] for r in chb['receipts'])})")
        host = measure_host_native(matrix, batch[0],
                                   target_seconds=0.3 if args.smoke
                                   else 1.5)
        if host is not None:
            result["metrics"].append(host)
        result["decode_parity"] = parity_check(matrix)
        if not result["decode_parity"]:
            rc = 1
    except Exception as e:
        result["error"] = repr(e)
        rc = 1

    if args.gate != "off":
        root = args.root or os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        traj = regress.load_trajectory(root)
        gate = regress.compare_against_trajectory(
            result["metrics"], traj, result["platform"],
            tolerance=(args.tolerance
                       if args.tolerance is not None
                       else regress.DEFAULT_TOLERANCE))
        result["gate"] = gate
        for r in gate["regressions"]:
            if r.get("unit") == "invariant":
                # SKEW GATE entries are absolute: `baseline` carries
                # the violated invariant, not a prior round's value
                progress(f"REGRESSION {r['name']}: {r['value']} — "
                         f"{r['baseline']}")
                continue
            pct = f"{r['change']:+.0%}" if r.get("change") is not None \
                else "new-copies"
            progress(f"REGRESSION {r['name']}: {r['value']} vs "
                     f"r{r['baseline_round']} baseline {r['baseline']} "
                     f"({pct})")
        if gate["regressions"] and args.gate == "fail":
            rc = max(rc, 2)

    result["perf"] = bench_perf_counters().dump()
    # histogram metric lines: the same perf-histogram surface the admin
    # socket's `perf histogram dump` serves, scoped to this bench run
    from ..trace import g_devprof, g_oplat, g_perf_histograms
    result["perf_histograms"] = g_perf_histograms.dump("bench")
    # the run's stage-latency ledger (same shape as `latency dump`):
    # where the microseconds went, per daemon per stage — the
    # run-level companion of every workload's stage_breakdown block
    result["oplat"] = g_oplat.dump()
    # the run's device-flow ledger (same shape as `prof dump`): which
    # call-sites moved how many bytes across the host<->device boundary
    prof = g_devprof.dump()
    result["devprof"] = {"sites": prof["sites"],
                         "totals": prof["totals"]}
    result["elapsed_s"] = round(time.monotonic() - t0, 1)
    sys.stdout.write(json.dumps(result) + "\n")
    sys.stdout.flush()
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
