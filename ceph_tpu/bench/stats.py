"""Measurement statistics: warmup discard, repeats, robust summaries.

A single timed loop gives a point value whose error bars are unknown —
and over a shared tunnel the run-to-run spread IS the story (round 2's
captures ranged 515-816 GiB/s).  Every published metric therefore
carries median/IQR/min/max over N post-warmup repeats next to the point
value, in the versioned schema (schema.py).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence


def _percentile(sorted_xs: Sequence[float], q: float) -> float:
    """Linear-interpolated percentile of an ascending sequence."""
    n = len(sorted_xs)
    if n == 1:
        return float(sorted_xs[0])
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return float(sorted_xs[lo] * (1 - frac) + sorted_xs[hi] * frac)


def summarize(samples: Sequence[float]) -> Dict[str, Any]:
    """{n, median, iqr, min, max} of the samples (no warmup handling —
    the caller discards warmup before summarizing)."""
    if not samples:
        raise ValueError("summarize() needs at least one sample")
    xs = sorted(float(x) for x in samples)
    return {
        "n": len(xs),
        "median": _percentile(xs, 0.5),
        "iqr": _percentile(xs, 0.75) - _percentile(xs, 0.25),
        "min": xs[0],
        "max": xs[-1],
    }


def repeat_measure(fn: Callable[[], float], repeats: int = 5,
                   warmup: int = 1) -> Dict[str, Any]:
    """Run ``fn`` warmup+repeats times, discard the warmup samples, and
    return ``summarize`` of the rest plus the raw samples.

    ``fn`` returns one sample (e.g. one FencedTiming's throughput).
    Warmup runs absorb compile + cache-population cost; they are timed
    but excluded from the summary and reported under "warmup_samples"
    so a pathological first run is still visible.
    """
    if repeats < 1:
        raise ValueError("repeats must be >= 1")
    warm: List[float] = [float(fn()) for _ in range(max(warmup, 0))]
    xs: List[float] = [float(fn()) for _ in range(repeats)]
    out = summarize(xs)
    out["samples"] = xs
    if warm:
        out["warmup_samples"] = warm
    return out
