"""Performance measurement subsystem — completion-fenced timing,
roofline validation, statistics, and regression gating.

Round 5's verdict found the headline TPU encode numbers were dispatch-
rate upper bounds, not measurements: the timing loop never round-tripped
the tunnel per batch of steps, and the 807 GiB/s reading implied ~444
int8 TOPS — above a v5e chip's ~394 TOPS physical peak.  This package
owns every timed number the repo publishes so that cannot recur:

- ``fence``     — timers that refuse to stop until outputs materialize
                  on the host (drain-by-fetch through the transport),
                  with the transport round-trip measured separately and
                  *reported*, never silently subtracted.
- ``roofline``  — a small chip-physics model (int8 TOPS / HBM GiB/s per
                  known backend) that computes the implied op rate of
                  each reading and stamps ``suspect: true`` on anything
                  exceeding peak, so a bogus number can never again
                  become a headline.
- ``stats``     — warmup discard, N repeats, median/IQR/min alongside
                  the point value.
- ``schema``    — the versioned metric record everything above feeds;
                  validation rejects malformed or impossible fields
                  (e.g. a device time of exactly 0.0).
- ``regress``   — comparator over the ``BENCH_r*.json`` trajectory that
                  warns or fails when a fenced metric regresses beyond
                  tolerance.
- ``workloads`` — the EC encode/decode and CRUSH remap measurement
                  bodies, emitting per-kernel timings through
                  ``common.kernel_trace`` and per-run counters through
                  ``common.perf_counters``.

``python -m ceph_tpu.bench --smoke`` runs the whole harness on CPU in
seconds — the harness itself is regression-tested every PR.  The
repo-root ``bench.py`` survivability driver (budget pacing, signal
watchers, tunnel probing) is a thin shell over these modules.
"""
from .fence import (FencedTiming, drain, fenced_time, measure_rtt)
from .roofline import (chip_spec, validate_reading, EC_ENCODE_K8M4,
                       EC_DECODE_K8M4)
from .schema import (SCHEMA_VERSION, make_metric, validate_metric,
                     SchemaError)
from .stats import summarize, repeat_measure
from .regress import load_trajectory, compare_against_trajectory

__all__ = [
    "FencedTiming", "drain", "fenced_time", "measure_rtt",
    "chip_spec", "validate_reading", "EC_ENCODE_K8M4", "EC_DECODE_K8M4",
    "SCHEMA_VERSION", "make_metric", "validate_metric", "SchemaError",
    "summarize", "repeat_measure",
    "load_trajectory", "compare_against_trajectory",
]
