"""Chip-physics sanity model: no reading above peak survives unflagged.

Round 5's 807 GiB/s encode capture implied ~444 int8 TOPS on a chip
whose absolute peak is ~394 — the number was impossible, and nothing in
the pipeline noticed.  This module is that missing check: every
throughput reading is converted to the op and byte rates it implies,
compared against the backend's physical ceilings, and stamped
``suspect: true`` when it exceeds either.  A suspect reading still gets
reported (the raw data is evidence of a broken fence), but the schema
carries the verdict so it can never silently become a headline.

Peaks are per single chip, from public TPU spec sheets; the CPU entry
is a deliberately generous bound so only transport-cache artifacts trip
it, not honest readings on a fast host.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

# (int8_tops, hbm_gibs) per backend, single chip/core.  device_kind
# substrings are matched case-insensitively; first hit wins.
CHIP_SPECS = {
    # TPU generations (public peak specs; int8 where published, else
    # 2x the bf16 figure which is the MXU's int8 ratio)
    "v5e": {"int8_tops": 394.0, "hbm_gibs": 760.0},
    "v5 lite": {"int8_tops": 394.0, "hbm_gibs": 760.0},
    "v5p": {"int8_tops": 918.0, "hbm_gibs": 2587.0},
    "v4": {"int8_tops": 275.0, "hbm_gibs": 1130.0},
    "v6e": {"int8_tops": 1836.0, "hbm_gibs": 1530.0},
    "v6": {"int8_tops": 1836.0, "hbm_gibs": 1530.0},
    "v3": {"int8_tops": 123.0, "hbm_gibs": 855.0},
    # Generous host ceiling: ~2 int8 TOPS covers any AVX-512 box this
    # runs on; memory bound matches big dual-socket DDR5.
    "cpu": {"int8_tops": 2.0, "hbm_gibs": 600.0},
}

# Workload cost models: device int8 ops and HBM bytes per byte of
# OBJECT data (the unit the GiB/s metrics are denominated in).
#
# EC encode k=8,m=4 as the MXU bit-matmul: each C-element contracts
# (k*8) bit lanes against (m*8) output lanes = 64*32 MACs over k=8 data
# bytes -> 2*64*32/8 = 512 int8 ops per data byte.  HBM traffic per
# data byte: read 1 (data), write m/k (parity), plus the 8x-unpacked
# bit planes if XLA fails to fuse them — use the fused lower bound for
# the roofline (suspect flags on the compute axis are what matter).
EC_ENCODE_K8M4 = {
    "name": "ec_encode_k8m4",
    "ops_per_byte": 512.0,
    "hbm_bytes_per_byte": 1.0 + 4.0 / 8.0,
}
# Decode with e erasures runs the identical contraction shape (the
# inverted matrix has k columns; output rows differ but the dominant
# cost is the same bits @ B) — reconstructing e rows from k survivors
# is 2*(k*8)*(e*8)/8 ops per survivor byte; e=2 -> 256.
EC_DECODE_K8M4 = {
    "name": "ec_decode_k8m4_e2",
    "ops_per_byte": 256.0,
    "hbm_bytes_per_byte": 1.0 + 2.0 / 8.0,
}


def chip_spec(platform: str, device_kind: str = "") -> Optional[Dict[str, float]]:
    """Resolve (platform, device_kind) to physical peaks, or None when
    the backend is unknown (verdict becomes "unknown", never "ok")."""
    kind = (device_kind or "").lower()
    for key, spec in CHIP_SPECS.items():
        if key != "cpu" and key in kind:
            return dict(spec)
    if platform == "cpu":
        return dict(CHIP_SPECS["cpu"])
    if platform == "tpu" and not kind:
        # unknown TPU generation: use the most permissive known peak so
        # only physically impossible-anywhere numbers trip the flag
        return dict(CHIP_SPECS["v6e"])
    return None


def validate_reading(gibs: float, workload: Dict[str, Any],
                     platform: str, device_kind: str = "",
                     n_devices: int = 1) -> Dict[str, Any]:
    """Roofline verdict for a throughput reading.

    Returns ``{implied_tops, implied_hbm_gibs, peak_tops, peak_hbm_gibs,
    mfu, suspect, verdict}``.  ``suspect`` is True when the implied op
    or byte rate exceeds the chip's peak (scaled by ``n_devices``) —
    meaning the "measurement" cannot have been a measurement.
    """
    implied_tops = gibs * (1 << 30) * workload["ops_per_byte"] / 1e12
    implied_hbm = gibs * workload["hbm_bytes_per_byte"]
    out: Dict[str, Any] = {
        "workload": workload["name"],
        "implied_tops": round(implied_tops, 2),
        "implied_hbm_gibs": round(implied_hbm, 2),
    }
    spec = chip_spec(platform, device_kind)
    if spec is None:
        out.update(peak_tops=None, peak_hbm_gibs=None, mfu=None,
                   suspect=False, verdict="unknown")
        return out
    peak_tops = spec["int8_tops"] * max(n_devices, 1)
    peak_hbm = spec["hbm_gibs"] * max(n_devices, 1)
    mfu = implied_tops / peak_tops
    suspect = implied_tops > peak_tops or implied_hbm > peak_hbm
    out.update(peak_tops=peak_tops, peak_hbm_gibs=peak_hbm,
               mfu=round(mfu, 4), suspect=bool(suspect),
               verdict="suspect" if suspect else "ok")
    return out
