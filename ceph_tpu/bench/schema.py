"""Versioned metric record — the one shape every published number takes.

A metric dict carries: what was measured (name/unit/value), how (fenced
flag, RTT, statistics), and whether physics believes it (roofline
verdict).  ``validate_metric`` enforces the contract, including the
round-5 lesson that a device-time field of exactly 0.0 means "didn't
run", never "fast" (VERDICT Weak #3: a 100k-PG resolve published as
0.0 us because a fallback guard failed silently).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

SCHEMA_VERSION = 1

_REQUIRED = ("schema_version", "name", "value", "unit", "fenced")


class SchemaError(ValueError):
    """A metric record violates the schema contract."""


def make_metric(name: str, value: float, unit: str, *,
                fenced: bool,
                rtt_s: Optional[float] = None,
                stats: Optional[Dict[str, Any]] = None,
                roofline: Optional[Dict[str, Any]] = None,
                extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Assemble and validate one metric record."""
    m: Dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "name": name,
        "value": round(float(value), 4),
        "unit": unit,
        "fenced": bool(fenced),
    }
    if rtt_s is not None:
        m["rtt_ms"] = round(float(rtt_s) * 1e3, 3)
    if stats is not None:
        m["stats"] = {k: (round(v, 4) if isinstance(v, float) else v)
                      for k, v in stats.items()
                      if k not in ("samples", "warmup_samples")}
        for k in ("samples", "warmup_samples"):
            if k in stats:
                m["stats"][k] = [round(float(x), 4) for x in stats[k]]
    if roofline is not None:
        m["roofline"] = dict(roofline)
        m["suspect"] = bool(roofline.get("suspect", False))
    if extra:
        for k, v in extra.items():
            if k in m:
                raise SchemaError(f"extra field {k!r} collides with "
                                  "a schema field")
            m[k] = v
    validate_metric(m)
    return m


def validate_metric(m: Dict[str, Any]) -> None:
    """Raise SchemaError unless *m* is a well-formed metric record."""
    for k in _REQUIRED:
        if k not in m:
            raise SchemaError(f"metric missing required field {k!r}")
    if m["schema_version"] != SCHEMA_VERSION:
        raise SchemaError(f"unknown schema_version {m['schema_version']!r}")
    if not isinstance(m["name"], str) or not m["name"]:
        raise SchemaError("metric name must be a non-empty string")
    if not isinstance(m["fenced"], bool):
        raise SchemaError("fenced must be a bool")
    v = m["value"]
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        raise SchemaError(f"value must be numeric, got {type(v).__name__}")
    if v < 0:
        raise SchemaError("value must be non-negative")
    # "fast" and "didn't run" must be distinguishable: an exact 0.0 in
    # a timing/throughput metric is always the latter (Weak #3).
    if v == 0.0 and m["unit"] in ("GiB/s", "ms", "us", "s"):
        raise SchemaError(
            f"metric {m['name']!r} is exactly 0.0 {m['unit']} — a zero "
            "reading means the measurement did not run; refuse to "
            "publish it as a number")
    st = m.get("stats")
    if st is not None:
        for k in ("n", "median", "iqr", "min"):
            if k not in st:
                raise SchemaError(f"stats missing {k!r}")
        if st["n"] < 1:
            raise SchemaError("stats.n must be >= 1")
        if st["min"] > st["median"]:
            raise SchemaError("stats.min exceeds stats.median")
    rl = m.get("roofline")
    if rl is not None:
        if "verdict" not in rl or rl["verdict"] not in (
                "ok", "suspect", "unknown"):
            raise SchemaError("roofline.verdict must be ok|suspect|unknown")
        if "suspect" not in m or m["suspect"] != (rl["verdict"] == "suspect"):
            raise SchemaError("top-level suspect must mirror the "
                              "roofline verdict")
