"""Measurement bodies: EC encode/decode, native host baseline, CRUSH
remap — all through the fenced harness.

Everything here returns schema metrics (schema.py) built from fenced
timings (fence.py), summarized over repeats (stats.py), and stamped
with a roofline verdict (roofline.py).  Per-kernel wall timings flow
through ``common.kernel_trace.g_kernel_timer`` (same registry the admin
socket dumps) and per-run dispatch/byte counters through a
``common.perf_counters`` logger, so the bench shares one observability
surface with the daemons instead of growing its own.

The salted-input trick (no layer can serve a repeat dispatch from
cache) and the fetch-drain fence are both load-bearing: without the
salt, identical-input repeats measured 3-10x above the chip's compute
floor; without the drain, dispatch acknowledgements were mistaken for
completions (round 5's physically impossible 807 GiB/s).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .fence import fenced_time, measure_rtt
from .roofline import EC_DECODE_K8M4, EC_ENCODE_K8M4, validate_reading
from .schema import make_metric
from .stats import repeat_measure
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..trace.devprof import devflow_delta, g_devprof
from ..trace.oplat import g_oplat

K, M = 8, 4

# ---- perf counters ---------------------------------------------------------
BENCH_FIRST = 90000
l_bench_dispatches = 90001     # device dispatches issued by the harness
l_bench_bytes = 90002          # object bytes pushed through timed regions
l_bench_fences = 90003         # drain fences executed
l_bench_fence_time = 90004     # seconds spent inside fenced regions
BENCH_LAST = 90010

_bench_pc: Optional[PerfCounters] = None


def bench_perf_counters() -> PerfCounters:
    """The bench subsystem's counter logger (admin-socket dumpable)."""
    global _bench_pc
    if _bench_pc is None:
        b = PerfCountersBuilder("bench", BENCH_FIRST, BENCH_LAST)
        b.add_u64_counter(l_bench_dispatches, "dispatches",
                          "device dispatches issued")
        b.add_u64_counter(l_bench_bytes, "bytes",
                          "object bytes through timed regions")
        b.add_u64_counter(l_bench_fences, "fences",
                          "completion fences executed")
        b.add_time_avg(l_bench_fence_time, "fenced_region",
                       "time inside fenced regions")
        _bench_pc = b.create_perf_counters()
    return _bench_pc


# ---- shared jitted step ----------------------------------------------------
_STEP = None

# Process-global monotonic salt: a RETRIED or repeated measurement must
# never replay an input the transport has already seen (a per-call
# counter reset would re-dispatch identical (payload ^ salt) values on
# bench.py's section retry, and a caching layer serving the repeats
# inflates the reading 3-10x — the artifact the salt exists to prevent).
_SALT = [0]


def _next_salt() -> int:
    _SALT[0] += 1
    return _SALT[0] & 0xFFFFFFFF


def salted_matmul_step():
    """One shared jitted (payload ^ salt) @ bits step.

    Salting with a never-repeating per-iteration scalar means no layer
    (XLA or a tunnelled PJRT shim) can serve a repeat dispatch from
    cache: every iteration is a genuinely new execution.  The full
    32-bit salt is xored across u32 lanes so the input never repeats
    within a run — a uint8 salt would cycle every 256 iters.
    """
    global _STEP
    if _STEP is not None:
        return _STEP
    import jax
    import jax.numpy as jnp
    from ..ops.gf_matmul import gf_bit_matmul

    @jax.jit  # lint: allow[jit-cache-hygiene] — memoized in _STEP
    def step(d, b, salt):
        s_, k_, c_ = d.shape
        d32 = jax.lax.bitcast_convert_type(
            d.reshape(s_, k_, c_ // 4, 4), jnp.uint32)
        d8 = jax.lax.bitcast_convert_type(
            d32 ^ salt, jnp.uint8).reshape(s_, k_, c_)
        return gf_bit_matmul(d8, b)

    _STEP = step
    return step


def _calibrate_steps(step: Callable[[int], Any], target_s: float,
                     rtt_s: float, lo: int = 4, hi: int = 8192,
                     drain_fn=None) -> int:
    """Pick how many back-to-back dispatches one fenced region needs so
    compute dominates the single drain RTT and the region lands near
    ``target_s``.

    The region is stretched to at least 10x the RTT so the fence costs
    <~10% of the reading even on a ~100 ms tunnel (256 dispatches of a
    sub-ms kernel would otherwise be RTT-dominated and understate the
    fenced throughput several-fold).  ``hi`` only bounds the dispatch
    queue depth — outputs are not retained (fence.fenced_time), so
    memory does not grow with n."""
    probe = fenced_time(step, lo, rtt_s=rtt_s, drain_fn=drain_fn)
    per_step = max((probe.elapsed_s - rtt_s) / lo, 1e-6)
    n = int(max(target_s, 10.0 * rtt_s) / per_step)
    return max(lo, min(n, hi))


def _fenced_throughput(step: Callable[[int], Any], n_steps: int,
                       bytes_per_step: int, rtt_s: float,
                       kernel_name: str,
                       drain_fn=None) -> Tuple[float, Dict[str, Any]]:
    """One fenced sample: GiB/s plus the raw timing dict."""
    timing = fenced_time(step, n_steps, rtt_s=rtt_s,
                         kernel_name=kernel_name, drain_fn=drain_fn)
    pc = bench_perf_counters()
    pc.inc(l_bench_dispatches, n_steps)
    pc.inc(l_bench_bytes, n_steps * bytes_per_step)
    pc.inc(l_bench_fences)
    pc.tinc(l_bench_fence_time, timing.elapsed_s)
    return timing.throughput(bytes_per_step), timing.to_dict()


def _devflow_since(before: Dict[str, int], n_ops: int) -> Dict[str, Any]:
    """The ``devflow`` block every fenced workload carries: device-flow
    deltas over the measured region, normalized per op.
    ``copies_per_op`` / ``bytes_per_op`` are GATED metrics
    (regress.py's copy-budget gate), so a zero-copy refactor must move
    a number CI watches — and a copy regression fails the gate like a
    latency regression."""
    return devflow_delta(before, g_devprof.snapshot(), n_ops)


def _stage_breakdown_since(before, wall_s: float,
                           n_ops: int) -> Dict[str, Any]:
    """The ``stage_breakdown`` block every fenced workload carries
    (trace/oplat.py): per-stage time over the measured region —
    share-of-stage-sum, per-op time, p50/p99 — with ``coverage``
    (stage-sum over wall) as the reconciliation receipt: ~1.0 for a
    serial region, ~occupancy under coalescing (per-op attribution of
    a shared device call — the occupancy story in time units).  The
    ``usec_per_op`` figures are gated by regress.py's stage-budget
    gate, so the mesh/zero-copy refactors must move a stage number CI
    watches."""
    return g_oplat.breakdown_since(before, wall_s, n_ops)


def _device_info() -> Tuple[str, str, int]:
    try:
        import jax
        d = jax.devices()[0]
        return d.platform, getattr(d, "device_kind", ""), 1
    except Exception:
        return "unknown", "", 1


def _measure_fenced_gf(bits, batch: np.ndarray, *, metric_name: str,
                       workload: Dict[str, Any], kernel_name: str,
                       target_seconds: float, repeats: int, warmup: int,
                       rtt_s: Optional[float],
                       mesh=None,
                       n_steps: Optional[int] = None) -> Dict[str, Any]:
    """Shared fenced pipeline for the GF bit-matmul workloads: warm the
    jitted step, calibrate the per-region dispatch count, take
    warmup+repeat fenced samples, and wrap the median in a schema
    metric with a roofline verdict.  Encode and decode differ only in
    the bitmatrix and the cost model.

    With *mesh* the same step runs SPMD: the batch rows are placed
    ``NamedSharding(mesh, PartitionSpec("batch"))``, the bit-matrix
    replicated, the fence is ``drain_sharded`` (one readback per shard
    — each chip's completion proven, not inferred) and the roofline
    verdict scales the chip peak by the mesh size (``mesh_roofline``).
    """
    import jax
    import jax.numpy as jnp

    drain_fn = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..mesh.topology import BATCH_AXIS
        from ..parallel.ec import drain_sharded
        dev = jax.device_put(jnp.asarray(batch),
                             NamedSharding(mesh, P(BATCH_AXIS, None,
                                                   None)))
        bits = jax.device_put(bits, NamedSharding(mesh, P(None, None)))
        drain_fn = drain_sharded
    else:
        dev = jax.device_put(jnp.asarray(batch))
    jitted = salted_matmul_step()
    warm = jitted(dev, bits, jnp.uint32(0))
    jax.block_until_ready(warm)                              # compile
    if drain_fn is not None:
        drain_fn(warm)       # warm the fence's own tiny programs too

    def step(i: int):
        return jitted(dev, bits, jnp.uint32(_next_salt()))

    if rtt_s is None:
        rtt_s = measure_rtt()
    bytes_per_step = int(batch.shape[0]) * int(batch.shape[1]) \
        * int(batch.shape[2])
    if n_steps is None:
        n_steps = _calibrate_steps(step,
                                   target_seconds / max(repeats, 1),
                                   rtt_s, drain_fn=drain_fn)
    flow0 = g_devprof.snapshot()
    stage0 = g_oplat.snapshot()
    wall_t0 = time.perf_counter()
    st = repeat_measure(
        lambda: _fenced_throughput(step, n_steps, bytes_per_step, rtt_s,
                                   kernel_name, drain_fn=drain_fn)[0],
        repeats=repeats, warmup=warmup)
    wall_s = time.perf_counter() - wall_t0
    n_ops = n_steps * (repeats + warmup)
    devflow = _devflow_since(flow0, n_ops)
    platform, kind, ndev = _device_info()
    if mesh is not None:
        from ..parallel.ec import mesh_roofline
        rl = mesh_roofline(st["median"], workload, mesh)
        ndev = mesh.size
    else:
        rl = validate_reading(st["median"], workload, platform, kind,
                              ndev)
    return make_metric(
        metric_name, st["median"], "GiB/s", fenced=True,
        rtt_s=rtt_s, stats=st, roofline=rl,
        extra={"n_steps": n_steps, "bytes_per_step": bytes_per_step,
               "platform": platform, "n_devices": ndev,
               "devflow": devflow,
               "stage_breakdown": _stage_breakdown_since(
                   stage0, wall_s, n_ops)})


def measure_encode(matrix: np.ndarray, batch: np.ndarray, *,
                   target_seconds: float = 3.0, repeats: int = 3,
                   warmup: int = 1, rtt_s: Optional[float] = None
                   ) -> Dict[str, Any]:
    """Fenced EC encode throughput metric for a (S, k, C) batch."""
    import jax.numpy as jnp
    from ..gf.tables import expand_to_bitmatrix

    bits = jnp.asarray(expand_to_bitmatrix(matrix[K:]).astype(np.int8))
    return _measure_fenced_gf(
        bits, batch, metric_name="ec_encode_k8m4_fenced",
        workload=EC_ENCODE_K8M4, kernel_name="bench_encode_fenced",
        target_seconds=target_seconds, repeats=repeats,
        warmup=warmup, rtt_s=rtt_s)


def measure_decode(matrix: np.ndarray, batch: np.ndarray, *,
                   erasures: int = 2, target_seconds: float = 3.0,
                   repeats: int = 3, warmup: int = 1,
                   rtt_s: Optional[float] = None) -> Dict[str, Any]:
    """Fenced decode-with-erasures throughput metric.

    The survivor payload is random: the GF matmul's timing is
    data-independent, and correctness on REAL coded data is proved by
    ``parity_check`` (which fetches, so it runs last in any driver).
    """
    from ..ops.gf_matmul import DeviceRSBackend

    be = DeviceRSBackend(matrix)
    lost = tuple(range(erasures))
    srcs = tuple(range(erasures, K)) + tuple(K + i for i in range(erasures))
    bits = be._decode_bits_for(srcs, lost)
    return _measure_fenced_gf(
        bits, batch, metric_name="ec_decode_k8m4_e2_fenced",
        workload=EC_DECODE_K8M4, kernel_name="bench_decode_fenced",
        target_seconds=target_seconds, repeats=repeats, warmup=warmup,
        rtt_s=rtt_s)


def measure_host_native(matrix: np.ndarray, data2d: np.ndarray,
                        target_seconds: float = 1.5
                        ) -> Optional[Dict[str, Any]]:
    """GiB/s of the native C++ region coder on one (k, C) object, or
    None when the native library is absent.  Host execution completes
    synchronously, so the reading is fenced by construction."""
    from ..native import native_rs_encode, native_available
    if not native_available():
        return None
    rows = matrix[K:]
    object_size = int(data2d.shape[0]) * int(data2d.shape[1])
    native_rs_encode(rows, data2d)  # warm tables

    def one_sample() -> float:
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < target_seconds / 3:
            native_rs_encode(rows, data2d)
            n += 1
        dt = time.perf_counter() - t0
        one_sample.n_ops += n
        # the whole region is host codec compute: one stage, so the
        # native baseline's stage_breakdown reconciles trivially
        g_oplat.record("bench", "host_compute", dt * 1e6)
        return n * object_size / dt / (1 << 30)

    one_sample.n_ops = 0
    flow0 = g_devprof.snapshot()
    stage0 = g_oplat.snapshot()
    wall_t0 = time.perf_counter()
    st = repeat_measure(one_sample, repeats=3, warmup=0)
    wall_s = time.perf_counter() - wall_t0
    # the native path never crosses the device boundary — its devflow
    # block is the zero-copy baseline the device paths are judged by
    devflow = _devflow_since(flow0, max(one_sample.n_ops, 1))
    rl = validate_reading(st["median"], EC_ENCODE_K8M4, "cpu", "", 1)
    return make_metric("ec_encode_host_native", st["median"], "GiB/s",
                       fenced=True, rtt_s=0.0, stats=st, roofline=rl,
                       extra={"platform": "cpu", "devflow": devflow,
                              "stage_breakdown": _stage_breakdown_since(
                                  stage0, wall_s,
                                  max(one_sample.n_ops, 1))})


def measure_dispatch_coalesce(*, n_requests: int = 8,
                              object_bytes: int = 65536,
                              target_seconds: float = 0.6,
                              repeats: int = 3, warmup: int = 1,
                              rtt_s: Optional[float] = None
                              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """N concurrent 64 KiB k=8,m=4 encodes through the dispatch
    scheduler: coalesced (one padded device call per flush, batch_max
    trigger) vs serial dispatch (window=0 exact passthrough, one device
    call per request).

    Fencing: both paths return fully host-materialized chunk buffers —
    the device output is fetched before the clock stops, which is the
    drain contract (fence.py) by construction; the measured region
    therefore includes one transport round trip per device call, which
    is exactly the per-call overhead the coalesced path amortizes.  The
    RTT is measured and reported, never subtracted.  Inputs are salted
    per pass so no layer can serve a repeat from cache.
    """
    from ..common.config import g_conf
    from ..dispatch import g_dispatcher
    from ..ec.tpu_plugin import ErasureCodeTpu
    from ..osd.ecutil import stripe_info_t

    impl = ErasureCodeTpu()
    impl.init({"k": str(K), "m": str(M), "technique": "reed_sol_van"})
    assert object_bytes % K == 0
    sinfo = stripe_info_t(K, object_bytes)
    want = set(range(K + M))
    rng = np.random.default_rng(20260803)
    base = rng.integers(0, 256, size=(n_requests, object_bytes),
                        dtype=np.uint8)
    if rtt_s is None:
        rtt_s = measure_rtt()
    saved = {name: g_conf.values.get(name) for name in
             ("ec_dispatch_batch_max", "ec_dispatch_batch_window_us")}
    pc = bench_perf_counters()

    def one_pass(coalesced: bool) -> None:
        payloads = np.bitwise_xor(base, np.uint8(_next_salt() & 0xFF))
        if coalesced:
            futs = [g_dispatcher.submit_encode(sinfo, impl, payloads[i],
                                               want)
                    for i in range(n_requests)]
            for f in futs:
                f.result()
        else:
            for i in range(n_requests):
                g_dispatcher.encode(sinfo, impl, payloads[i], want)
        pc.inc(l_bench_dispatches, 1 if coalesced else n_requests)
        pc.inc(l_bench_bytes, n_requests * object_bytes)

    def make_sampler(coalesced: bool, rounds: int):
        def sample() -> float:
            if coalesced:
                g_conf.set_val("ec_dispatch_batch_max", n_requests)
                g_conf.set_val("ec_dispatch_batch_window_us", 10**7)
            else:
                g_conf.set_val("ec_dispatch_batch_window_us", 0)
            t0 = time.perf_counter()
            for _ in range(rounds):
                one_pass(coalesced)
            dt = time.perf_counter() - t0
            pc.tinc(l_bench_fence_time, dt)
            return rounds * n_requests * object_bytes / dt / (1 << 30)

        return sample

    try:
        results = {}
        flows = {}
        breakdowns = {}
        for mode in ("serial", "coalesced"):
            coalesced = mode == "coalesced"
            # warm compiles, then calibrate rounds per sample so the
            # region dwarfs a single fence round trip
            make_sampler(coalesced, 1)()
            t0 = time.perf_counter()
            make_sampler(coalesced, 1)()
            per_pass = max(time.perf_counter() - t0, 1e-6)
            rounds = max(1, min(
                int(max(target_seconds / max(repeats, 1),
                        4.0 * rtt_s) / per_pass), 256))
            flow0 = g_devprof.snapshot()
            stage0 = g_oplat.snapshot()
            wall_t0 = time.perf_counter()
            results[mode] = repeat_measure(
                make_sampler(coalesced, rounds),
                repeats=repeats, warmup=warmup)
            wall_s = time.perf_counter() - wall_t0
            n_ops = rounds * n_requests * (repeats + warmup)
            flows[mode] = _devflow_since(flow0, n_ops)
            breakdowns[mode] = _stage_breakdown_since(stage0, wall_s,
                                                      n_ops)
    finally:
        for name, v in saved.items():
            g_conf.rm_val(name) if v is None else g_conf.set_val(name, v)
        g_dispatcher.flush()
    platform, kind, ndev = _device_info()
    mets = []
    for mode, name in (("coalesced", "ec_dispatch_coalesce_fenced"),
                       ("serial", "ec_dispatch_serial_fenced")):
        st = results[mode]
        rl = validate_reading(st["median"], EC_ENCODE_K8M4, platform,
                              kind, ndev)
        extra = {"n_requests": n_requests, "object_bytes": object_bytes,
                 "platform": platform, "devflow": flows[mode],
                 "stage_breakdown": breakdowns[mode]}
        if mode == "coalesced":
            extra["serial_gibs"] = round(results["serial"]["median"], 4)
            extra["speedup"] = round(
                st["median"] / max(results["serial"]["median"], 1e-9), 3)
            extra["batch_occupancy"] = n_requests
        mets.append(make_metric(name, st["median"], "GiB/s", fenced=True,
                                rtt_s=rtt_s, stats=st, roofline=rl,
                                extra=extra))
    return mets[0], mets[1]


def measure_ec_pipeline(*, n_requests: int = 64,
                        object_bytes: int = 65536, depth: int = 8,
                        target_seconds: float = 0.6,
                        repeats: int = 3, warmup: int = 1,
                        rtt_s: Optional[float] = None
                        ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """N sequential 64 KiB k=8,m=4 encodes from ONE submitter thread:
    pipeline depth 8 (non-blocking dispatch futures with continuation
    completion, window drained by a forced flush at the depth boundary
    — the ec_backend backpressure rule) vs depth 1 (the synchronous
    submit → result() per op the write path used before the async
    pipeline).  The depth-1 leg models exactly why a lone OSD op
    thread never filled a batch: each op demands its result inline, so
    every encode pays a full dispatch.

    Fencing: completion is a continuation observing the fully
    host-materialized chunk buffers, so the clock stops only after the
    device output crossed back to the host (the drain contract, as in
    measure_dispatch_coalesce); the RTT is measured and reported,
    never subtracted.  Inputs are salted per pass.  The occupancy the
    pipeline actually achieved is read back from the dispatcher's
    batch-occupancy histogram and reported as
    ``mean_batch_occupancy``; byte-identity of the pipelined outputs
    against the depth-1 path is checked every run (``identical``).
    """
    from ..common.config import g_conf
    from ..dispatch import g_dispatcher
    from ..ec.tpu_plugin import ErasureCodeTpu
    from ..osd.ecutil import stripe_info_t
    from ..trace import g_perf_histograms, occupancy_axes

    impl = ErasureCodeTpu()
    impl.init({"k": str(K), "m": str(M), "technique": "reed_sol_van"})
    assert object_bytes % K == 0
    sinfo = stripe_info_t(K, object_bytes)
    want = set(range(K + M))
    rng = np.random.default_rng(20260804)
    base = rng.integers(0, 256, size=(n_requests, object_bytes),
                        dtype=np.uint8)
    if rtt_s is None:
        rtt_s = measure_rtt()
    saved = {name: g_conf.values.get(name) for name in
             ("ec_dispatch_batch_max", "ec_dispatch_batch_window_us")}
    pc = bench_perf_counters()
    occ_hist = g_perf_histograms.get(
        "dispatch", "dispatch_batch_occupancy_histogram",
        occupancy_axes)

    def one_pass(d: int, collect: Optional[list] = None) -> None:
        payloads = np.bitwise_xor(base, np.uint8(_next_salt() & 0xFF))
        if d <= 1:
            for i in range(n_requests):
                out = g_dispatcher.encode(sinfo, impl, payloads[i],
                                          want)
                if collect is not None:
                    collect.append(out)
            pc.inc(l_bench_dispatches, n_requests)
        else:
            done = [None] * n_requests
            inflight = [0]
            for i in range(n_requests):
                if inflight[0] >= d:
                    # the per-PG window is full: backpressure drains it
                    # by executing the batch inline (never by waiting)
                    g_dispatcher.flush()
                fut = g_dispatcher.submit_encode(sinfo, impl,
                                                 payloads[i], want)
                inflight[0] += 1

                def on_ready(f, i=i):
                    inflight[0] -= 1
                    done[i] = f.result()    # resolved: host buffers

                fut.add_done_callback(on_ready)
            g_dispatcher.flush()            # completion fence
            assert all(r is not None for r in done)
            if collect is not None:
                collect.extend(done)
            pc.inc(l_bench_dispatches, (n_requests + d - 1) // d)
        pc.inc(l_bench_bytes, n_requests * object_bytes)

    def make_sampler(d: int, rounds: int):
        def sample() -> float:
            g_conf.set_val("ec_dispatch_batch_max", max(d, 1))
            g_conf.set_val("ec_dispatch_batch_window_us", 10**7)
            t0 = time.perf_counter()
            for _ in range(rounds):
                one_pass(d)
            dt = time.perf_counter() - t0
            pc.tinc(l_bench_fence_time, dt)
            return rounds * n_requests * object_bytes / dt / (1 << 30)

        return sample

    try:
        # byte-identity receipt: the same salted payloads through both
        # depths must produce identical chunk buffers
        salt_before = _SALT[0]
        g_conf.set_val("ec_dispatch_batch_max", depth)
        g_conf.set_val("ec_dispatch_batch_window_us", 10**7)
        piped: list = []
        one_pass(depth, collect=piped)
        _SALT[0] = salt_before          # replay the same inputs
        serial: list = []
        one_pass(1, collect=serial)
        identical = all(
            sorted(a) == sorted(b)
            and all(np.asarray(a[i]).tobytes()
                    == np.asarray(b[i]).tobytes() for i in a)
            for a, b in zip(piped, serial))
        results = {}
        flows = {}
        breakdowns = {}
        occupancy = None
        for d in (1, depth):
            make_sampler(d, 1)()        # warm compiles
            t0 = time.perf_counter()
            make_sampler(d, 1)()
            per_pass = max(time.perf_counter() - t0, 1e-6)
            rounds = max(1, min(
                int(max(target_seconds / max(repeats, 1),
                        4.0 * rtt_s) / per_pass), 256))
            if d == depth:
                occ0 = (occ_hist.axis0_sum, occ_hist.total_count)
            flow0 = g_devprof.snapshot()
            stage0 = g_oplat.snapshot()
            wall_t0 = time.perf_counter()
            results[d] = repeat_measure(make_sampler(d, rounds),
                                        repeats=repeats, warmup=warmup)
            wall_s = time.perf_counter() - wall_t0
            n_ops = rounds * n_requests * (repeats + warmup)
            flows[d] = _devflow_since(flow0, n_ops)
            # the stage story --smoke tells in time units: depth-1's
            # breakdown is device_call-dominated (every op demands its
            # own flush), depth-8 grows a real batch_window share and
            # its coverage approaches the achieved occupancy
            breakdowns[d] = _stage_breakdown_since(stage0, wall_s,
                                                   n_ops)
            if d == depth:
                ds = occ_hist.axis0_sum - occ0[0]
                dn = occ_hist.total_count - occ0[1]
                occupancy = round(ds / dn, 2) if dn else 0.0
    finally:
        for name, v in saved.items():
            g_conf.rm_val(name) if v is None else g_conf.set_val(name, v)
        g_dispatcher.flush()
    platform, kind, ndev = _device_info()
    mets = []
    for d, name in ((depth, "ec_pipeline_fenced"),
                    (1, "ec_pipeline_depth1_fenced")):
        st = results[d]
        rl = validate_reading(st["median"], EC_ENCODE_K8M4, platform,
                              kind, ndev)
        extra = {"n_requests": n_requests, "object_bytes": object_bytes,
                 "pipeline_depth": d, "platform": platform,
                 "devflow": flows[d],
                 "stage_breakdown": breakdowns[d]}
        if d == depth:
            extra["depth1_gibs"] = round(results[1]["median"], 4)
            extra["speedup"] = round(
                st["median"] / max(results[1]["median"], 1e-9), 3)
            extra["mean_batch_occupancy"] = occupancy
            extra["identical"] = bool(identical)
        mets.append(make_metric(name, st["median"], "GiB/s",
                                fenced=True, rtt_s=rtt_s, stats=st,
                                roofline=rl, extra=extra))
    return mets[0], mets[1]


def _mesh_dispatch_receipt(mesh_chips: int, n_requests: int,
                           object_bytes: int) -> Dict[str, Any]:
    """The mesh workload's correctness + occupancy receipt, taken
    through the REAL dispatch path: the same coalesced k8m4 encode
    batch through the scheduler with the mesh on vs the single-device
    twin (mesh off), outputs byte-compared shard by shard, per-chip
    stripe deltas read back from the runtime.  Runs outside the timed
    region — receipts must not pollute the fenced numbers."""
    from ..common.config import g_conf
    from ..dispatch import g_dispatcher
    from ..ec.tpu_plugin import ErasureCodeTpu
    from ..mesh import g_mesh
    from ..osd.ecutil import stripe_info_t

    impl = ErasureCodeTpu()
    impl.init({"k": str(K), "m": str(M), "technique": "reed_sol_van"})
    assert object_bytes % K == 0
    sinfo = stripe_info_t(K, object_bytes)
    want = set(range(K + M))
    rng = np.random.default_rng(20260805)
    payloads = [rng.integers(0, 256, size=object_bytes, dtype=np.uint8)
                for _ in range(n_requests)]
    saved = {name: g_conf.values.get(name) for name in
             ("ec_dispatch_batch_max", "ec_dispatch_batch_window_us",
              "ec_mesh_chips")}

    def run_batch():
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                for p in payloads]
        g_dispatcher.flush()
        return [f.result() for f in futs]

    try:
        g_conf.set_val("ec_dispatch_batch_max", n_requests)
        g_conf.set_val("ec_dispatch_batch_window_us", 10**7)
        g_conf.set_val("ec_mesh_chips", 0)
        single = run_batch()
        g_conf.set_val("ec_mesh_chips", mesh_chips)
        chips0 = {i: v["stripes"] for i, v in g_mesh.per_chip().items()}
        meshed = run_batch()
        per_chip = {i: v["stripes"] - chips0.get(i, 0)
                    for i, v in g_mesh.per_chip().items()}
        identical = all(
            sorted(a) == sorted(b)
            and all(np.asarray(a[i]).tobytes()
                    == np.asarray(b[i]).tobytes() for i in a)
            for a, b in zip(meshed, single))
        dump = g_mesh.dump()
        return {"identical": bool(identical),
                "per_chip_stripes": per_chip,
                "mesh_size": dump["size"],
                "plan_cache": len(dump["plans"]),
                "pool": dump["pool"]}
    finally:
        for name, v in saved.items():
            g_conf.rm_val(name) if v is None else g_conf.set_val(name, v)
        g_dispatcher.flush()


def measure_ec_mesh(matrix: np.ndarray, *, mesh_chips: int = 8,
                    chunk: int = 8192, n_requests: int = 8,
                    object_bytes: int = 65536,
                    target_seconds: float = 0.3, repeats: int = 3,
                    warmup: int = 1, rtt_s: Optional[float] = None,
                    n_steps: Optional[int] = None
                    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """k=8,m=4 encodes across the dispatch mesh vs a single-device
    twin (ceph_tpu/mesh, docs/DISPATCH.md "Mesh-sharded dispatch").

    Two legs of the SAME salted GF bit-matmul step: ``ec_mesh_fenced``
    runs it SPMD over a 1-D batch-axis mesh of *mesh_chips* devices
    (CPU smoke: the 8-device virtual host platform), completion-fenced
    via ``drain_sharded`` — one readback from EVERY shard, because a
    mesh output is only proven complete per device — and validated by
    ``mesh_roofline`` (chip peaks scaled by mesh size);
    ``ec_mesh_single_fenced`` is the identical step on one device
    under the standard drain.  The RTT is measured and reported, never
    subtracted; inputs are salted per dispatch.

    The mesh metric also carries the dispatch-path receipt
    (``_mesh_dispatch_receipt``): byte-identity of a coalesced batch
    through the real scheduler with the mesh on vs off, and the
    per-chip stripe occupancy the flush produced — every chip of the
    smoke mesh must show work.
    """
    import jax.numpy as jnp
    from ..gf.tables import expand_to_bitmatrix
    from ..mesh.topology import batch_mesh

    mesh = batch_mesh(mesh_chips)
    batch_s = 2 * mesh.size
    rng = np.random.default_rng(20260806)
    batch = rng.integers(0, 256, size=(batch_s, K, chunk),
                         dtype=np.uint8)
    bits = jnp.asarray(expand_to_bitmatrix(matrix[K:]).astype(np.int8))
    if rtt_s is None:
        rtt_s = measure_rtt()
    # a PINNED step count (smoke) keeps the twin's fence-flow per-op
    # figures deterministic round over round; None (full mode)
    # calibrates the region like every other fenced workload
    m_single = _measure_fenced_gf(
        bits, batch, metric_name="ec_mesh_single_fenced",
        workload=EC_ENCODE_K8M4, kernel_name="bench_mesh_single_fenced",
        target_seconds=target_seconds, repeats=repeats, warmup=warmup,
        rtt_s=rtt_s, n_steps=n_steps)
    m_mesh = _measure_fenced_gf(
        bits, batch, metric_name="ec_mesh_fenced",
        workload=EC_ENCODE_K8M4, kernel_name="bench_mesh_fenced",
        target_seconds=target_seconds, repeats=repeats, warmup=warmup,
        rtt_s=rtt_s, mesh=mesh, n_steps=n_steps)
    receipt = _mesh_dispatch_receipt(mesh_chips, n_requests,
                                     object_bytes)
    m_mesh["mesh_chips"] = mesh.size
    m_mesh["single_gibs"] = round(m_single["value"], 4)
    m_mesh["speedup"] = round(
        m_mesh["value"] / max(m_single["value"], 1e-9), 3)
    m_mesh.update(receipt)
    return m_mesh, m_single


def measure_mesh_skew(*, mesh_chips: int = 8, slow_chip: int = 5,
                      delay_us: int = 30_000, threshold: float = 3.0,
                      healthy_flushes: int = 4, max_probes: int = 10,
                      n_requests: int = 3, chunk: int = 1024, k: int = 4,
                      m: int = 2, n_stripes: int = 2,
                      name: str = "ec_mesh_skew") -> Dict[str, Any]:
    """The straggler ruler (docs/OBSERVABILITY.md "Per-chip timing &
    skew health"): run the mesh twin healthy vs one-chip-slowed and
    measure what the chip-health scoreboard SEES — skew ratio at
    detection, per-chip p99 spread, and detection latency in probes.

    Shape: a mini cluster (the mgr must tick DURING the run — the
    TPU_MESH_SKEW raise/clear is part of the measurement) with an
    8-chip mesh and ``ec_mesh_skew_sample_every=1``; coalesced k4m2
    encode flushes drive the dispatch path directly.  Leg 1 (healthy):
    N flushes, the scoreboard must stay quiet — zero false suspects is
    a gated assertion, not a hope.  Leg 2 (slowed): the fault registry
    arms ``mesh.chip_slowdown`` on exactly *slow_chip* with a
    *delay_us* stall (~10x the healthy CPU probe delta) and the run
    counts flushes until the scoreboard marks a suspect; the suspect
    must be exactly the slowed chip, TPU_MESH_SKEW must raise while
    the mgr ticks, and after the fault clears the check must clear
    again.  Every flush's output is byte-compared against the
    single-request oracle (skew sampling must never touch the data
    path).  bench/regress.py's SKEW GATE enforces the detection
    window, the exact-chip verdict and the quiet healthy twin.

    CPU-smoke caveat: the 8 virtual devices share host cores, so the
    HEALTHY per-chip spread here is calibration only — the real
    healthy-spread number is a live-TPU capture (ROADMAP backlog 7).
    """
    from ..cluster import MiniCluster
    from ..common.config import g_conf
    from ..dispatch import g_dispatcher
    from ..ec.tpu_plugin import ErasureCodeTpu
    from ..fault import g_faults
    from ..mesh import g_chipstat, g_mesh
    from ..osd.ecutil import encode as eu_encode, stripe_info_t

    saved = {opt: g_conf.values.get(opt) for opt in
             ("ec_mesh_chips", "ec_dispatch_batch_max",
              "ec_dispatch_batch_window_us",
              "ec_mesh_skew_sample_every", "ec_mesh_skew_threshold")}
    g_conf.set_val("ec_mesh_chips", mesh_chips)
    g_conf.set_val("ec_dispatch_batch_max", 64)
    g_conf.set_val("ec_dispatch_batch_window_us", 10**7)
    g_conf.set_val("ec_mesh_skew_sample_every", 1)
    g_conf.set_val("ec_mesh_skew_threshold", threshold)

    cluster = MiniCluster(n_osds=4)
    impl = ErasureCodeTpu()
    impl.init({"k": str(k), "m": str(m), "technique": "reed_sol_van"})
    sinfo = stripe_info_t(k, k * chunk)
    want = set(range(k + m))
    rng = np.random.default_rng(20260804)
    flow0 = g_devprof.snapshot()
    stage0 = g_oplat.snapshot()
    t_wall0 = time.perf_counter()

    n_flushes = [0]

    def flush_once() -> bool:
        """One coalesced mesh flush, byte-checked vs the oracle."""
        n_flushes[0] += 1
        payloads = [rng.integers(0, 256, size=n_stripes * k * chunk,
                                 dtype=np.uint8)
                    for _ in range(n_requests)]
        oracles = [eu_encode(sinfo, impl, p, want) for p in payloads]
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                for p in payloads]
        g_dispatcher.flush()
        ok = True
        for f, oracle in zip(futs, oracles):
            res = f.result()
            ok = ok and sorted(res) == sorted(oracle) and all(
                np.asarray(res[i]).tobytes()
                == np.asarray(oracle[i]).tobytes() for i in oracle)
        cluster.tick(dt=1.0)     # the mgr judges DURING the run
        return ok

    def spread(pcts: Dict[int, Dict[str, float]]) -> float:
        # max p99 over the mesh-median p99, with the scoreboard's own
        # median rule so the two surfaces cannot drift
        from ..mesh.chipstat import ChipStat
        p99s = [p["p99"] for p in pcts.values() if p["p99"] > 0]
        if not p99s:
            return 0.0
        med = ChipStat._median(p99s)
        return round(max(p99s) / max(med, 1e-9), 3)

    identical = True
    try:
        identical &= flush_once()          # compile warmup
        g_chipstat.reset()                 # drop compile-era samples
        # ---- leg 1: healthy twin ----------------------------------------
        for _ in range(healthy_flushes):
            identical &= flush_once()
        healthy_false_suspects = len(g_chipstat.suspects())
        healthy_raised = "TPU_MESH_SKEW" in cluster.mgr.health_checks
        healthy_spread = spread(g_chipstat.per_chip_percentiles())
        healthy_max_ratio = max(
            (r["skew_ratio"] for r in
             g_chipstat.summary()["per_chip"].values()), default=0.0)
        # ---- leg 2: one chip slowed -------------------------------------
        g_chipstat.reset()
        g_faults.inject("mesh.chip_slowdown", mode="always",
                        match=f"chip={slow_chip}/", delay_us=delay_us)
        detection_probes = 0
        for i in range(1, max_probes + 1):
            identical &= flush_once()
            if g_chipstat.suspects():
                detection_probes = i
                break
        suspects = g_chipstat.suspects()
        detected_chip = suspects[0]["chip"] if suspects else -1
        skew_ratio_detected = suspects[0]["skew_ratio"] if suspects \
            else 0.0
        raised = "TPU_MESH_SKEW" in cluster.mgr.health_checks
        raised_message = cluster.mgr.health_checks.get(
            "TPU_MESH_SKEW", "")
        slowed_spread = spread(g_chipstat.per_chip_percentiles())
        # ---- leg 3: fault removed, the check must clear -----------------
        g_faults.clear("mesh.chip_slowdown")
        cleared = False
        for _ in range(4 * max_probes):
            identical &= flush_once()
            if not g_chipstat.suspects() \
                    and "TPU_MESH_SKEW" not in \
                    cluster.mgr.health_checks:
                cleared = True
                break
        n_probes_total = g_chipstat.summary()["probes"]
    finally:
        g_faults.clear("mesh.chip_slowdown")
        for opt, v in saved.items():
            g_conf.rm_val(opt) if v is None else g_conf.set_val(opt, v)
        g_dispatcher.flush()
        g_mesh.topology()
        # the scoreboard is process-global: a residual suspect (a run
        # whose clear leg failed) must not raise TPU_MESH_SKEW in the
        # unrelated workloads that follow this one
        g_chipstat.reset()
    wall_s = max(time.perf_counter() - t_wall0, 1e-3)
    # EXACT op count for the gated per-op blocks: the clear leg's
    # flush count varies with how fast the EWMA streaks settle, so
    # reconstructing it would make copies_per_op wobble round-to-round
    n_ops = n_flushes[0] * n_requests
    v = max(skew_ratio_detected, 1e-6)
    return make_metric(
        name, v, "ratio", fenced=True,
        stats={"n": 1, "median": v, "iqr": 0.0, "min": v, "max": v},
        roofline={"verdict": "unknown", "suspect": False},
        extra={
            "skew": {
                "mesh_chips": mesh_chips,
                "slow_chip": slow_chip,
                "delay_us": delay_us,
                "threshold": threshold,
                "detected_chip": detected_chip,
                "skew_ratio_detected": skew_ratio_detected,
                "detection_probes": detection_probes,
                "healthy_false_suspects": healthy_false_suspects,
                "healthy_raised": bool(healthy_raised),
                "healthy_max_ratio": healthy_max_ratio,
                "healthy_p99_spread": healthy_spread,
                "slowed_p99_spread": slowed_spread,
                "raised": bool(raised),
                "cleared": bool(cleared),
                "probes_total": n_probes_total,
            },
            "identical": bool(identical),
            "raised_message": raised_message,
            "devflow": _devflow_since(flow0, max(n_ops, 1)),
            "stage_breakdown": _stage_breakdown_since(
                stage0, wall_s, max(n_ops, 1)),
        })


def measure_mesh_straggler(*, mesh_chips: int = 8, slow_chip: int = 5,
                           delay_us: int = 30_000, threshold: float = 3.0,
                           n_flushes: int = 24, detect_max: int = 10,
                           n_requests: int = 3, chunk: int = 1024,
                           k: int = 4, m: int = 2, n_stripes: int = 2,
                           unprotected_flushes: int = 6,
                           name: str = "ec_mesh_straggler"
                           ) -> Dict[str, Any]:
    """The straggler-proof encode A/B (docs/DISPATCH.md "Rateless
    coded encode"): the flagship robustness claim — with one chip
    slowed 10x, the rateless-coded mesh keeps cluster_rollup
    ``device_call`` p999 next to the healthy twin's, where the
    block-sharded path pays the whole delay on every probing flush.

    Four legs on one mini cluster (the mgr ticks after EVERY flush, so
    each phase's cluster_rollup window isolates that phase's
    histogram deltas):

    1. **healthy** (rateless on): N coalesced flushes; phase
       rollup yields the healthy ``device_call`` p999 and the devprof
       site deltas yield the coded-bandwidth overhead the healthy
       twin pays for protection (parity h2d over systematic h2d —
       gated < 2x).
    2. **detect** (``mesh.chip_slowdown`` armed on exactly
       *slow_chip*): flushes until the scoreboard marks the suspect —
       ``skew_ratio_detected`` is the injected-degradation receipt
       the gate requires, ``detection_probes`` bounds the transient.
    3. **protected steady state** (fault still armed, chip now
       SUSPECT): N more flushes; the phase rollup's ``device_call``
       p999 over the healthy twin's is ``protected_p999_ratio`` — the
       gated claim.  Rollup percentiles are log2-bucket edges, so the
       companion ``protected_p999_wall_ratio`` (exact per-flush wall
       times) carries the unquantized figure.
    4. **unprotected twin** (rateless OFF, fault still armed): a few
       flushes through the block-sharded path, whose every-flush
       probe genuinely waits out the delay — the ~10x p999 the fix
       exists to kill, reported for contrast.

    Every flush's outputs are byte-compared against the unprotected
    single-device oracle (subset completion + host re-solves must be
    invisible in the bytes), and the protected legs must record zero
    single-device fallbacks — completion comes from the surviving
    subset, not the degradation ladder.
    """
    from ..cluster import MiniCluster
    from ..common.config import g_conf
    from ..dispatch import g_dispatcher
    from ..ec.tpu_plugin import ErasureCodeTpu
    from ..fault import g_faults
    from ..mesh import (g_chipstat, g_mesh, rateless_perf_counters)
    from ..mesh.runtime import l_mesh_fallbacks, mesh_perf_counters
    from ..osd.ecutil import encode as eu_encode, stripe_info_t

    saved = {opt: g_conf.values.get(opt) for opt in
             ("ec_mesh_chips", "ec_dispatch_batch_max",
              "ec_dispatch_batch_window_us",
              "ec_mesh_skew_sample_every", "ec_mesh_skew_threshold",
              "ec_mesh_rateless", "ec_mesh_rateless_tasks")}
    g_conf.set_val("ec_mesh_chips", mesh_chips)
    g_conf.set_val("ec_dispatch_batch_max", 64)
    g_conf.set_val("ec_dispatch_batch_window_us", 10**7)
    g_conf.set_val("ec_mesh_skew_sample_every", 1)
    g_conf.set_val("ec_mesh_skew_threshold", threshold)
    g_conf.set_val("ec_mesh_rateless", True)

    cluster = MiniCluster(n_osds=4)
    impl = ErasureCodeTpu()
    impl.init({"k": str(k), "m": str(m), "technique": "reed_sol_van"})
    sinfo = stripe_info_t(k, k * chunk)
    want = set(range(k + m))
    rng = np.random.default_rng(20260804)
    flow0 = g_devprof.snapshot()
    stage0 = g_oplat.snapshot()
    t_wall0 = time.perf_counter()
    n_flushes_total = [0]
    identical = [True]

    def flush_once() -> float:
        """One coalesced mesh flush, byte-checked vs the oracle;
        returns the wall seconds of the submit->resolve section (the
        oracle encode and the byte compare run outside the clock)."""
        n_flushes_total[0] += 1
        payloads = [rng.integers(0, 256, size=n_stripes * k * chunk,
                                 dtype=np.uint8)
                    for _ in range(n_requests)]
        oracles = [eu_encode(sinfo, impl, p, want) for p in payloads]
        t0 = time.perf_counter()
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                for p in payloads]
        g_dispatcher.flush()
        results = [f.result() for f in futs]
        wall = time.perf_counter() - t0
        for res, oracle in zip(results, oracles):
            ok = sorted(res) == sorted(oracle) and all(
                np.asarray(res[i]).tobytes()
                == np.asarray(oracle[i]).tobytes() for i in oracle)
            identical[0] = identical[0] and ok
        cluster.tick(dt=1.0)     # the mgr rolls up DURING the run
        return wall

    def phase(n: int):
        """Run *n* flushes as one cluster_rollup window; returns
        (device_call percentiles from the phase rollup, wall p999)."""
        cluster.tick(dt=1.0)            # the window's baseline sample
        clock0 = cluster.clock
        walls = [flush_once() for _ in range(n)]
        # window anchored ON the baseline sample: the newest sample at
        # least (span - 0.5) old is exactly the clock0 tick (samples
        # land on 1.0-spaced ticks), so the rollup deltas cover THIS
        # phase's flushes and nothing earlier
        roll = cluster.mgr.telemetry.rollup(
            window_s=cluster.clock - clock0 - 0.5)
        dc = roll.get("oplat", {}).get("device_call", {})
        walls.sort()
        p999_wall = walls[min(int(np.ceil(0.999 * len(walls))) - 1,
                              len(walls) - 1)]
        return dc, p999_wall * 1e6

    def wasted_ratio(c0: Dict[str, int]) -> float:
        c1 = rateless_perf_counters().dump()
        coded = c1["coded_tasks"] - c0["coded_tasks"]
        parity = c1["parity_tasks"] - c0["parity_tasks"]
        return round(coded / max(coded - parity, 1), 4)

    try:
        flush_once()                    # compile warmup
        g_chipstat.reset()
        rl0 = rateless_perf_counters().dump()
        mesh_fb0 = mesh_perf_counters().get(l_mesh_fallbacks)
        # ---- leg 1: healthy twin, rateless on ---------------------------
        sites0 = {s: dict(v) for s, v in
                  g_devprof.dump()["sites"].items()}
        healthy_dc, healthy_wall_p999 = phase(n_flushes)
        sites1 = g_devprof.dump()["sites"]

        def h2d_delta(site: str) -> int:
            return (sites1.get(site, {}).get("h2d_bytes", 0)
                    - sites0.get(site, {}).get("h2d_bytes", 0))

        sys_h2d = h2d_delta("mesh.encode")
        parity_h2d = h2d_delta("mesh.rateless_parity")
        bandwidth_overhead = round(
            (sys_h2d + parity_h2d) / max(sys_h2d, 1), 4)
        healthy_false_suspects = len(g_chipstat.suspects())
        # ---- leg 2: slow one chip, count probes to detection ------------
        g_faults.inject("mesh.chip_slowdown", mode="always",
                        match=f"chip={slow_chip}/", delay_us=delay_us)
        detection_probes = 0
        for i in range(1, detect_max + 1):
            flush_once()
            if g_chipstat.suspects():
                detection_probes = i
                break
        suspects = g_chipstat.suspects()
        detected_chip = suspects[0]["chip"] if suspects else -1
        skew_ratio_detected = suspects[0]["skew_ratio"] if suspects \
            else 0.0
        # ---- leg 3: protected steady state (chip SUSPECT, still slow) --
        slowed_dc, slowed_wall_p999 = phase(n_flushes)
        subset_completions = (rateless_perf_counters().dump()
                              ["subset_completions"]
                              - rl0["subset_completions"])
        chip_failures = (rateless_perf_counters().dump()
                         ["chip_failures"] - rl0["chip_failures"])
        fallbacks = mesh_perf_counters().get(l_mesh_fallbacks) \
            - mesh_fb0
        coded_overhead = wasted_ratio(rl0)
        # ---- leg 4: the unprotected twin (block-sharded SPMD path) ------
        g_conf.set_val("ec_mesh_rateless", False)
        flush_once()       # SPMD plan compile warmup, outside the clock
        unprot_dc, unprot_wall_p999 = phase(unprotected_flushes)
        g_conf.set_val("ec_mesh_rateless", True)
    finally:
        g_faults.clear("mesh.chip_slowdown")
        for opt, v in saved.items():
            g_conf.rm_val(opt) if v is None else g_conf.set_val(opt, v)
        g_dispatcher.flush()
        g_mesh.topology()
        # process-global scoreboard: a leftover suspect must not haunt
        # the workloads that follow (the skew workload's policy)
        g_chipstat.reset()
    # incident forensics receipt: the detect/protected legs raise
    # TPU_MESH_SKEW through the ticked mgr, which auto-captures a
    # bundle; the operator fallback only fires if detection never did
    inc_mgr = cluster.mgr.incident
    if inc_mgr.captures_total == 0:
        inc_mgr.capture("operator", "straggler forensic snapshot",
                        reason="operator")
    incidents = inc_mgr.receipt()
    wall_s = max(time.perf_counter() - t_wall0, 1e-3)
    n_ops = n_flushes_total[0] * n_requests
    healthy_p999 = float(healthy_dc.get("p999", 0.0) or 0.0)
    slowed_p999 = float(slowed_dc.get("p999", 0.0) or 0.0)
    unprot_p999 = float(unprot_dc.get("p999", 0.0) or 0.0)
    ratio = round(slowed_p999 / max(healthy_p999, 1e-9), 4)
    wall_ratio = round(slowed_wall_p999 / max(healthy_wall_p999, 1e-9),
                       4)
    v = max(wall_ratio, 1e-6)
    return make_metric(
        name, v, "ratio", fenced=True,
        stats={"n": 1, "median": v, "iqr": 0.0, "min": v, "max": v},
        roofline={"verdict": "unknown", "suspect": False},
        extra={
            "straggler": {
                "mesh_chips": mesh_chips,
                "slow_chip": slow_chip,
                "delay_us": delay_us,
                "threshold": threshold,
                "detection_probes": detection_probes,
                "detected_chip": detected_chip,
                "skew_ratio_detected": skew_ratio_detected,
                "healthy_false_suspects": healthy_false_suspects,
                "healthy_p999_usec": healthy_p999,
                "slowed_p999_usec": slowed_p999,
                "unprotected_p999_usec": unprot_p999,
                "protected_p999_ratio": ratio,
                "protected_p999_wall_ratio": wall_ratio,
                "healthy_p999_wall_usec": round(healthy_wall_p999, 1),
                "slowed_p999_wall_usec": round(slowed_wall_p999, 1),
                "unprotected_p999_wall_usec": round(unprot_wall_p999,
                                                    1),
                "unprotected_p999_wall_ratio": round(
                    unprot_wall_p999 / max(healthy_wall_p999, 1e-9),
                    4),
                "bandwidth_overhead": bandwidth_overhead,
                "coded_task_overhead": coded_overhead,
                "subset_completions": int(subset_completions),
                "chip_failures": int(chip_failures),
                "single_device_fallbacks": int(fallbacks),
                "byte_identical": bool(identical[0]),
            },
            "identical": bool(identical[0]),
            "incidents": incidents,
            "devflow": _devflow_since(flow0, max(n_ops, 1)),
            "stage_breakdown": _stage_breakdown_since(
                stage0, wall_s, max(n_ops, 1)),
        })


def measure_traffic(*, n_clients: int = 8, ops_per_client: int = 32,
                    read_fraction: float = 0.5, n_osds: int = 4,
                    pg_num: int = 8, mode: str = "closed",
                    rate_multipliers: Tuple[float, ...] = (),
                    admission_max: int = 0, seed: int = 20260803,
                    keep_completions: bool = False,
                    name: str = "traffic_harness_smoke",
                    progress=None) -> Dict[str, Any]:
    """The traffic-harness workload (ceph_tpu/load, docs/QOS.md): N
    synthetic clients over the real messenger/client stack against a
    fresh replicated mini-cluster, per-client p50/p99/p999 out of the
    PerfHistogram machinery, byte-exact verification of every op.

    Fencing: the value is client-observed completions per wall second —
    the clock stops only when every reply's bytes have crossed back to
    the issuing client, which is the drain contract by construction
    (host-side fabric; no device dispatch is in the op path to
    acknowledge early).  No roofline model applies to scheduler
    throughput, so the verdict is ``unknown``, never silently ``ok``.
    """
    from ..cluster import MiniCluster
    from ..common.config import g_conf
    from ..load import TrafficSpec, run_traffic

    cluster = MiniCluster(n_osds=n_osds)
    cluster.create_replicated_pool("load", size=3, pg_num=pg_num)
    saved = g_conf.values.get("osd_op_queue_admission_max")
    saved_ret = g_conf.values.get("mgr_telemetry_retention")
    if admission_max:
        g_conf.set_val("osd_op_queue_admission_max", admission_max)
    # the whole-run rollup below needs the mgr's boot baseline sample
    # to SURVIVE the run's tick count — ring eviction would silently
    # truncate the "whole-run" window to its tail and under-report the
    # wall rates.  10k samples covers any max_rounds/tick_every shape
    # the harness can produce (one sample per cluster tick).
    g_conf.set_val("mgr_telemetry_retention", 10_000)
    flow0 = g_devprof.snapshot()
    stage0 = g_oplat.snapshot()
    try:
        res = run_traffic(cluster, TrafficSpec(
            pool="load", n_clients=n_clients,
            ops_per_client=ops_per_client, read_fraction=read_fraction,
            mode=mode, rate_multipliers=tuple(rate_multipliers),
            seed=seed, keep_completions=keep_completions),
            progress=progress)
        # end-of-run cluster rollup (mgr/telemetry.py): the window
        # spans the whole run — the boot-time baseline isolates this
        # cluster's deltas from earlier workloads' process-global
        # counts — so harness A/B comparisons (mesh dispatch,
        # zero-copy) read ONE cluster tail number per stage instead
        # of N per-daemon dumps
        wall_run_s = max(res.elapsed_s, 1e-3)
        cluster.clock += wall_run_s
        cluster.mgr.telemetry.tick(cluster.mgr, cluster.clock)
        roll = cluster.mgr.telemetry.rollup(
            window_s=cluster.clock + 1.0)
    finally:
        if admission_max:
            if saved is None:
                g_conf.rm_val("osd_op_queue_admission_max")
            else:
                g_conf.set_val("osd_op_queue_admission_max", saved)
        if saved_ret is None:
            g_conf.rm_val("mgr_telemetry_retention")
        else:
            g_conf.set_val("mgr_telemetry_retention", saved_ret)
    pc = bench_perf_counters()
    pc.inc(l_bench_bytes, res.bytes_moved)
    # the rollup window's dt mixes run_traffic's simulated tick
    # seconds with the final wall bump; rescale to WALL rates so the
    # A/B number is a real throughput figure (rate * span = the
    # window's counter delta, so this is exact, not a guess)
    wall_rates = {k: round(v * roll["span_s"] / wall_run_s, 4)
                  for k, v in roll["rates"].items()}
    cluster_rollup = {
        "oplat_p99_usec": roll["oplat_p99_usec"],
        "rates": wall_rates,
        "copies_per_op": roll["copies_per_op"],
        "slo": {check: st["state"]
                for check, st in roll["slo"].items()},
        "samples": roll["samples"],
        "span_s": roll["span_s"],
    }
    v = max(res.ops_per_sec, 1e-6)
    return make_metric(
        name, v, "ops/s", fenced=True,
        stats={"n": 1, "median": v, "iqr": 0.0, "min": v, "max": v},
        roofline={"verdict": "unknown", "suspect": False},
        extra={"n_clients": n_clients, "total_ops": res.total_ops,
               "devflow": _devflow_since(flow0, max(res.completed, 1)),
               # the op-path stage decomposition (admission -> queue
               # tiers -> service -> fan-out -> reply) over the run;
               # queued ops wait concurrently, so coverage can exceed 1
               "stage_breakdown": _stage_breakdown_since(
                   stage0, max(res.elapsed_s, 1e-9),
                   max(res.completed, 1)),
               "cluster_rollup": cluster_rollup,
               "completed": res.completed,
               "byte_exact": bool(res.byte_exact),
               "rounds": res.rounds,
               "elapsed_s": round(res.elapsed_s, 3),
               "throttled_total": res.throttled_total,
               "admission_rejections": res.admission_rejections,
               "max_intake_depth": res.max_intake_depth,
               # per-client percentiles in usec (PerfHistogram bucket
               # upper edges — the same series Prometheus exports)
               "per_client": res.per_client,
               "aggregate": res.aggregate,
               "errors": res.errors[:8]})


def measure_ec_write_zero_copy(*, n_osds: int = 6, k: int = 3,
                               m: int = 2, n_objects: int = 6,
                               stripes_per_object: int = 2,
                               pg_num: int = 8,
                               name: str = "ec_write_zero_copy"
                               ) -> Dict[str, Any]:
    """The zero-copy write path A/B (docs/DISPATCH.md "Zero-copy write
    path"): the same EC client writes through two fresh mini-clusters —
    device-RESIDENT (``os_memstore_device_bytes_max`` large: fused
    encode+crc, shard bodies stay in HBM as DeviceShard handles, zero
    body d2h) vs the BYTES twin (budget 0: today's host-bytes funnel) —
    with each leg's devflow captured over the write region only.

    The receipt is the ``zero_copy`` block, judged by regress.py's
    ZERO-COPY gate as absolute invariants: the resident leg's write-path
    d2h must stay under the devflow floor (512 B/op — the only fetch is
    the crc scalar), its copies_per_op must be STRICTLY below the bytes
    twin's (the deleted copies are the whole point), residency must have
    actually engaged (shard handles live in the store when the region
    closes), and read-backs — which materialize lazily, AFTER the delta
    capture — must be byte-exact on both legs and equal across them.

    Fencing: the write region's clock stops when every client ack has
    returned on the in-process fabric; the resident leg's encode path
    ends in the crc d2h fetch, which is itself a completion fence for
    the fused kernel (the scalar cannot come back before the shard
    bodies exist)."""
    from ..cluster import MiniCluster
    from ..common.config import g_conf
    from ..os_store.device_shard import g_device_budget

    width = k * int(g_conf.get_val("osd_pool_erasure_code_stripe_unit"))
    object_bytes = stripes_per_object * width
    rng = np.random.default_rng(20260807)
    payloads = [rng.integers(0, 256, size=object_bytes,
                             dtype=np.uint8).tobytes()
                for _ in range(n_objects)]
    saved = g_conf.values.get("os_memstore_device_bytes_max")
    pc = bench_perf_counters()
    legs: Dict[str, Dict[str, Any]] = {}
    read_backs: Dict[str, list] = {}
    try:
        for leg, budget in (("resident", 1 << 30), ("bytes_twin", 0)):
            g_conf.set_val("os_memstore_device_bytes_max", budget)
            cluster = MiniCluster(n_osds=n_osds)
            cluster.create_ec_pool("zc", k=k, m=m, pg_num=pg_num)
            cl = cluster.client(f"client.zc_{leg}")
            flow0 = g_devprof.snapshot()
            stage0 = g_oplat.snapshot()
            t0 = time.perf_counter()
            for i, data in enumerate(payloads):
                rc = cl.write_full("zc", f"obj-{i}", data)
                assert rc == 0, f"write_full rc={rc}"
            wall_s = max(time.perf_counter() - t0, 1e-9)
            # the gated receipt: flow over the WRITE region only —
            # read-backs (below) materialize resident shards, and that
            # d2h is the read path's to pay, not the write path's
            flow = _devflow_since(flow0, n_objects)
            breakdown = _stage_breakdown_since(stage0, wall_s,
                                               n_objects)
            resident_shards = g_device_budget.resident_shards()
            read_backs[leg] = [cl.read("zc", f"obj-{i}")
                               for i in range(n_objects)]
            legs[leg] = {"devflow": flow, "stage_breakdown": breakdown,
                         "wall_s": wall_s,
                         "resident_shards": resident_shards,
                         "ops_per_sec": round(n_objects / wall_s, 2)}
            pc.inc(l_bench_bytes, n_objects * object_bytes)
    finally:
        if saved is None:
            g_conf.rm_val("os_memstore_device_bytes_max")
        else:
            g_conf.set_val("os_memstore_device_bytes_max", saved)
    byte_exact = all(
        bytes(read_backs["resident"][i]) == payloads[i]
        and bytes(read_backs["bytes_twin"][i]) == payloads[i]
        for i in range(n_objects))
    res_flow = legs["resident"]["devflow"]
    twin_flow = legs["bytes_twin"]["devflow"]
    zero_copy = {
        "resident": res_flow,
        "bytes_twin": twin_flow,
        "resident_d2h_bytes_per_op": round(
            res_flow["d2h_bytes"] / max(n_objects, 1), 2),
        "resident_copies_per_op": res_flow["copies_per_op"],
        "twin_copies_per_op": twin_flow["copies_per_op"],
        "resident_shards": legs["resident"]["resident_shards"],
        "byte_exact": bool(byte_exact),
    }
    v = legs["resident"]["ops_per_sec"]
    return make_metric(
        name, v, "ops/s", fenced=True,
        stats={"n": 1, "median": v, "iqr": 0.0, "min": v, "max": v},
        roofline={"verdict": "unknown", "suspect": False},
        extra={"n_objects": n_objects, "object_bytes": object_bytes,
               "k": k, "m": m,
               "devflow": res_flow,
               "stage_breakdown": legs["resident"]["stage_breakdown"],
               "twin_ops_per_sec": legs["bytes_twin"]["ops_per_sec"],
               "twin_devflow": twin_flow,
               "twin_stage_breakdown":
                   legs["bytes_twin"]["stage_breakdown"],
               "zero_copy": zero_copy})


def measure_recovery_storm(*, k: int = 8, m: int = 4, d: int = 10,
                           n_osds: int = 0, pg_num: int = 4,
                           n_objects: int = 8,
                           object_bytes: int = 4096,
                           n_clients: int = 4,
                           ops_per_client: int = 12,
                           seed: int = 20260804,
                           name: str = "ec_recovery_storm"
                           ) -> Dict[str, Any]:
    """The recovery-storm workload (docs/RECOVERY.md): kill an OSD
    under open-loop harness traffic and measure
    bytes-moved-per-repaired-shard for the regenerating codec family
    vs the RS full-stripe baseline — the repair-bandwidth claim as a
    gated number, with the well-behaved clients' cluster_rollup
    per-stage p99 + SLO state captured DURING the backfill.

    Shape: one cluster, two EC pools over the same object set —
    ``storm_rs`` (tpu plugin, classic RS matrix) and ``storm_regen``
    (product-matrix regenerating, repair via d sub-chunk helper
    contributions).  The traffic harness drives open-loop clients
    against the RS pool while the event schedule kills + outs one
    acting OSD mid-run; backfill to the spare rebuilds its shards on
    BOTH pools through the recovery scheduler, which tallies bytes
    moved per codec family.  Fencing: all figures are client-observed
    or counter deltas on the host-side fabric — no device dispatch can
    acknowledge early — and the byte-exact read-back of every
    pre-populated object after backfill is the correctness receipt.
    """
    from ..cluster import MiniCluster
    from ..common.config import g_conf
    from ..load import TrafficSpec, run_traffic
    from ..recovery import aggregate_families

    if not n_osds:
        n_osds = k + m + 2              # one spare + one margin
    cluster = MiniCluster(n_osds=n_osds)
    cluster.create_ec_pool("storm_rs", k=k, m=m, pg_num=pg_num,
                           plugin="tpu")
    cluster.create_ec_pool("storm_regen", k=k, m=m, pg_num=pg_num,
                           plugin="regenerating",
                           extra_profile={"d": str(d)})
    cl = cluster.client("client.storm")
    rng = np.random.default_rng(seed)
    bodies: Dict[str, bytes] = {}
    for i in range(n_objects):
        body = rng.integers(0, 256, object_bytes,
                            dtype=np.uint8).tobytes()
        bodies[f"storm-{i}"] = body
        for pool in ("storm_rs", "storm_regen"):
            assert cl.write_full(pool, f"storm-{i}", body) == 0
    # victim: an OSD acting for EC PGs in both pools, so ONE failure
    # drives both families' repair paths
    votes: Dict[int, int] = {}
    for _pgid, pg in cluster.primary_pgs():
        if pg.backend is not None:
            for o in pg.acting:
                if o >= 0:
                    votes[o] = votes.get(o, 0) + 1
    victim = max(sorted(votes), key=lambda o: votes[o])
    fam_before = aggregate_families(cluster.osds.values())
    saved_slo = g_conf.values.get("mgr_slo_oplat_p99_usec")
    saved_ret = g_conf.values.get("mgr_telemetry_retention")
    # a generous latency objective makes "no TPU_SLO_OPLAT during the
    # storm" a real (armed) assertion instead of a vacuous one
    g_conf.set_val("mgr_slo_oplat_p99_usec", "reply:2000000")
    g_conf.set_val("mgr_telemetry_retention", 10_000)
    flow0 = g_devprof.snapshot()
    stage0 = g_oplat.snapshot()
    slo_seen: Dict[str, str] = {}
    try:
        spec = TrafficSpec(
            pool="storm_rs", n_clients=n_clients,
            ops_per_client=ops_per_client, read_fraction=0.5,
            mode="open", rate=4.0, seed=seed,
            keep_completions=False,
            events=((2, "osd_kill", victim), (3, "osd_out", victim)))
        res = run_traffic(cluster, spec)
        # drive backfill to completion under the post-storm map
        for _ in range(16):
            cluster.tick(dt=1.0)
            states = set(cluster.pg_states().values())
            if states <= {"active"}:
                break
        wall_run_s = max(res.elapsed_s, 1e-3)
        cluster.clock += wall_run_s
        cluster.mgr.telemetry.tick(cluster.mgr, cluster.clock)
        roll = cluster.mgr.telemetry.rollup(
            window_s=cluster.clock + 1.0)
        slo_seen = {check: st["state"]
                    for check, st in roll["slo"].items()}
    finally:
        if saved_slo is None:
            g_conf.rm_val("mgr_slo_oplat_p99_usec")
        else:
            g_conf.set_val("mgr_slo_oplat_p99_usec", saved_slo)
        if saved_ret is None:
            g_conf.rm_val("mgr_telemetry_retention")
        else:
            g_conf.set_val("mgr_telemetry_retention", saved_ret)
    # byte-exact read-back of every pre-populated object AFTER backfill
    # (both pools) — the storm's correctness receipt
    identical = True
    for oid, body in bodies.items():
        for pool in ("storm_rs", "storm_regen"):
            if cl.read(pool, oid) != body:
                identical = False
    fam_after = aggregate_families(cluster.osds.values())
    # incident forensics receipt: a plain OSD kill raises no mgr
    # health check (health() counts down osds inline), so the storm
    # stamps an operator capture — the bundle still carries the
    # osd_down/osd_out journal events and the post-backfill state
    inc_mgr = cluster.mgr.incident
    if inc_mgr.captures_total == 0:
        inc_mgr.capture("operator", "post-storm forensic snapshot",
                        reason="operator")
    incidents = inc_mgr.receipt()

    from ..recovery.scheduler import FAMILY_KEYS

    def _delta(fam: str) -> Dict[str, float]:
        a = fam_after.get(fam, {})
        b = fam_before.get(fam, {})
        out = {key: a.get(key, 0) - b.get(key, 0)
               for key in FAMILY_KEYS}
        out["bytes_per_repaired_shard"] = round(
            out["bytes_moved"] / max(out["repaired_shards"], 1), 2)
        return out

    regen = _delta("pm-regen")
    rs = _delta("isa-matrix")
    ratio = regen["bytes_per_repaired_shard"] / \
        max(rs["bytes_per_repaired_shard"], 1e-9)
    pc = bench_perf_counters()
    pc.inc(l_bench_bytes, res.bytes_moved)
    wall_rates = {key: round(v * roll["span_s"] / wall_run_s, 4)
                  for key, v in roll["rates"].items()}
    cluster_rollup = {
        "oplat_p99_usec": roll["oplat_p99_usec"],
        "rates": wall_rates,
        "copies_per_op": roll["copies_per_op"],
        "slo": slo_seen,
        "samples": roll["samples"],
        "span_s": roll["span_s"],
    }
    v = max(regen["bytes_per_repaired_shard"], 1e-6)
    return make_metric(
        name, v, "B/shard", fenced=True,
        stats={"n": 1, "median": v, "iqr": 0.0, "min": v, "max": v},
        roofline={"verdict": "unknown", "suspect": False},
        extra={
            "recovery": {
                "bytes_per_repaired_shard_regen":
                    regen["bytes_per_repaired_shard"],
                "bytes_per_repaired_shard_rs":
                    rs["bytes_per_repaired_shard"],
                "regen_vs_rs_ratio": round(ratio, 4),
                "families": {"pm-regen": regen, "isa-matrix": rs},
            },
            "k": k, "m": m, "d": d, "victim_osd": victim,
            "identical": identical,
            "byte_exact_traffic": bool(res.byte_exact),
            "traffic_completed": res.completed,
            "slo": slo_seen,
            "incidents": incidents,
            "cluster_rollup": cluster_rollup,
            "devflow": _devflow_since(
                flow0, max(regen["repaired_shards"]
                           + rs["repaired_shards"], 1)),
            "stage_breakdown": _stage_breakdown_since(
                stage0, wall_run_s,
                max(regen["repaired_shards"]
                    + rs["repaired_shards"], 1)),
            "errors": res.errors[:8],
        })


def measure_degraded_read(*, mesh_chips: int = 8, slow_chip: int = 5,
                          delay_us: int = 30_000, threshold: float = 3.0,
                          n_batches: int = 16, detect_max: int = 10,
                          n_objects: int = 4, object_bytes: int = 4096,
                          k: int = 4, m: int = 2,
                          n_clients: int = 4, ops_per_client: int = 8,
                          meshoff_batches: int = 6,
                          seed: int = 20260807,
                          name: str = "ec_degraded_read"
                          ) -> Dict[str, Any]:
    """The straggler-proof degraded-read A/B (docs/DISPATCH.md
    "Mesh-sharded degraded reads"): kill a data-shard OSD under
    open-loop harness traffic, then drive every read of the pool
    through the meshed rateless decode path with one chip slowed 10x —
    the read-side twin of ``ec_mesh_straggler``, judged by the same
    STRAGGLER GATE.

    Shape: one pg_num=1 EC pool so a single OSD kill (acting[1] — a
    non-primary DATA shard) degrades every object; the traffic harness
    lands the kill mid-run (open-loop clients stay byte-exact through
    it), after which the cluster never backfills (down, not out) and
    each read is a fresh survivor-sharded decode.  Four legs on the
    degraded cluster, each a cluster_rollup window like the encode
    twin's:

    1. **healthy** (mesh on, rateless on): N read batches; phase
       rollup yields the healthy ``device_call`` p999 and the
       DECODE_SITES h2d deltas yield the coded-bandwidth overhead
       (parity over systematic — gated < 2x).
    2. **detect** (``mesh.chip_slowdown`` armed on *slow_chip*): read
       batches until the scoreboard marks the suspect from decode
       probes alone — no write traffic to help.
    3. **protected steady state** (fault armed, chip SUSPECT): N more
       batches; ``device_call`` p999 over the healthy twin's is the
       gated ``protected_p999_ratio``, wall p999 the unquantized
       companion.
    4. **mesh-off twin** (``ec_mesh_chips=1`` via the checked-set
       membership transition): the single-device decode baseline the
       tentpole replaced, reported as the stage_breakdown A/B
       (``device_call``/``d2h`` per-op, mesh-on vs mesh-off).

    Every read byte-compared against the pre-populated body; the
    protected legs must record zero ``mesh_decode_fallbacks`` —
    completion comes from the first spanning subset, not the
    single-device degradation ladder.
    """
    from ..cluster import MiniCluster
    from ..common.config import g_conf
    from ..fault import g_faults
    from ..load import TrafficSpec, run_traffic
    from ..mesh import g_chipstat, g_mesh, rateless_perf_counters
    from ..mesh.runtime import (l_mdec_fallbacks,
                                mesh_decode_perf_counters)

    saved = {opt: g_conf.values.get(opt) for opt in
             ("ec_mesh_chips", "ec_mesh_skew_sample_every",
              "ec_mesh_skew_threshold", "ec_mesh_rateless",
              "ec_mesh_rateless_tasks")}
    g_conf.set_val("ec_mesh_chips", mesh_chips)
    g_conf.set_val("ec_mesh_skew_sample_every", 1)
    g_conf.set_val("ec_mesh_skew_threshold", threshold)
    g_conf.set_val("ec_mesh_rateless", True)

    cluster = MiniCluster(n_osds=k + m + 2)
    cluster.create_ec_pool("dread", k=k, m=m, pg_num=1, plugin="tpu")
    cl = cluster.client("client.dread")
    rng = np.random.default_rng(seed)
    bodies: Dict[str, bytes] = {}
    for i in range(n_objects):
        body = rng.integers(0, 256, object_bytes,
                            dtype=np.uint8).tobytes()
        bodies[f"dread-{i}"] = body
        assert cl.write_full("dread", f"dread-{i}", body) == 0
    pool_id = cluster.mon.osdmap.lookup_pg_pool_name("dread")
    acting = next(pg.acting for pgid, pg in cluster.primary_pgs()
                  if pg.backend is not None and pgid[0] == pool_id)
    victim = acting[1]                  # non-primary DATA shard
    flow0 = g_devprof.snapshot()
    stage0 = g_oplat.snapshot()
    t_wall0 = time.perf_counter()
    n_batches_total = [0]
    identical = [True]

    def read_batch() -> float:
        """One batch of degraded reads (every object, byte-compared);
        returns the wall seconds of the read section."""
        n_batches_total[0] += 1
        t0 = time.perf_counter()
        got = [cl.read("dread", oid) for oid in bodies]
        wall = time.perf_counter() - t0
        for g, body in zip(got, bodies.values()):
            identical[0] = identical[0] and g == body
        cluster.tick(dt=1.0)     # the mgr rolls up DURING the run
        return wall

    def phase(n: int):
        """Run *n* read batches as one cluster_rollup window; returns
        (device_call percentiles from the phase rollup, wall p999) —
        the anchored-window pattern of measure_mesh_straggler."""
        cluster.tick(dt=1.0)
        clock0 = cluster.clock
        walls = [read_batch() for _ in range(n)]
        roll = cluster.mgr.telemetry.rollup(
            window_s=cluster.clock - clock0 - 0.5)
        dc = roll.get("oplat", {}).get("device_call", {})
        walls.sort()
        p999_wall = walls[min(int(np.ceil(0.999 * len(walls))) - 1,
                              len(walls) - 1)]
        return dc, p999_wall * 1e6

    def stage_pair(before, wall_s: float, n_ops: int) -> Dict[str, Any]:
        sb = _stage_breakdown_since(before, max(wall_s, 1e-3),
                                    max(n_ops, 1))
        stages = sb.get("stages") or {}
        return {st: stages.get(st, {}) for st in ("device_call", "d2h")}

    try:
        # ---- the storm: open-loop traffic, kill landing mid-run ------
        spec = TrafficSpec(
            pool="dread", n_clients=n_clients,
            ops_per_client=ops_per_client, read_fraction=0.5,
            mode="open", rate=4.0, seed=seed, keep_completions=False,
            events=((1, "osd_kill", victim),))
        res = run_traffic(cluster, spec)
        traffic_byte_exact = bool(res.byte_exact)
        read_batch()                    # decode compile warmup
        g_chipstat.reset()
        mdec0 = mesh_decode_perf_counters().get(l_mdec_fallbacks)
        # ---- leg 1: healthy twin, meshed rateless decode -------------
        sites0 = {s: dict(v) for s, v in
                  g_devprof.dump()["sites"].items()}
        on0 = g_oplat.snapshot()
        t_on0 = time.perf_counter()
        healthy_dc, healthy_wall_p999 = phase(n_batches)
        sites1 = g_devprof.dump()["sites"]

        def h2d_delta(site: str) -> int:
            return (sites1.get(site, {}).get("h2d_bytes", 0)
                    - sites0.get(site, {}).get("h2d_bytes", 0))

        sys_h2d = h2d_delta("mesh.decode")
        parity_h2d = h2d_delta("mesh.decode_parity")
        bandwidth_overhead = round(
            (sys_h2d + parity_h2d) / max(sys_h2d, 1), 4)
        healthy_false_suspects = len(g_chipstat.suspects())
        # ---- leg 2: slow one chip, detect from decode probes alone ---
        rl0 = rateless_perf_counters().dump()
        g_faults.inject("mesh.chip_slowdown", mode="always",
                        match=f"chip={slow_chip}/", delay_us=delay_us)
        detection_probes = 0
        for i in range(1, detect_max + 1):
            read_batch()
            if g_chipstat.suspects():
                detection_probes = i
                break
        suspects = g_chipstat.suspects()
        detected_chip = suspects[0]["chip"] if suspects else -1
        skew_ratio_detected = suspects[0]["skew_ratio"] if suspects \
            else 0.0
        # ---- leg 3: protected steady state (chip SUSPECT, slow) ------
        slowed_dc, slowed_wall_p999 = phase(n_batches)
        n_on_ops = n_batches_total[0] * n_objects
        twin_on = stage_pair(on0, time.perf_counter() - t_on0,
                             n_on_ops)
        subset_completions = (rateless_perf_counters().dump()
                              ["subset_completions"]
                              - rl0["subset_completions"])
        fallbacks = mesh_decode_perf_counters().get(l_mdec_fallbacks) \
            - mdec0
        # ---- leg 4: the mesh-off twin (single-device decode) ---------
        g_conf.set_checked("ec_mesh_chips", 1)
        read_batch()                    # single-device compile warmup
        off0 = g_oplat.snapshot()
        t_off0 = time.perf_counter()
        batches_before = n_batches_total[0]
        unprot_dc, unprot_wall_p999 = phase(meshoff_batches)
        twin_off = stage_pair(
            off0, time.perf_counter() - t_off0,
            (n_batches_total[0] - batches_before) * n_objects)
    finally:
        g_faults.clear("mesh.chip_slowdown")
        for opt, v in saved.items():
            g_conf.rm_val(opt) if v is None else g_conf.set_val(opt, v)
        g_mesh.topology()
        g_chipstat.reset()
    inc_mgr = cluster.mgr.incident
    if inc_mgr.captures_total == 0:
        inc_mgr.capture("operator", "degraded-read forensic snapshot",
                        reason="operator")
    incidents = inc_mgr.receipt()
    wall_s = max(time.perf_counter() - t_wall0, 1e-3)
    n_ops = n_batches_total[0] * n_objects
    healthy_p999 = float(healthy_dc.get("p999", 0.0) or 0.0)
    slowed_p999 = float(slowed_dc.get("p999", 0.0) or 0.0)
    unprot_p999 = float(unprot_dc.get("p999", 0.0) or 0.0)
    ratio = round(slowed_p999 / max(healthy_p999, 1e-9), 4)
    wall_ratio = round(slowed_wall_p999 / max(healthy_wall_p999, 1e-9),
                       4)
    v = max(wall_ratio, 1e-6)
    return make_metric(
        name, v, "ratio", fenced=True,
        stats={"n": 1, "median": v, "iqr": 0.0, "min": v, "max": v},
        roofline={"verdict": "unknown", "suspect": False},
        extra={
            "straggler": {
                "mesh_chips": mesh_chips,
                "slow_chip": slow_chip,
                "delay_us": delay_us,
                "threshold": threshold,
                "detection_probes": detection_probes,
                "detected_chip": detected_chip,
                "skew_ratio_detected": skew_ratio_detected,
                "healthy_false_suspects": healthy_false_suspects,
                "healthy_p999_usec": healthy_p999,
                "slowed_p999_usec": slowed_p999,
                "meshoff_p999_usec": unprot_p999,
                "protected_p999_ratio": ratio,
                "protected_p999_wall_ratio": wall_ratio,
                "healthy_p999_wall_usec": round(healthy_wall_p999, 1),
                "slowed_p999_wall_usec": round(slowed_wall_p999, 1),
                "meshoff_p999_wall_usec": round(unprot_wall_p999, 1),
                "bandwidth_overhead": bandwidth_overhead,
                "subset_completions": int(subset_completions),
                "single_device_fallbacks": int(fallbacks),
                "byte_identical": bool(identical[0]
                                       and traffic_byte_exact),
            },
            "victim_osd": victim,
            "identical": bool(identical[0]),
            "byte_exact_traffic": traffic_byte_exact,
            "traffic_completed": res.completed,
            "twin": {"mesh_on": twin_on, "mesh_off": twin_off},
            "incidents": incidents,
            "devflow": _devflow_since(flow0, max(n_ops, 1)),
            "stage_breakdown": _stage_breakdown_since(
                stage0, wall_s, max(n_ops, 1)),
            "errors": res.errors[:8],
        })


def parity_check(matrix: np.ndarray) -> bool:
    """Encode REAL data on device, erase two data shards, decode on
    device, fetch, byte-compare against the original — the on-hardware
    correctness receipt for the decode throughput number.  Involves
    full device→host fetches, so drivers must run it LAST (sync-
    dispatch poisoning no longer matters by then)."""
    from ..ops.gf_matmul import DeviceRSBackend
    rng = np.random.default_rng(20260731)
    data = rng.integers(0, 256, size=(2, K, 4096), dtype=np.uint8)
    be = DeviceRSBackend(matrix)
    coding = be.encode(data)
    lost = (0, 1)
    srcs = tuple(range(2, K)) + (K, K + 1)
    survivors = np.concatenate([data[:, 2:, :], coding[:, :2, :]], axis=1)
    got = be.decode_data(survivors, srcs, lost)
    return bool(np.array_equal(got, data[:, :2, :]))


def measure_crush_remap(n_osds=1000, n_pgs=100_000, epochs=10,
                        uniform=True, partial=None, infix="",
                        debug=False):
    """The <50 ms north star: remap ALL PGs after an epoch change.

    The workload is OSDMapMapping's per-epoch job (OSDMapMapping.h:17):
    the crush topology is unchanged (candidate tables cached on device),
    one osd flips out per epoch (new weight vector), and the resolution
    kernel re-derives every PG's mapping.  Reported:
      - wall: full map_batch (device resolve + transfer + host
        compaction + exact residual replay) per epoch, median over
        ``epochs``;
      - device: sustained resolve-kernel time amortized over
        back-to-back dispatches drained by a one-element fetch of the
        LAST output (fence.drain's contract) — what a pipelined
        consumer pays per epoch.  The drain RTT is measured and
        reported; the un-subtracted total is also published so nothing
        is silently subtracted.

    ``partial`` is the survivability milestone callback: flat legacy
    keys flush to the caller the moment they exist.  Returns
    (wall_ms, dev_ms, host_ms, residual_fraction, rtt_ms, metrics).
    """
    import sys
    import jax
    import jax.numpy as jnp
    from ..crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    from ..ops.crush_fast import compile_fast_rule
    per_host = 20
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    hosts = []
    rng_w = np.random.default_rng(7)
    for h in range(n_osds // per_host):
        osds = list(range(h * per_host, (h + 1) * per_host))
        if uniform:
            ws = [0x10000] * per_host
        else:
            # heterogeneous drives: the exact64 draw path (u64 table
            # divide, zero residuals; f32+replay when a backend can't
            # lower u64), not the quotient tables
            ws = [int(v) * 0x8000
                  for v in rng_w.integers(1, 5, size=per_host)]
        hosts.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}",
                                   osds, ws, id=-(h + 2)))
    cw.set_max_devices(n_osds)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", hosts,
                  [0x10000 * per_host] * len(hosts), id=-1)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    xs = np.arange(n_pgs, dtype=np.uint32)
    w = np.full(n_osds, 0x10000, dtype=np.uint32)

    tmark = time.monotonic()

    def mark(label: str) -> None:
        nonlocal tmark
        if debug:
            now = time.monotonic()
            print(f"[crush-bench] {label}: {now - tmark:.1f}s",
                  file=sys.stderr)
            tmark = now

    def report(**kv) -> None:
        # milestone callback: the caller re-emits its JSON line, so a
        # watchdog kill later in the section cannot erase what this
        # section already measured.  *infix* keeps the uniform and
        # nonuniform sections' keys distinct.
        if partial is not None:
            partial({k.replace("@", infix): v for k, v in kv.items()})

    metrics = []

    # the native-host baseline first: pure C++, no tunnel exposure —
    # worst case the device phases die and the line still carries it
    host_ms = None
    try:
        from ..native import NativeCrushMapper, native_available
        if native_available():
            nm = NativeCrushMapper(cw.crush)
            w0 = [0x10000] * n_osds
            sample = 2000
            t0 = time.perf_counter()
            nm.do_rule_batch(rno, list(range(sample)), 3, w0)
            host_ms = (time.perf_counter() - t0) \
                * (n_pgs / sample) * 1000
            if uniform:
                report(crush_remap_native_host_ms=round(host_ms, 2))
    except Exception:
        pass
    mark("native host baseline")

    fr = compile_fast_rule(cw.crush, rno, 3)
    mark("compile_fast_rule (host tables)")
    fr.map_batch(xs, w)  # compile + candidate tables + warm (full fetch)
    mark("map_batch warm #1 (cand+resolve compiles)")
    wwarm = w.copy()
    wwarm[1] = 0
    fr.map_batch(xs, wwarm)  # warm the delta-path trace/compile too
    mark("map_batch warm #2 (delta compile)")
    # per-epoch wall time: one osd out per epoch.  map_batch's delta
    # path fetches only changed rows, so the wall is one resolve + one
    # small device->host transfer (OSDMapMapping's per-epoch job).
    flow_wall0 = g_devprof.snapshot()
    walls = []
    for e in range(epochs):
        w2 = w.copy()
        w2[(7 * e + 3) % n_osds] = 0
        t0 = time.perf_counter()
        fr.map_batch(xs, w2)
        walls.append((time.perf_counter() - t0) * 1000)
    from .stats import summarize
    wall_st = summarize(walls)
    wall_ms = wall_st["median"]
    devflow_wall = _devflow_since(flow_wall0, epochs)
    report(**{"crush_remap@_pgs": n_pgs,
              "crush_remap@_wall_ms": round(wall_ms, 2),
              "crush@_residual_fraction": fr.residual_fraction})
    mark("per-epoch wall loop")
    # device->host round-trip floor of this transport (tunnelled PJRT
    # pays ~100 ms here; local PCIe pays ~0) so wall_ms is interpretable
    rtt_s = measure_rtt()
    rtt_ms = rtt_s * 1000
    # sustained device resolve time: back-to-back dispatches drained by
    # fetching one element of the LAST output.  PJRT executes in
    # submission order, so that fetch completing means every dispatch
    # completed — block_until_ready alone is not trustworthy over a
    # tunnelled transport (it can acknowledge before remote completion).
    wds = []
    for e in range(epochs):
        w2 = w.copy()
        w2[(13 * e + 29) % n_osds] = 0
        wds.append(jnp.asarray(w2))
    np.asarray(fr.resolve_device(wds[0])[0][0, 0])   # warm + drain
    mark("resolve_device warm")
    pc = bench_perf_counters()
    flow_dev0 = g_devprof.snapshot()
    stage_dev0 = g_oplat.snapshot()
    t0 = time.perf_counter()
    outs = [fr.resolve_device(wd) for wd in wds]
    t_issued = time.perf_counter()
    np.asarray(outs[-1][0][0, 0])
    t_end = time.perf_counter()
    total = (t_end - t0) * 1000
    devflow_dev = _devflow_since(flow_dev0, epochs)
    # stage split of the sustained region: back-to-back dispatch
    # (device_call) vs the one-element drain fetch (d2h)
    g_oplat.record("bench", "device_call", (t_issued - t0) * 1e6)
    g_oplat.record("bench", "d2h", (t_end - t_issued) * 1e6)
    stage_bd_dev = _stage_breakdown_since(stage_dev0, t_end - t0,
                                          epochs)
    pc.inc(l_bench_dispatches, len(wds))
    pc.inc(l_bench_fences)
    pc.tinc(l_bench_fence_time, total / 1000.0)
    mark("sustained resolve loop")
    # The fenced total includes exactly one drain round trip.  Publish
    # BOTH the raw per-epoch figure and the RTT (never silently
    # subtract); the rtt-corrected figure is derived and floored at
    # one dispatch's worth so "fast" can never read as "didn't run".
    dev_ms_raw = total / len(wds)
    dev_ms = max((total - rtt_ms), 0.0) / len(wds)
    if round(dev_ms * 1000.0, 2) <= 0.0:
        # resolves faster than one round trip: the subtraction is all
        # noise — fall back to the honest upper bound
        dev_ms = dev_ms_raw
    kv = {"crush_remap@_us": round(dev_ms * 1000.0, 2),
          "crush_remap@_us_raw": round(dev_ms_raw * 1000.0, 2)}
    if uniform:
        kv["transport_rtt_ms"] = round(rtt_ms, 2)
    report(**kv)
    name_sfx = infix or ""
    try:
        metrics.append(make_metric(
            f"crush_remap{name_sfx}_device", dev_ms, "ms", fenced=True,
            rtt_s=rtt_s,
            stats={"n": len(wds), "median": dev_ms, "iqr": 0.0,
                   "min": dev_ms, "max": dev_ms_raw},
            extra={"pgs": n_pgs, "n_osds": n_osds,
                   "raw_ms": round(dev_ms_raw, 4),
                   "devflow": devflow_dev,
                   "stage_breakdown": stage_bd_dev}))
        metrics.append(make_metric(
            f"crush_remap{name_sfx}_wall", wall_ms, "ms", fenced=True,
            rtt_s=rtt_s, stats=wall_st,
            extra={"pgs": n_pgs, "n_osds": n_osds,
                   "devflow": devflow_wall}))
    except Exception as e:
        # schema refused the reading (e.g. exact 0.0) — the flat keys
        # above still carry the raw evidence; note the refusal
        report(**{f"crush_remap{name_sfx}_schema_error": repr(e)})
    return wall_ms, dev_ms, host_ms, fr.residual_fraction, rtt_ms, metrics


def measure_slo_autotune(*, mesh_chips: int = 8, slow_chip: int = 5,
                         delay_us: int = 30_000,
                         tick_budget: int = 80,
                         seed: int = 20260807,
                         name: str = "slo_autotune") -> Dict[str, Any]:
    """The closed-loop control-plane workload (docs/CONTROL.md): run
    the policy map's three scenarios — abusive client, recovery storm
    under an SLO burn, straggling chip — on real mini clusters with
    the mgr controller ENABLED and nothing else touching the knobs,
    and record the actuation receipts bench/regress.py's CONTROL GATE
    pins as absolute invariants:

    - each scenario RAISES its SLO/health pressure, the controller
      moves the responsible knob, and the episode CLEARS (knobs back
      at baseline) within *tick_budget* mgr ticks of the pressure
      ending — zero operator action;
    - every move in every ledger stays inside its knob's
      floor/ceiling;
    - a disabled-controller twin of the abusive-client leg makes ZERO
      moves (observe-only mgr by construction);
    - client ops stay byte-exact throughout (the control plane must
      never touch the data path).

    The metric value is the worst (largest) convergence tick count
    across the three scenarios — lower is a snappier control plane,
    and the CONTROL GATE's budget is the hard wall.
    """
    from ..cluster import MiniCluster
    from ..common.config import g_conf
    from ..dispatch import g_dispatcher
    from ..ec.tpu_plugin import ErasureCodeTpu
    from ..fault import g_faults
    from ..load import TrafficSpec, run_traffic
    from ..mesh import g_chipstat, g_mesh
    from ..osd.ecutil import encode as eu_encode, stripe_info_t

    saved = {opt: g_conf.values.get(opt) for opt in
             ("mgr_control_enable", "mgr_control_cooldown_ticks",
              "mgr_control_bounds", "mgr_slo_admission_rate_max",
              "mgr_slo_oplat_p99_usec", "mgr_slo_fast_window_s",
              "mgr_slo_slow_window_s", "mgr_telemetry_retention",
              "osd_op_queue_admission_max",
              "osd_mclock_client_overrides",
              "osd_mclock_class_overrides", "osd_recovery_max_active",
              "ec_mesh_chips", "ec_mesh_rateless",
              "ec_mesh_rateless_tasks", "ec_mesh_skew_sample_every",
              "ec_mesh_skew_threshold", "ec_dispatch_batch_max",
              "ec_dispatch_batch_window_us")}
    t_wall0 = time.perf_counter()
    flow0 = g_devprof.snapshot()
    stage0 = g_oplat.snapshot()
    byte_exact = True
    receipts: list = []
    incident_blocks: Dict[str, Any] = {}

    def _leg_incidents(leg: str, cluster) -> None:
        # each leg's cluster is discarded on return, so the incident
        # receipt is harvested here; the health raise auto-captures,
        # and the operator fallback only fires if it never raised
        inc_mgr = cluster.mgr.incident
        if inc_mgr.captures_total == 0:
            inc_mgr.capture("operator", f"{leg} leg fallback capture",
                            reason="operator")
        incident_blocks[leg] = inc_mgr.receipt()

    def _slo_windows() -> None:
        g_conf.set_val("mgr_slo_fast_window_s", 6.0)
        g_conf.set_val("mgr_slo_slow_window_s", 12.0)
        g_conf.set_val("mgr_telemetry_retention", 10_000)

    def _in_bounds(ctl) -> bool:
        # pressure-driven moves must land inside [floor, ceiling];
        # restore/teardown moves walk back to the OPERATOR baseline,
        # which may legitimately sit outside the actuation corridor
        # (e.g. cap 0 = uncapped) — their invariant is "cleared"
        knobs = ctl.dump()["knobs"]
        return all(knobs[e["knob"]]["floor"] <= e["to"]
                   <= knobs[e["knob"]]["ceiling"]
                   for e in ctl._ledger
                   if e["reflex"] not in ("restore", "teardown"))

    def _abusive_run(cluster, ops_per_client=96):
        spec = TrafficSpec(pool="abuse", n_clients=4,
                           ops_per_client=ops_per_client,
                           read_fraction=0.25,
                           mode="open", rate=10.0,
                           rate_multipliers=(6.0, 1.0, 1.0, 1.0),
                           tick_every=1, seed=seed,
                           keep_completions=False)
        return run_traffic(cluster, spec)

    def leg_disabled_twin() -> int:
        """The abusive-client drive with the controller OFF: the mgr
        must be observe-only by construction — zero moves."""
        nonlocal byte_exact
        cluster = MiniCluster(n_osds=4)
        cluster.create_replicated_pool("abuse", size=2, pg_num=8)
        _slo_windows()
        g_conf.set_val("mgr_slo_admission_rate_max", 0.001)
        g_conf.set_val("osd_op_queue_admission_max", 4)
        res = _abusive_run(cluster, ops_per_client=48)
        byte_exact &= bool(res.byte_exact)
        for _ in range(8):
            cluster.tick(dt=1.0)
        return cluster.mgr.control.moves_total

    def leg_admission() -> Dict[str, Any]:
        nonlocal byte_exact
        cluster = MiniCluster(n_osds=4)
        cluster.create_replicated_pool("abuse", size=2, pg_num=8)
        g_conf.set_val("mgr_control_enable", True)
        g_conf.set_val("mgr_control_cooldown_ticks", 1)
        _slo_windows()
        g_conf.set_val("mgr_slo_admission_rate_max", 0.001)
        g_conf.set_val("osd_op_queue_admission_max", 4)
        res = _abusive_run(cluster)
        byte_exact &= bool(res.byte_exact)
        ctl = cluster.mgr.control
        tightens = [e for e in ctl._ledger
                    if e["reflex"] == "admission"]
        converge = -1
        for i in range(tick_budget):
            cluster.tick(dt=1.0)
            if "TPU_SLO_ADMISSION" not in cluster.mgr.health_checks \
                    and all(k["baseline"] is None for k in
                            ctl.dump()["knobs"].values()):
                converge = i + 1
                break
        receipts.extend(list(ctl._ledger)[-6:])
        _leg_incidents("admission", cluster)
        return {"raised": bool(tightens),
                "moves": ctl.moves_total,
                "abuser_correct": all("client.abuse.0" in e["reason"]
                                      for e in tightens),
                "cleared": converge >= 0,
                "converge_ticks": converge,
                "in_bounds": _in_bounds(ctl)}

    def leg_recovery() -> Dict[str, Any]:
        nonlocal byte_exact
        # k8m4/d10 mirrors measure_recovery_storm so the smoke tier
        # reuses its compiled encode/decode shapes
        cluster = MiniCluster(n_osds=14)
        cluster.create_ec_pool("rstorm", k=8, m=4, pg_num=4,
                               plugin="regenerating",
                               extra_profile={"d": "10"})
        cl = cluster.client("client.rstorm")
        rng = np.random.default_rng(seed)
        bodies = {}
        for i in range(10):
            body = rng.integers(0, 256, 4096,
                                dtype=np.uint8).tobytes()
            bodies[f"o{i}"] = body
            assert cl.write_full("rstorm", f"o{i}", body) == 0
        g_conf.set_val("mgr_control_enable", True)
        g_conf.set_val("mgr_control_cooldown_ticks", 1)
        _slo_windows()
        g_conf.set_val("mgr_slo_oplat_p99_usec", "reply:1")
        base_active = int(g_conf.get_val("osd_recovery_max_active"))
        ctl = cluster.mgr.control
        # phase 1: the burn sustains under client IO, no storm yet
        for i in range(6):
            cl.write_full("rstorm", f"pre{i}", b"x" * 4096)
            cluster.tick(dt=1.0)
        raised = "TPU_SLO_OPLAT" in cluster.mgr.health_checks
        quiet_moves = ctl.moves_total        # burn alone: no move
        # phase 2: an OSD dies mid-burn -> the storm
        pid = cluster.mon.osdmap.lookup_pg_pool_name("rstorm")
        victim = next(pg.acting[-1]
                      for pgid, pg in cluster.primary_pgs()
                      if pgid[0] == pid and pg.backend is not None)
        cluster.kill_osd(victim)
        cluster.mark_osd_down(victim)
        cluster.mark_osd_out(victim)
        for i in range(8):
            cl.write_full("rstorm", f"live{i}", b"x" * 4096)
            cluster.tick(dt=1.0)
        storm_moves = [e for e in ctl._ledger
                       if e["reflex"] == "recovery"]
        # phase 3: quiesce -> the burn clears -> restore to baseline
        converge = -1
        for i in range(tick_budget):
            cluster.tick(dt=1.0)
            if "TPU_SLO_OPLAT" not in cluster.mgr.health_checks \
                    and int(g_conf.get_val("osd_recovery_max_active")) \
                    == base_active:
                converge = i + 1
                break
        for oid, body in bodies.items():
            byte_exact &= cl.read("rstorm", oid) == body
        receipts.extend(list(ctl._ledger)[-6:])
        _leg_incidents("recovery", cluster)
        return {"raised": raised,
                "moves": ctl.moves_total,
                "quiet_moves_before_storm": quiet_moves,
                "storm_moves": len(storm_moves),
                "cleared": converge >= 0,
                "converge_ticks": converge,
                "in_bounds": _in_bounds(ctl)}

    def leg_straggler() -> Dict[str, Any]:
        nonlocal byte_exact
        g_conf.set_val("ec_mesh_chips", mesh_chips)
        g_conf.set_val("ec_dispatch_batch_window_us", 10**7)
        g_conf.set_val("ec_dispatch_batch_max", 64)
        g_conf.set_val("ec_mesh_skew_sample_every", 1)
        g_conf.set_val("ec_mesh_skew_threshold", 3.0)
        g_conf.set_val("ec_mesh_rateless", True)
        g_conf.rm_val("ec_mesh_rateless_tasks")
        cluster = MiniCluster(n_osds=4)
        g_conf.set_val("mgr_control_enable", True)
        g_conf.set_val("mgr_control_cooldown_ticks", 1)
        g_conf.set_val("mgr_control_bounds",
                       f"ec_mesh_rateless_tasks:"
                       f"{mesh_chips + 1}:{mesh_chips + 4}")
        # k4m2 x 3-request x 2-stripe x 1KiB chunks mirrors
        # measure_mesh_skew so the smoke tier reuses its compiles
        impl = ErasureCodeTpu()
        impl.init({"k": "4", "m": "2", "technique": "reed_sol_van"})
        sinfo = stripe_info_t(4, 4 * 1024)
        want = set(range(6))
        rng = np.random.default_rng(seed)

        def flush() -> None:
            nonlocal byte_exact
            payloads = [rng.integers(0, 256, size=2 * 4 * 1024,
                                     dtype=np.uint8)
                        for _ in range(3)]
            oracles = [eu_encode(sinfo, impl, p, want)
                       for p in payloads]
            futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                    for p in payloads]
            g_dispatcher.flush()
            for f, oracle in zip(futs, oracles):
                res = f.result()
                byte_exact &= sorted(res) == sorted(oracle) and all(
                    np.asarray(res[i]).tobytes()
                    == np.asarray(oracle[i]).tobytes()
                    for i in oracle)

        flush()                            # compile warmup
        g_chipstat.reset()
        mesh_size = g_mesh.topology().size
        auto_width = mesh_size + 2
        ctl = cluster.mgr.control
        g_faults.inject("mesh.chip_slowdown", mode="always",
                        match=f"chip={slow_chip}/", delay_us=delay_us)
        widened_at, raised = -1, False
        try:
            for i in range(16):
                flush()
                cluster.tick(dt=1.0)
                raised |= "TPU_MESH_SKEW" in cluster.mgr.health_checks
                if int(g_conf.get_val("ec_mesh_rateless_tasks")
                       or 0) > auto_width:
                    widened_at = i + 1
                    break
        finally:
            g_faults.clear("mesh.chip_slowdown")
        peak = int(g_conf.get_val("ec_mesh_rateless_tasks") or 0)
        converge = -1
        for i in range(tick_budget):
            flush()
            cluster.tick(dt=1.0)
            width = int(g_conf.get_val("ec_mesh_rateless_tasks") or 0)
            peak = max(peak, width)
            if "TPU_MESH_SKEW" not in cluster.mgr.health_checks \
                    and width < peak:
                converge = i + 1
                break
        widths_ok = all(
            mesh_size + 1 <= e["to"] <= 2 * mesh_size
            for e in ctl._ledger
            if e["knob"] == "ec_mesh_rateless_tasks")
        receipts.extend(list(ctl._ledger)[-6:])
        _leg_incidents("straggler", cluster)
        return {"raised": raised,
                "moves": ctl.moves_total,
                "widen_ticks": widened_at,
                "peak_width": peak,
                "cleared": converge >= 0,
                "converge_ticks": converge,
                "in_bounds": _in_bounds(ctl) and widths_ok}

    try:
        disabled_moves = leg_disabled_twin()
        admission = leg_admission()
        recovery = leg_recovery()
        straggler = leg_straggler()
    finally:
        g_faults.clear()
        for opt, v in saved.items():
            g_conf.rm_val(opt) if v is None else g_conf.set_val(opt, v)
        g_dispatcher.flush()
        g_mesh.topology()
        g_chipstat.reset()
    wall_s = round(max(time.perf_counter() - t_wall0, 1e-3), 3)
    worst = max(admission["converge_ticks"],
                recovery["converge_ticks"],
                straggler["converge_ticks"])
    v = float(worst if worst > 0 else tick_budget + 1)
    return make_metric(
        name, v, "ticks", fenced=True,
        stats={"n": 1, "median": v, "iqr": 0.0, "min": v, "max": v},
        roofline={"verdict": "unknown", "suspect": False},
        extra={
            "control": {
                "disabled_moves": disabled_moves,
                "byte_exact": byte_exact,
                "tick_budget": tick_budget,
                "scenarios": {"admission": admission,
                              "recovery": recovery,
                              "straggler": straggler},
            },
            "incidents": incident_blocks,
            "receipts": receipts[-18:],
            "devflow": _devflow_since(flow0, max(len(receipts), 1)),
            "stage_breakdown": _stage_breakdown_since(
                stage0, wall_s, max(len(receipts), 1)),
            "wall_s": wall_s,
        })


def measure_composed_chaos(*, seeds: Tuple[int, ...] = (24, 103),
                           name: str = "composed_chaos"
                           ) -> Dict[str, Any]:
    """The composed-chaos workload (ceph_tpu/chaos, docs/CHAOS.md):
    execute one seeded multi-fault storyline per entry in *seeds* on a
    fresh ticking MiniCluster under open-loop harness traffic, and
    record every receipt for bench/regress.py's CHAOS GATE, which pins
    the universal acceptance as absolute invariants:

    - every client op and every dispatcher oracle stays byte-exact
      through the whole storyline;
    - every health check the storyline promises — and every collateral
      raise — both RAISES and CLEARS with zero operator action;
    - every raise leaves a FINALIZED incident bundle whose gseq-ordered
      timeline tells the injected storyline back (or a journaled
      capture drop when losing the capture was itself the leg);
    - zero wedges (no storyline exhausts its settle budget) and zero
      mesh single-device fallbacks.

    The metric value is aggregate completed client ops/s across the
    seeds — a throughput floor for the whole chaos machinery, with the
    invariants carried in the ``chaos`` block.
    """
    from ..chaos import compose_scenario, run_scenario

    t0 = time.perf_counter()
    flow0 = g_devprof.snapshot()
    stage0 = g_oplat.snapshot()
    receipts = []
    total_ops = 0
    for seed in seeds:
        r = run_scenario(compose_scenario(int(seed)))
        receipts.append(r)
        total_ops += int(r["ops_completed"])
    wall_s = round(max(time.perf_counter() - t0, 1e-3), 3)
    v = round(total_ops / wall_s, 2)
    return make_metric(
        name, v, "ops/s", fenced=True,
        stats={"n": len(receipts), "median": v, "iqr": 0.0,
               "min": v, "max": v},
        roofline={"verdict": "unknown", "suspect": False},
        extra={
            "chaos": {
                "seeds": [int(s) for s in seeds],
                "accepted": all(r["accepted"] for r in receipts),
                "receipts": receipts,
            },
            "devflow": _devflow_since(flow0, max(total_ops, 1)),
            "stage_breakdown": _stage_breakdown_since(
                stage0, wall_s, max(total_ops, 1)),
            "wall_s": wall_s,
        })
