"""Completion-fenced timing: the clock stops only after outputs exist
on the host.

Why ``block_until_ready`` is not enough here: over a tunnelled PJRT
transport the ready acknowledgement can arrive before remote execution
completes, so a loop that fences each step with ``block_until_ready``
still measures dispatch rate, not compute (bench.py's crush section
documented this in round 4; round 5's verdict proved the encode numbers
it produced were physically impossible).  The only fence this transport
honors is a device→host readback: PJRT executes in submission order, so
fetching one element of the LAST output means every dispatch before it
completed on the device.

Accounting contract: the fenced elapsed time INCLUDES one transport
round trip (the drain fetch).  That RTT is measured separately and
reported alongside — never silently subtracted — so a reader can bound
the pure-compute time as ``elapsed - rtt <= compute <= elapsed`` and
the number stays honest on both a 70 ms tunnel and a microsecond PCIe
link.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import numpy as np


class FencedTiming:
    """One fenced measurement: N steps dispatched back-to-back, drained,
    timed as a unit."""

    __slots__ = ("elapsed_s", "n_steps", "rtt_s", "fenced")

    def __init__(self, elapsed_s: float, n_steps: int, rtt_s: float):
        self.elapsed_s = elapsed_s
        self.n_steps = n_steps
        self.rtt_s = rtt_s
        self.fenced = True

    @property
    def per_step_s(self) -> float:
        return self.elapsed_s / max(self.n_steps, 1)

    def throughput(self, bytes_per_step: int) -> float:
        """GiB/s of payload through the timed region (fence included)."""
        return self.n_steps * bytes_per_step / self.elapsed_s / (1 << 30)

    def to_dict(self) -> Dict[str, Any]:
        return {"elapsed_s": self.elapsed_s, "n_steps": self.n_steps,
                "rtt_s": self.rtt_s, "fenced": True}


def drain(out: Any) -> None:
    """Materialize *out* on the host — the completion fence.

    Order matters: ``block_until_ready`` first (cheap, and on a local
    backend it is the whole fence), then a one-element host fetch, which
    is the only signal a tunnelled transport cannot fake.  Works on any
    object exposing the jax Array protocol or plain ``__array__`` —
    including test doubles that delay materialization.
    """
    bur = getattr(out, "block_until_ready", None)
    if bur is not None:
        bur()
    # One-ELEMENT readback, not the full array: a large device→host
    # fetch over the tunnelled transport flips it into sync-dispatch
    # mode and poisons every later measurement in the process (measured
    # 137 us -> 81 ms per dispatch after one 16 MB fetch).  The slice
    # dispatch is submitted after the timed work, so its completion
    # implies everything before it completed.
    try:
        one = out.ravel()[:1]
    except Exception:
        one = out
    arr = np.asarray(one)
    if arr.size:
        arr.ravel()[:1].copy()
    # the fence IS a device->host readback: account it like any other
    # transfer so the devflow ledger never hides the drain's own copy
    from ..trace.devprof import g_devprof
    g_devprof.account_d2h("bench.drain", arr.nbytes)


def measure_rtt(make_tiny: Optional[Callable[[], Any]] = None,
                repeats: int = 3) -> float:
    """Median device→host round trip (seconds) for a tiny transfer.

    This is the fence's own cost: ~100 ms over the axon tunnel, ~0 on
    locally attached hardware.  Reported next to every fenced elapsed
    time so the reading is interpretable on both.
    """
    if make_tiny is None:
        import jax
        import jax.numpy as jnp

        def make_tiny():
            t = jnp.zeros((8,), jnp.int32) + jnp.int32(1)
            jax.block_until_ready(t)
            return t

    samples = []
    for _ in range(max(repeats, 1)):
        tiny = make_tiny()
        t0 = time.perf_counter()
        np.asarray(tiny)
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


def fenced_time(step: Callable[[int], Any], n_steps: int,
                rtt_s: Optional[float] = None,
                kernel_name: Optional[str] = None,
                drain_fn: Optional[Callable[[Any], Any]] = None
                ) -> FencedTiming:
    """Dispatch ``step(i)`` for i in [0, n_steps) back-to-back, fence on
    the LAST output, and time the whole region.

    ``drain_fn`` overrides the fence for outputs whose completion
    contract needs more than the single-element drain — a mesh-sharded
    output is only proven complete by a readback from EVERY shard's
    device (``parallel.ec.drain_sharded``); the default ``drain`` is
    the single-device contract.

    ``step`` must return the dispatch's output (device array or pytree
    leaf).  Only the LAST output is retained: a submitted PJRT dispatch
    executes whether or not its output handle is kept (dropping the
    handle frees the buffer after execution, it does not cancel it), so
    retention would buy nothing — and holding all N outputs at the
    calibrated step count can pin gigabytes of HBM and OOM a real-chip
    run.  The caller salts the step input by ``i`` so no transport/XLA
    layer can serve a repeat from cache.
    """
    if n_steps < 1:
        raise ValueError("n_steps must be >= 1")
    if rtt_s is None:
        rtt_s = measure_rtt()
    from ..trace import g_perf_histograms, g_tracer, latency_axes
    span = g_tracer.begin(
        f"bench_fence:{kernel_name or 'fenced'}") if g_tracer.enabled \
        else None
    last: Any = None
    t0 = time.perf_counter()
    with g_tracer.activate(span):
        for i in range(n_steps):
            last = step(i)
        t_issued = time.perf_counter()
        drain_span = g_tracer.begin("drain") if span is not None else None
        (drain_fn or drain)(last)
        g_tracer.finish(drain_span)
    elapsed = time.perf_counter() - t0
    g_tracer.finish(span)
    # stage-latency ledger (trace/oplat.py): a fenced region decomposes
    # into the back-to-back dispatch loop (device_call) and the drain
    # fetch that completes it (d2h) — the two stamps sum to the fenced
    # elapsed exactly, so every fenced workload's stage_breakdown
    # reconciles with its wall by construction
    from ..trace.oplat import g_oplat
    g_oplat.record("bench", "device_call", (t_issued - t0) * 1e6)
    g_oplat.record("bench", "d2h", (t0 + elapsed - t_issued) * 1e6)
    timing = FencedTiming(elapsed, n_steps, rtt_s)
    # per-step latency lands in the always-on bench histogram so
    # `python -m ceph_tpu.bench` metric lines carry the distribution
    g_perf_histograms.get("bench", "fenced_step_latency_histogram",
                          latency_axes).inc(
        elapsed / n_steps * 1e6)
    if kernel_name:
        from ..common.kernel_trace import g_kernel_timer
        if g_kernel_timer.enabled:
            g_kernel_timer._record(kernel_name, elapsed)
    return timing
