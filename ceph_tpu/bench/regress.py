"""Perf-regression gate over the per-round bench trajectory.

The driver archives one ``BENCH_r<N>.json`` per round: ``{"n": round,
"rc": ..., "parsed": <last JSON line bench.py printed>}``.  Since this
subsystem landed, that line embeds a ``metrics`` list of schema records
(schema.py).  The comparator walks the trajectory newest-first, finds
the most recent round with a comparable reading (same metric name, same
platform, fenced, not suspect), and flags a regression when the current
reading moved beyond tolerance in the bad direction — lower for
throughputs, higher for times.

Legacy rounds (r01-r05) predate the schema and carry only flat unfenced
keys; they are never used as a gate baseline (an unfenced dispatch-rate
number would make every honest fenced number look like a regression).

Configurable fail/warn: CI runs ``python -m ceph_tpu.bench --smoke
--gate warn`` (a shared-tunnel wobble should not break the build);
``--gate fail`` exits non-zero for release gating.
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional

DEFAULT_TOLERANCE = 0.30   # shared-tunnel runs wobble ~25% run-to-run

# units where a larger value is better; any other unit is lower-better
_HIGHER_BETTER_UNITS = {"GiB/s", "MiB/s", "ops/s"}

# the copy-budget gate (devprof PR): every fenced workload's devflow
# block carries these per-op flow figures; both are lower-better and
# gated alongside the workload's primary value, so a zero-copy refactor
# must move a number CI watches — and a copy regression fails the gate
# like a latency regression.  Unlike wall times these are deterministic
# counts, so the gate uses a tighter tolerance than the timing wobble.
#
# Floors: a device-resident workload's only accounted flow is the
# fence drain — copies_per_op ~ 1/n_steps where n_steps is calibrated
# from a timed probe, so the figure jitters with the same run-to-run
# wobble the timing tolerance exists for.  Values below the floors are
# sub-op-level noise, not a per-op copy chain: both sides under floor
# gates nothing, and "zero-copy baseline" means "under floor", so a
# regression fires only when a real per-op copy appears.
_DEVFLOW_GATED = (("copies_per_op", "copies/op"),
                  ("bytes_per_op", "B/op"))
DEVFLOW_TOLERANCE = 0.10
DEVFLOW_FLOORS = {"copies_per_op": 0.25, "bytes_per_op": 512.0}

# the stage-budget gate (oplat PR): every fenced workload's
# stage_breakdown carries per-stage usec_per_op figures; each is
# lower-better and gated alongside the workload's primary value, so
# the mesh-sharded dispatch and zero-copy refactors must move a
# CI-watched stage number instead of a prose claim.  Stage times are
# wall-clock (not deterministic counts like the copy budget), so the
# tolerance is looser than both the timing and copy gates; the per-op
# floor keeps microsecond-scale stages — scheduling jitter, not a
# budget — from gating anything.  A stage CROSSING the floor from a
# sub-floor baseline is a regression (a new time sink appeared), the
# mirror of the copy gate's zero-copy-baseline rule.
STAGE_TOLERANCE = 0.50
STAGE_FLOOR_USEC_PER_OP = 50.0

# the recovery gate (recovery-storm PR): the ec_recovery_storm
# workload's `recovery` block carries bytes-moved-per-repaired-shard
# per codec family plus the regen/RS ratio; all three are lower-better
# counter-delta figures (deterministic for a fixed object set, like
# the copy budget) gated at the tight tolerance.  Floors: a run that
# repaired nothing reports 0 — below-floor readings gate nothing.
_RECOVERY_GATED = (("bytes_per_repaired_shard_regen", "B/shard", 64.0),
                   ("bytes_per_repaired_shard_rs", "B/shard", 64.0),
                   ("regen_vs_rs_ratio", "ratio", 0.01))
RECOVERY_TOLERANCE = 0.10

# the SKEW GATE (per-chip timing PR): the ec_mesh_skew workload's
# `skew` block records what the chip-health scoreboard saw with one
# chip slowed 10x vs a healthy twin.  Unlike the other gates this one
# is ABSOLUTE (invariants of the ruler itself, no baseline needed):
# detection must fire within SKEW_MAX_DETECTION_PROBES probes, on
# EXACTLY the slowed chip, the TPU_MESH_SKEW health check must raise
# during the run and clear after the fault is removed, and the healthy
# twin must stay quiet — a false suspect is a gate failure, because a
# ruler that cries wolf is worse than no ruler.
SKEW_MAX_DETECTION_PROBES = 8

# the STRAGGLER GATE (rateless coded mesh encode PR; extended to the
# READ path by the meshed-decode PR): every fenced workload carrying a
# `straggler` block is judged by the same absolute invariants —
# ec_mesh_straggler A/Bs the rateless ENCODE path healthy vs
# one-chip-slowed-10x, ec_degraded_read drives the meshed rateless
# DECODE path (shard killed under open-loop traffic, every read a
# survivor-sharded reconstruct) through the identical twin protocol.
# Absolute invariants like the SKEW GATE — the fix either holds or it
# does not:
# - the scoreboard must detect the slowed chip within the probe window
#   and report a nonzero skew ratio (the injected-degradation receipt:
#   a quiet run proves nothing);
# - protected cluster_rollup device_call p999 must stay within ONE
#   log2 bucket of the healthy twin (ratio <= 2.0 on edge-quantized
#   percentiles; measured 1.0 on CPU smoke — the unprotected twin
#   sits ~8 buckets up) AND the exact wall-clock p999 ratio within
#   1.5 (measured 0.9-1.0; the margin absorbs shared-core smoke
#   wobble, the unprotected twin measures 6-7x);
# - every op byte-identical to the unprotected oracle (subset
#   completion + host re-solves invisible in the bytes);
# - zero single-device fallbacks (completion must come from the
#   surviving subset, not the degradation ladder — on the read side a
#   fallback is a `mesh_decode_fallbacks` tick) and at least one
#   subset completion (the protection actually engaged);
# - the healthy twin pays < 2x coded-bandwidth overhead and marks no
#   false suspects.
STRAGGLER_MAX_DETECTION_PROBES = 8
STRAGGLER_MAX_P999_RATIO = 2.0
STRAGGLER_MAX_WALL_P999_RATIO = 1.5
STRAGGLER_MAX_BANDWIDTH_OVERHEAD = 2.0

# the CONTROL GATE (self-tuning control plane PR, docs/CONTROL.md):
# the slo_autotune workload's `control` block records the three
# closed-loop scenarios (abusive client, recovery storm under an SLO
# burn, straggling chip) run on real clusters with the mgr controller
# enabled.  Absolute invariants, baseline or not:
# - every scenario RAISED its pressure, the controller MOVED, and the
#   episode CLEARED back to baseline within the workload's tick
#   budget (zero operator action is the whole point);
# - every pressure-driven move landed inside its knob's
#   floor/ceiling corridor;
# - the disabled-controller twin made ZERO moves (an off controller
#   is observe-only by construction — mgr_control_enable gates every
#   actuation);
# - client ops stayed byte-exact throughout (the control plane never
#   touches the data path).

# the ZERO-COPY gate (device-resident shard store PR,
# docs/DISPATCH.md "Zero-copy write path"): the ec_write_zero_copy
# workload's `zero_copy` block A/Bs the resident write path
# (os_memstore_device_bytes_max large — fused encode+crc, shard bodies
# stay in HBM) against the bytes twin (budget 0).  Absolute
# invariants, baseline or not:
# - the resident leg's write-region d2h stays under the devflow floor
#   (the only fetch is the crc scalar — a shard body crossing back is
#   a regression of the whole point);
# - resident copies_per_op STRICTLY below the bytes twin's (the
#   deleted pack/slice/message copies must show up in the ledger);
# - residency actually engaged (DeviceShard handles live in the store
#   when the write region closes — a 0 here means the fused path
#   silently degraded and the A/B measured nothing);
# - read-backs byte-exact on both legs (lazy materialization is
#   invisible in the bytes).
ZERO_COPY_MAX_D2H_BYTES_PER_OP = 512.0

# the CHAOS GATE (composed-chaos scenario engine PR, docs/CHAOS.md):
# the composed_chaos workload's `chaos` block carries one receipt per
# pinned storyline seed — the engine's own universal-acceptance
# judgment, re-pinned here as absolute invariants so a bench round can
# never ship a storyline regression as a mere throughput wobble:
# - every receipt ACCEPTED (the engine's conjunction of the below);
# - every op byte-exact through the whole storyline (client replies
#   and dispatcher oracles both);
# - zero wedges (no storyline exhausted its settle budget);
# - every expected health check raised AND cleared with a finalized
#   incident bundle whose gseq timeline tells the storyline back, and
#   every collateral raise resolved the same way;
# - zero mesh single-device fallbacks (composed faults must be
#   absorbed by the coded path, never the degradation ladder).


def load_trajectory(root: str) -> List[Dict[str, Any]]:
    """All parseable BENCH_r*.json records under *root*, oldest first.

    Each item: {"round": N, "path": ..., "parsed": <dict or None>}.
    Unreadable or rc-failed rounds still appear (with parsed=None) so
    the gate can report how far back the baseline is.
    """
    out: List[Dict[str, Any]] = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        rec: Dict[str, Any] = {"round": int(m.group(1)), "path": path,
                               "parsed": None}
        try:
            with open(path) as f:
                data = json.load(f)
            parsed = data.get("parsed")
            if isinstance(parsed, dict):
                rec["parsed"] = parsed
        except Exception:
            pass
        out.append(rec)
    out.sort(key=lambda r: r["round"])
    return out


def _fenced_metrics(parsed: Optional[Dict[str, Any]]
                    ) -> Dict[str, Dict[str, Any]]:
    """name -> schema metric for every gate-eligible reading in one
    round's parsed line: fenced, not suspect, schema-carrying."""
    if not parsed:
        return {}
    out: Dict[str, Dict[str, Any]] = {}
    for m in parsed.get("metrics", []) or []:
        if not isinstance(m, dict) or not m.get("fenced"):
            continue
        if m.get("suspect"):
            continue            # a broken-fence reading gates nothing
        name = m.get("name")
        if isinstance(name, str) and name:
            out[name] = m
    return out


def _gate_lower_better(name: str, unit: str, cv: float, bv: float,
                       floor: float, tolerance: float,
                       baseline_round, regressions: List,
                       improvements: List) -> bool:
    """The one lower-better floor/tolerance rule both per-op gates
    (copy budget, stage budget) apply, so the semantics cannot drift:
    a sub-floor baseline is sacred — crossing the floor is a
    regression with no ratio to report, sub-floor drift gates nothing;
    over the floor, movement beyond *tolerance* classifies as
    regression/improvement, and dropping under the floor is always an
    improvement.  Returns True when the pair was actually compared."""
    if bv < floor:
        if cv >= floor:
            regressions.append({
                "name": name, "unit": unit, "value": cv,
                "baseline": bv, "baseline_round": baseline_round,
                "change": None})
            return True
        return False
    change = (cv - bv) / bv
    entry = {"name": name, "unit": unit, "value": cv, "baseline": bv,
             "baseline_round": baseline_round,
             "change": round(change, 4)}
    if cv < floor:
        improvements.append(entry)          # dropped under floor
    elif change > tolerance:
        regressions.append(entry)
    elif change < -tolerance:
        improvements.append(entry)
    return True


def compare_against_trajectory(
        current: List[Dict[str, Any]], trajectory: List[Dict[str, Any]],
        platform: str, tolerance: float = DEFAULT_TOLERANCE
) -> Dict[str, Any]:
    """Gate the *current* schema metrics against the newest comparable
    round per metric.

    Returns {"regressions": [...], "improvements": [...], "compared": N,
    "no_baseline": [names...]}.  A regression entry carries the metric
    name, both values, the baseline round, and the relative change.
    Caller decides warn-vs-fail.
    """
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []
    no_baseline: List[str] = []
    compared = 0           # metrics with a value baseline
    devflow_compared = 0   # devflow keys with a gated baseline
    stage_compared = 0     # stage usec/op figures with a gated baseline
    recovery_compared = 0  # recovery storm figures with a baseline
    skew_compared = 0      # skew blocks checked (absolute gate)
    straggler_compared = 0  # straggler blocks checked (absolute gate)
    control_compared = 0   # control blocks checked (absolute gate)
    chaos_compared = 0     # chaos blocks checked (absolute gate)
    zero_copy_compared = 0  # zero_copy blocks checked (absolute gate)
    for cur in current:
        if not cur.get("fenced") or cur.get("suspect"):
            continue
        name = cur["name"]
        # ---- SKEW GATE: absolute invariants, runs baseline or not ------
        sk = cur.get("skew")
        if isinstance(sk, dict):
            skew_compared += 1
            regressions.extend(_skew_gate(name, sk))
        # ---- STRAGGLER GATE: absolute invariants, baseline or not ------
        st = cur.get("straggler")
        if isinstance(st, dict):
            straggler_compared += 1
            regressions.extend(_straggler_gate(name, st))
        # ---- CONTROL GATE: absolute invariants, baseline or not --------
        ct = cur.get("control")
        if isinstance(ct, dict):
            control_compared += 1
            regressions.extend(_control_gate(name, ct))
        # ---- CHAOS GATE: absolute invariants, baseline or not ----------
        ch = cur.get("chaos")
        if isinstance(ch, dict):
            chaos_compared += 1
            regressions.extend(_chaos_gate(name, ch))
        # ---- ZERO-COPY gate: absolute invariants, baseline or not ------
        zc = cur.get("zero_copy")
        if isinstance(zc, dict):
            zero_copy_compared += 1
            regressions.extend(_zero_copy_gate(name, zc))
        baseline = None
        baseline_round = None
        for rec in reversed(trajectory):
            parsed = rec["parsed"]
            if not parsed or parsed.get("platform") != platform:
                continue
            prev = _fenced_metrics(parsed).get(name)
            if prev is not None:
                baseline, baseline_round = prev, rec["round"]
                break
        if baseline is None:
            no_baseline.append(name)
            continue
        compared += 1
        cur_v, prev_v = float(cur["value"]), float(baseline["value"])
        higher_better = cur["unit"] in _HIGHER_BETTER_UNITS
        if prev_v <= 0:
            continue
        change = (cur_v - prev_v) / prev_v
        bad = (change < -tolerance) if higher_better \
            else (change > tolerance)
        entry = {"name": name, "unit": cur["unit"], "value": cur_v,
                 "baseline": prev_v, "baseline_round": baseline_round,
                 "change": round(change, 4)}
        if bad:
            regressions.append(entry)
        elif (change > tolerance) if higher_better \
                else (change < -tolerance):
            improvements.append(entry)
        # ---- copy-budget gate: the workload's devflow block ------------
        flow_cur = cur.get("devflow")
        flow_prev = baseline.get("devflow")
        if isinstance(flow_cur, dict) and isinstance(flow_prev, dict):
            for key, unit in _DEVFLOW_GATED:
                devflow_compared += _gate_lower_better(
                    f"{name}.{key}", unit,
                    float(flow_cur.get(key, 0.0) or 0.0),
                    float(flow_prev.get(key, 0.0) or 0.0),
                    DEVFLOW_FLOORS[key], DEVFLOW_TOLERANCE,
                    baseline_round, regressions, improvements)
        # ---- recovery gate: the storm's bytes-per-repaired-shard -------
        rec_cur = cur.get("recovery")
        rec_prev = baseline.get("recovery")
        if isinstance(rec_cur, dict) and isinstance(rec_prev, dict):
            for key, unit, floor in _RECOVERY_GATED:
                recovery_compared += _gate_lower_better(
                    f"{name}.recovery.{key}", unit,
                    float(rec_cur.get(key, 0.0) or 0.0),
                    float(rec_prev.get(key, 0.0) or 0.0),
                    floor, RECOVERY_TOLERANCE,
                    baseline_round, regressions, improvements)
        # ---- stage-budget gate: the workload's stage_breakdown ---------
        sb_cur = (cur.get("stage_breakdown") or {}).get("stages")
        sb_prev = (baseline.get("stage_breakdown") or {}).get("stages")
        if not isinstance(sb_cur, dict) or not isinstance(sb_prev, dict):
            continue        # pre-oplat rounds gate no stages
        for stage in sorted(set(sb_cur) | set(sb_prev)):
            stage_compared += _gate_lower_better(
                f"{name}.stage.{stage}", "usec/op",
                float((sb_cur.get(stage) or {}).get("usec_per_op",
                                                    0.0) or 0.0),
                float((sb_prev.get(stage) or {}).get("usec_per_op",
                                                     0.0) or 0.0),
                STAGE_FLOOR_USEC_PER_OP, STAGE_TOLERANCE,
                baseline_round, regressions, improvements)
    return {"regressions": regressions, "improvements": improvements,
            "compared": compared, "devflow_compared": devflow_compared,
            "stage_compared": stage_compared,
            "recovery_compared": recovery_compared,
            "skew_compared": skew_compared,
            "straggler_compared": straggler_compared,
            "control_compared": control_compared,
            "chaos_compared": chaos_compared,
            "zero_copy_compared": zero_copy_compared,
            "no_baseline": no_baseline,
            "tolerance": tolerance, "platform": platform}


def _skew_gate(name: str, sk: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The skew workload's absolute invariants as regression entries
    (change=None: there is no ratio to report — the ruler either
    works or it does not)."""
    out: List[Dict[str, Any]] = []

    def fail(key: str, value, why: str) -> None:
        out.append({"name": f"{name}.skew.{key}", "unit": "invariant",
                    "value": value, "baseline": why,
                    "baseline_round": None, "change": None})

    det = int(sk.get("detection_probes") or 0)
    if det <= 0:
        fail("detection_probes", det,
             "scoreboard never marked the slowed chip suspect")
    elif det > SKEW_MAX_DETECTION_PROBES:
        fail("detection_probes", det,
             f"detection took more than {SKEW_MAX_DETECTION_PROBES} "
             f"probes")
    if det > 0 and sk.get("detected_chip") != sk.get("slow_chip"):
        fail("detected_chip", sk.get("detected_chip"),
             f"suspect is not the slowed chip "
             f"{sk.get('slow_chip')}")
    if int(sk.get("healthy_false_suspects") or 0) > 0 \
            or sk.get("healthy_raised"):
        fail("healthy_false_suspects",
             sk.get("healthy_false_suspects"),
             "the healthy twin raised a suspect/health check")
    if not sk.get("raised"):
        fail("raised", sk.get("raised"),
             "TPU_MESH_SKEW never raised while the mgr ticked")
    if not sk.get("cleared"):
        fail("cleared", sk.get("cleared"),
             "TPU_MESH_SKEW did not clear after the fault was removed")
    return out


def _control_gate(name: str,
                  ct: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The control-plane workload's absolute invariants as regression
    entries (change=None — a control plane that fails to converge, or
    moves when disabled, either holds its contract or it does not)."""
    out: List[Dict[str, Any]] = []

    def fail(key: str, value, why: str) -> None:
        out.append({"name": f"{name}.control.{key}",
                    "unit": "invariant", "value": value,
                    "baseline": why, "baseline_round": None,
                    "change": None})

    budget = int(ct.get("tick_budget") or 0)
    if int(ct.get("disabled_moves") or 0) != 0:
        fail("disabled_moves", ct.get("disabled_moves"),
             "the disabled-controller twin actuated a knob — "
             "mgr_control_enable no longer gates actuation")
    if not ct.get("byte_exact"):
        fail("byte_exact", ct.get("byte_exact"),
             "client ops diverged while the controller ran — the "
             "control plane touched the data path")
    for scen, block in sorted((ct.get("scenarios") or {}).items()):
        if not isinstance(block, dict):
            fail(scen, block, "scenario block missing")
            continue
        if not block.get("raised"):
            fail(f"{scen}.raised", block.get("raised"),
                 "the scenario never raised its SLO/health pressure "
                 "— the episode is vacuous")
        if int(block.get("moves") or 0) <= 0:
            fail(f"{scen}.moves", block.get("moves"),
                 "the controller never moved a knob under sustained "
                 "pressure")
        conv = int(block.get("converge_ticks") or -1)
        if not block.get("cleared") or conv <= 0 or conv > budget:
            fail(f"{scen}.converge_ticks", conv,
                 f"the episode did not clear back to baseline within "
                 f"{budget} mgr ticks of the pressure ending")
        if not block.get("in_bounds"):
            fail(f"{scen}.in_bounds", block.get("in_bounds"),
                 "a pressure-driven move landed outside its knob's "
                 "floor/ceiling corridor")
    if "admission" in (ct.get("scenarios") or {}):
        adm = ct["scenarios"]["admission"]
        if isinstance(adm, dict) and not adm.get("abuser_correct"):
            fail("admission.abuser_correct",
                 adm.get("abuser_correct"),
                 "the controller tightened a lane other than the "
                 "flooding client's")
    return out


def _straggler_gate(name: str,
                    st: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The straggler workload's absolute invariants as regression
    entries (change=None — the flagship robustness claim either holds
    or it does not)."""
    out: List[Dict[str, Any]] = []

    def fail(key: str, value, why: str) -> None:
        out.append({"name": f"{name}.straggler.{key}",
                    "unit": "invariant", "value": value,
                    "baseline": why, "baseline_round": None,
                    "change": None})

    det = int(st.get("detection_probes") or 0)
    if det <= 0:
        fail("detection_probes", det,
             "scoreboard never marked the slowed chip suspect — no "
             "injected-degradation receipt")
    elif det > STRAGGLER_MAX_DETECTION_PROBES:
        fail("detection_probes", det,
             f"detection took more than "
             f"{STRAGGLER_MAX_DETECTION_PROBES} probes")
    if det > 0 and st.get("detected_chip") != st.get("slow_chip"):
        fail("detected_chip", st.get("detected_chip"),
             f"suspect is not the slowed chip {st.get('slow_chip')}")
    if float(st.get("skew_ratio_detected") or 0.0) <= 0:
        fail("skew_ratio_detected", st.get("skew_ratio_detected"),
             "no skew ratio recorded at detection")
    ratio = float(st.get("protected_p999_ratio") or 0.0)
    if ratio <= 0 or ratio > STRAGGLER_MAX_P999_RATIO:
        fail("protected_p999_ratio", ratio,
             f"protected cluster_rollup device_call p999 beyond "
             f"{STRAGGLER_MAX_P999_RATIO}x the healthy twin "
             f"(log2-edge quantized: 2.0 = one bucket)")
    wall = float(st.get("protected_p999_wall_ratio") or 0.0)
    if wall <= 0 or wall > STRAGGLER_MAX_WALL_P999_RATIO:
        fail("protected_p999_wall_ratio", wall,
             f"protected wall-clock flush p999 beyond "
             f"{STRAGGLER_MAX_WALL_P999_RATIO}x the healthy twin")
    bw = float(st.get("bandwidth_overhead") or 0.0)
    if bw <= 0 or bw >= STRAGGLER_MAX_BANDWIDTH_OVERHEAD:
        fail("bandwidth_overhead", bw,
             f"healthy twin pays >= "
             f"{STRAGGLER_MAX_BANDWIDTH_OVERHEAD}x coded bandwidth")
    if not st.get("byte_identical"):
        fail("byte_identical", st.get("byte_identical"),
             "protected outputs diverged from the unprotected oracle")
    if int(st.get("single_device_fallbacks") or 0) > 0:
        fail("single_device_fallbacks",
             st.get("single_device_fallbacks"),
             "a protected flush degraded to the single-device path")
    if int(st.get("subset_completions") or 0) <= 0:
        fail("subset_completions", st.get("subset_completions"),
             "no flush completed from a strict subset — the "
             "protection never engaged")
    if int(st.get("healthy_false_suspects") or 0) > 0:
        fail("healthy_false_suspects",
             st.get("healthy_false_suspects"),
             "the healthy twin marked a suspect")
    return out


def _zero_copy_gate(name: str,
                    zc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The zero-copy workload's absolute invariants as regression
    entries (change=None — the resident write path either deletes the
    copies or it does not)."""
    out: List[Dict[str, Any]] = []

    def fail(key: str, value, why: str) -> None:
        out.append({"name": f"{name}.zero_copy.{key}",
                    "unit": "invariant", "value": value,
                    "baseline": why, "baseline_round": None,
                    "change": None})

    d2h = float(zc.get("resident_d2h_bytes_per_op") or 0.0)
    if d2h >= ZERO_COPY_MAX_D2H_BYTES_PER_OP:
        fail("resident_d2h_bytes_per_op", d2h,
             f"the resident write path fetched >= "
             f"{ZERO_COPY_MAX_D2H_BYTES_PER_OP} B/op from device — a "
             f"shard body is crossing back on the write path")
    res = float(zc.get("resident_copies_per_op") or 0.0)
    twin = float(zc.get("twin_copies_per_op") or 0.0)
    if not res < twin:
        fail("resident_copies_per_op", res,
             f"resident leg not strictly below the bytes twin's "
             f"{twin} copies/op — the fused path deleted nothing")
    if int(zc.get("resident_shards") or 0) <= 0:
        fail("resident_shards", zc.get("resident_shards"),
             "no DeviceShard was resident when the write region "
             "closed — the fused path silently degraded and the A/B "
             "measured nothing")
    if not zc.get("byte_exact"):
        fail("byte_exact", zc.get("byte_exact"),
             "a read-back diverged from the written payload")
    return out


def _chaos_gate(name: str, ch: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The composed-chaos workload's absolute invariants as regression
    entries (change=None — a storyline either survives the universal
    acceptance or it does not; there is no ratio to report)."""
    out: List[Dict[str, Any]] = []

    def fail(key: str, value, why: str) -> None:
        out.append({"name": f"{name}.chaos.{key}", "unit": "invariant",
                    "value": value, "baseline": why,
                    "baseline_round": None, "change": None})

    receipts = ch.get("receipts") or []
    if not receipts:
        fail("receipts", 0, "no storyline receipts — the chaos "
             "workload executed nothing")
        return out
    for r in receipts:
        if not isinstance(r, dict):
            fail("receipt", r, "malformed storyline receipt")
            continue
        seed = r.get("seed")
        if not r.get("byte_exact"):
            fail(f"seed{seed}.byte_exact", r.get("byte_exact"),
                 "an op or dispatcher oracle diverged from its "
                 "expected bytes under the storyline")
        if r.get("wedged"):
            fail(f"seed{seed}.wedged", r.get("wedged"),
                 "the storyline exhausted its settle budget — a "
                 "composed fault wedged the cluster")
        for chk, row in sorted((r.get("checks") or {}).items()):
            if not isinstance(row, dict) or not all(row.values()):
                fail(f"seed{seed}.{chk}", row,
                     "an expected health check failed to raise, "
                     "clear, or leave a finalized bundle that tells "
                     "the storyline back")
        if not r.get("all_raises_resolved"):
            fail(f"seed{seed}.all_raises_resolved",
                 r.get("all_raises_resolved"),
                 "a collateral health raise never cleared or left "
                 "no finalized incident bundle")
        if not r.get("storyline_told"):
            fail(f"seed{seed}.storyline_told", r.get("storyline_told"),
                 "the cluster journal does not contain the injected "
                 "storyline's promised event types")
        if int(r.get("mesh_fallbacks") or 0) != 0:
            fail(f"seed{seed}.mesh_fallbacks", r.get("mesh_fallbacks"),
                 "a composed fault degraded a flush to the "
                 "single-device fallback path")
        if not r.get("accepted"):
            fail(f"seed{seed}.accepted", r.get("accepted"),
                 "the storyline failed the engine's universal "
                 "acceptance")
    return out
