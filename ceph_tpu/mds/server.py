"""MDS-lite — the metadata server daemon for cephfs.

The reference's cephfs is MDS-mediated (src/mds/MDSDaemon.cc): clients
hold CAPABILITIES issued by the Locker (src/mds/Locker.cc) that gate
caching and write-back, every metadata mutation funnels through the MDS
(which persists dentries to the metadata pool and write-ahead-logs each
op in the MDS journal, src/mds/MDLog.cc), and snapshots attach to any
directory via the SnapRealm hierarchy (src/mds/SnapRealm.h).  This is
that architecture at lite scale:

- ONE metadata authority: the daemon owns a CephFS backend (the
  cls_fs-based engine) as the sole metadata-pool writer; clients speak
  MClientRequest/MClientReply and never touch metadata objects.
- Locker-lite capabilities: CEPH_CAP_FILE_BUFFER (exclusive write-back)
  conflicts with everything; CEPH_CAP_FILE_CACHE (shared read-cache)
  conflicts with BUFFER.  Conflicting opens trigger a revoke round —
  the holder flushes its buffered data to the DATA pool directly, then
  sends MClientCaps(flush) carrying the wrstat payload; the blocked
  request resumes once every revoke is acked (Locker::issue_caps +
  file_update_finish shape).  Holders that never ack are evicted after
  ``session_timeout`` (Session::is_stale eviction).
- MDS journal: every mutating op is appended to a Journaler ("mdlog")
  in the metadata pool BEFORE it is applied; a restarted daemon
  replays uncommitted events idempotently (MDLog replay).  This also
  makes cross-directory rename crash-safe: the two dentry updates are
  one journaled event, and only the (single-writer) MDS applies them,
  so no client can observe the intermediate state through the MDS.
- SnapRealm-lite: `snap_create(path, name)` records (md_sid, data_sid)
  in the realm table of that DIRECTORY; a file's write SnapContext is
  the union of the data snaps on its ancestor realm chain, handed to
  clients at open.  Files outside the subtree keep writing with a
  snapc that excludes the new snap, so no clone of them is preserved —
  per-directory snapshots fall out of per-file snap contexts.
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Set, Tuple

from ..cephfs.client import CephFS, FsError
from ..cephfs.cls_fs import ROOT_INO, dir_oid, file_oid
from ..client.rados import RadosClient
from ..msg.messages import (
    CEPH_CAP_FILE_BUFFER, CEPH_CAP_FILE_CACHE, MClientCaps,
    MClientReply, MClientRequest, MCommand, MCommandReply, Message,
)

MDLOG_ID = "mdlog"
REALM_PREFIX = "fs_realm."


def realm_oid(ino: int) -> str:
    return f"{REALM_PREFIX}{ino:x}"


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


# ops that mutate metadata and therefore ride the MDS journal
_JOURNALED = {"mkdir", "create", "symlink", "hardlink", "unlink",
              "rmdir", "rename", "setattr", "wrstat", "truncate",
              "snap_create", "snap_remove", "set_dir_pin",
              "set_quota", "set_layout"}
# ops answered read-only
_READONLY = {"stat", "listdir", "readlink", "resolve", "exists",
             "lssnap", "open", "release", "walk_snapc", "get_quota"}

# ops that add a dentry — gated by ancestor max_files quotas
# (Client.cc:11502/11636 is_quota_files_exceeded; authority-side here)
_CREATES_DENTRY = {"mkdir", "create", "symlink", "hardlink"}

# the request key that names the op's PRIMARY path — the one whose
# subtree authority decides which rank serves it (Server::
# dispatch_client_request routing by dentry auth)
_PATH_KEY = {"rename": "src", "hardlink": "existing"}

# MClientReply.result for "not my subtree — retry at rank N" (the
# lite form of MClientRequestForward); data carries forward_rank and,
# when known, the serving daemon's name
MDS_FORWARD = -2001


class MDSDaemon:
    """The mds-lite daemon: metadata authority + Locker + MDLog +
    SnapRealms.  Event-driven like Monitor/OSD: register on a network,
    pump delivers requests, ``tick(now)`` drives session timeouts."""

    def __init__(self, network, rados: RadosClient, name: str = "mds.0",
                 metadata_pool: str = "fsmeta", data_pool: str = "fsdata",
                 mkfs: bool = False, session_timeout: float = 20.0,
                 rank: int = 0):
        from ..journal import Journaler
        from ..trace import g_perf_histograms, latency_axes
        self.network = network
        self.name = name
        # per-daemon request-latency histogram, resolved once (same
        # pattern as OSD.hist_op_w — keeps the registry lock off the
        # per-request hot path)
        self._hist_req = g_perf_histograms.get(
            name, "req_latency_histogram", latency_axes)
        # ALL dispatch-visible state must exist before the messenger
        # registration: construction below does rados IO whose pumps
        # can deliver client requests to ms_fast_dispatch mid-__init__
        self._inbox: List[Message] = []
        self.caps = {}
        self.cap_seq = 0
        self.revoking = {}
        self.waiting = {}
        self.now = 0.0
        self.messenger = network.create_messenger(name)
        self.messenger.add_dispatcher_head(self)
        self.rados = rados
        # daemon mode shares ONE entity name between the MDS service
        # and its rados client (vstart "mds.0"): we hold the dispatcher
        # slot, so everything that isn't MDS traffic (MOSDOpReply, map
        # pushes, command acks) must fall through to the rados client
        self._fallthrough = rados if getattr(rados, "name", None) == \
            name else None
        self.mdpool = metadata_pool
        self.dpool = data_pool
        self.fs = CephFS(rados, metadata_pool, data_pool)
        self.session_timeout = session_timeout
        # subtree authority: this daemon serves RANK ``rank`` of the
        # fs (mds_rank_t); ranks partition the namespace by directory
        # pins (ceph.dir.pin vxattr / MDSMonitor fsmap ranks).  Each
        # rank journals to its OWN mdlog (the reference's per-rank
        # 0x200+rank journal inos); rank 0 keeps the legacy name so
        # single-active clusters are unchanged on disk.
        self.rank = rank
        self.mds_map: Dict[int, str] = {rank: name}
        self._cap_paths: Dict[int, str] = {}
        jname = MDLOG_ID if rank == 0 else f"{MDLOG_ID}.{rank}"
        self.journal = Journaler(rados, metadata_pool, jname,
                                 entries_per_object=128)
        from ..journal import JournalError
        if mkfs and rank == 0:
            try:
                self.fs.mkfs()
            except FsError as e:
                # a RETRIED boot (journal/PG settling killed the
                # previous attempt after mkfs landed) must not wedge
                # on its own half-finished init
                if e.result != -17:
                    raise
        # open-or-create: a freshly promoted rank (or first boot)
        # creates its journal; a rebooted one opens and replays
        try:
            self.journal.open()
        except JournalError as e:
            # only a first boot (mkfs) or a freshly promoted rank > 0
            # may create its journal; a plain rank-0 reboot with a
            # MISSING journal is a misconfiguration (wrong pool, lost
            # data) that must fail loudly, never silently skip replay
            if e.result != -2 or not (mkfs or rank > 0):
                raise
            try:
                self.journal.create(order=20, splay_width=2)
            except JournalError as e2:
                if e2.result != -17:
                    raise
                self.journal.open()   # a racing boot already made it
        try:
            self.journal.register_client("mds")
        except JournalError as e:
            if e.result != -17:
                raise
        # caps: ino -> {client: capbits}; revokes: ino -> {client:
        # (seq, issued_at)} with issued_at None until the first tick
        # supplies a clock; _inbox: dispatch only ENQUEUES (handlers do
        # blocking rados IO which cannot run nested inside a pump) —
        # all initialized above, before the messenger registration.
        #
        # completed request ids: mutating ops journal their reqid, so
        # a PROMOTED standby that replayed the journal can answer a
        # client's failover retry instead of re-executing it (the
        # reference persists completed_requests in the session map)
        from collections import OrderedDict
        self._completed: "OrderedDict[str, bool]" = OrderedDict()
        self._replay()

    # ---- journal (MDLog) ---------------------------------------------------
    def _replay(self) -> None:
        """Re-apply uncommitted journal events (MDLog replay after a
        crash).  Events are idempotent: already-applied mutations
        answer EEXIST/ENOENT and are treated as done."""
        committed = -1
        md = self.journal.get_metadata()
        cl = md.get("clients", {}).get("mds")
        if cl is None:
            self.journal.register_client("mds")
        else:
            committed = cl["commit_tid"]
        # ONE read of the retained journal serves both passes.
        # reqids are remembered for EVERY event, even committed ones
        # (a failover retry can reference an op the dead active
        # journaled AND committed) and tolerate gaps; the APPLY pass
        # keeps the strict gap rule FROM THE COMMIT POINT (events past
        # a gap are not safe to apply in order).
        raw = self.journal.scan_entries()
        # annul accounting: an op whose apply FAILED left its frame in
        # the journal plus an __annul__ record; a reqid counts as
        # applied only if it has more op frames than annuls (so a
        # failed attempt can never replay into a phantom success)
        frames: Dict[str, int] = {}
        annuls: Dict[str, int] = {}
        annulled_tids: Dict[int, int] = {}
        docs = []
        for tid_, payload in raw:
            try:
                doc = json.loads(payload)
            except ValueError:
                continue
            docs.append((tid_, doc))
            rid = doc.get("reqid")
            if doc.get("op") == "__annul__":
                if rid:
                    annuls[rid] = annuls.get(rid, 0) + 1
                ft = int(doc.get("for", -1))
                annulled_tids[ft] = annulled_tids.get(ft, 0) + 1
            elif rid:
                frames[rid] = frames.get(rid, 0) + 1
        for rid, n in frames.items():
            if n > annuls.get(rid, 0):
                self._remember(rid)
        entries = {t: d for t, d in docs if d.get("op") != "__annul__"}
        last = committed
        tid = committed + 1
        while tid in entries:
            ev = entries[tid]
            if annulled_tids.get(tid, 0) >= 1:
                # the failed attempt's frame: its effects never
                # happened; replaying could apply them now
                last = tid
                tid += 1
                continue
            try:
                self._apply(ev["op"], ev["args"])
            except FsError as e:
                if e.result not in (-17, -2, -39):
                    raise
            last = tid
            tid += 1
        if last > committed:
            self.journal.commit("mds", last)
        # frames appended after THIS scan belong to racing writers;
        # the duplicate fence only needs to look there
        self._boot_next_tid = getattr(self.journal, "_next_tid", 0)

    def _remember(self, reqid: str) -> None:
        self._completed[reqid] = True
        while len(self._completed) > 4096:
            self._completed.popitem(last=False)

    def _applied_elsewhere(self, reqid: str) -> bool:
        """Duplicate-apply fence: does *reqid* have an APPLIED journal
        frame besides the one the current invocation just wrote?
        Counts op frames minus __annul__ records minus our own
        attempt; scans fresh reads bounded to frames appended after
        our startup scan (only racing writers can live there — older
        applied frames already populated the memo at boot)."""
        boot = getattr(self, "_boot_next_tid", 0)
        needle = reqid.encode()
        frames = annuls = 0
        try:
            for tid_, payload in self.journal.scan_entries():
                if tid_ < boot or needle not in payload:
                    continue
                try:
                    doc = json.loads(payload)
                except ValueError:
                    continue
                if doc.get("reqid") != reqid:
                    continue
                if doc.get("op") == "__annul__":
                    annuls += 1
                else:
                    frames += 1
        except IOError:
            return False
        return frames - annuls - 1 > 0    # minus our own attempt

    def _journal_and_apply(self, op: str, args: Dict,
                           reqid: str = ""):
        ev = {"op": op, "args": args}
        if reqid:
            ev["reqid"] = reqid
        tid = self.journal.append(_j(ev))
        try:
            out = self._apply(op, args)
        except FsError as e:
            # duplicate-apply fence: a deposed incumbent can land a
            # mutation in OUR journal after our startup scan (the
            # dual-writer window before it fences).  An already-
            # exists-class failure is a duplicate iff the reqid has an
            # APPLIED frame besides ours (frames minus annuls minus
            # our own attempt) — answer from effect like the memo path
            if e.result in (-17, -2, -39) and reqid and \
                    self._applied_elsewhere(reqid):
                self.journal.commit("mds", tid)
                self._remember(reqid)
                return self._replayed_reply(op, args)
            # record the failure so no later consumer (startup memo,
            # replay, fence) mistakes this attempt's frame for effect
            self.journal.append(_j({"op": "__annul__", "for": tid,
                                    "reqid": reqid}))
            raise
        self.journal.commit("mds", tid)
        if reqid:
            self._remember(reqid)
        return out

    # ---- dispatch ----------------------------------------------------------
    def ms_fast_dispatch(self, msg: Message) -> None:
        if isinstance(msg, MCommand):
            # SYNCHRONOUS, unlike client traffic: the command handler
            # does no rados IO, so it is safe inside the pump — and a
            # blocked 'ceph tell' client could never drive process()
            self._handle_command(msg)
        elif isinstance(msg, (MClientRequest, MClientCaps)):
            self._inbox.append(msg)
        elif self._fallthrough is not None:
            self._fallthrough.ms_fast_dispatch(msg)

    def ms_dispatch(self, msg: Message) -> None:  # pragma: no cover
        self.ms_fast_dispatch(msg)

    def process(self) -> int:
        """Drain queued client traffic (the dispatch/workqueue split:
        handlers run OUTSIDE the network pump so their own rados round
        trips can pump freely).  Returns messages handled."""
        n = 0
        while self._inbox:
            msg = self._inbox.pop(0)
            n += 1
            if isinstance(msg, MClientRequest):
                self._handle_request(msg)
            else:
                self._handle_caps(msg)
        return n

    def _handle_command(self, msg) -> None:
        """'ceph tell mds.<name>' (MCommand.h): runtime config and
        introspection on a live metadata server.  The config
        vocabulary (incl. atomic injectargs) is
        ConfigProxy.handle_config_command, shared with the OSD."""
        from ..common.config import g_conf
        result, data = g_conf.run_daemon_command(msg.cmd, msg.args, {
            "session ls": lambda: {"sessions": sorted(
                {c for holders in self.caps.values()
                 for c in holders})},
            "status": lambda: {"name": self.name, "rank": self.rank,
                               "mds_map": {str(r): n for r, n
                                           in self.mds_map.items()}},
        })
        self.messenger.send_message(
            MCommandReply(tid=msg.tid, result=result, data=data),
            msg.src)

    # ---- subtree authority (multi-active ranks) ----------------------------
    def set_mds_map(self, ranks: Dict[int, str]) -> None:
        """Current rank->daemon map from the fsmap ('ceph fs status'):
        pins to ranks outside this map are ignored, exactly like the
        reference ignoring export_pin targets beyond max_mds."""
        self.mds_map = {int(r): n for r, n in ranks.items()}
        if self.rank not in self.mds_map:
            self.mds_map[self.rank] = self.name

    def _auth_rank(self, path: str) -> int:
        """The rank authoritative for *path*: the deepest ancestor
        directory pin along the (existing) path, rank 0 otherwise —
        static export pins as the lite MDBalancer (CInode::
        get_export_pin / Migrator policy at lite scale)."""
        auth = 0
        cur = ROOT_INO
        try:
            parts = self.fs._split(path)
        except Exception:
            return auth
        for part in parts:
            try:
                inode = self.fs._lookup(cur, part)
            except FsError:
                break
            pin = inode.get("pin")
            if inode.get("type") != "dir":
                break
            if pin is not None and int(pin) in self.mds_map:
                auth = int(pin)
            cur = inode["ino"]
        return auth

    def _route(self, op: str, args: Dict) -> Optional[int]:
        """None = ours; else the rank to forward to.  Single-rank maps
        short-circuit (no lookups on the hot path)."""
        if len(self.mds_map) <= 1:
            return None
        path = args.get(_PATH_KEY.get(op, "path"))
        if not isinstance(path, str):
            return None              # ino-addressed (release): local
        auth = self._auth_rank(path)
        return None if auth == self.rank else auth

    def _subtree_cap_inos(self, path: str) -> List[int]:
        """Inos with outstanding caps under *path* (handoff drain)."""
        prefix = "/" + "/".join(self.fs._split(path))
        out = []
        for ino, p in self._cap_paths.items():
            if not self.caps.get(ino):
                continue
            q = "/" + "/".join(self.fs._split(p))
            if q == prefix or q.startswith(prefix + "/"):
                out.append(ino)
        return out

    def _op_set_dir_pin(self, msg: MClientRequest,
                        args: Dict) -> Optional[Dict]:
        """Repin a subtree to another rank — the journaled handoff
        (Migrator::export_dir at lite scale).  Outstanding caps under
        the subtree are revoked and flushed FIRST, so the new
        authority never sees a writer it doesn't know about; the pin
        itself is one journaled event."""
        rank = int(args["rank"])
        inode = self.fs._resolve(args["path"], follow_final=True)
        if inode["type"] != "dir":
            raise FsError("set_dir_pin", -20)        # ENOTDIR
        if self._drain_caps(msg, self._subtree_cap_inos(args["path"])):
            return None
        return self._journal_and_apply(
            "set_dir_pin", {"path": args["path"], "rank": rank},
            getattr(msg, "reqid", ""))

    def _drain_caps(self, msg: MClientRequest, held: List[int]) -> bool:
        """Start revoke rounds on every held ino; True = *msg* parked
        (re-dispatched by _kick once the first ino drains; the re-run
        re-checks the remaining holders)."""
        parked_on = None
        for ino in held:
            holders = self.caps.get(ino, {})
            pending = self.revoking.setdefault(ino, {})
            for other in [c for c in holders if c not in pending]:
                self.cap_seq += 1
                pending[other] = (self.cap_seq,
                                  self.now if self.now else None)
                self.messenger.send_message(MClientCaps(
                    op=MClientCaps.OP_REVOKE, ino=ino,
                    caps=holders[other], seq=self.cap_seq), other)
            if pending and parked_on is None:
                parked_on = ino
            elif not pending:
                self.revoking.pop(ino, None)
        if parked_on is not None:
            self.waiting.setdefault(parked_on, []).append(msg)
            return True
        return False

    def beacon(self, mons, state: str = "active") -> None:
        """MMDSBeacon to every mon (MDSDaemon::beacon_send): liveness
        for the MDSMonitor's fsmap — a silent active gets failed over
        to a standby."""
        from ..msg.messages import MMDSBeacon
        self._beacon_seq = getattr(self, "_beacon_seq", 0) + 1
        for m in mons:
            self.messenger.send_message(MMDSBeacon(
                name=self.name, state=state,
                seq=self._beacon_seq), m)

    def tick(self, now: float) -> None:
        """Evict sessions that never acked a revoke (stale session
        eviction): their caps are dropped so the fs cannot wedge on a
        dead client; their buffered data is lost, like the reference
        evicting a stale session."""
        self.now = now
        for ino, m in list(self.revoking.items()):
            for client, (seq, issued) in list(m.items()):
                if issued is None:
                    # revoke predates our first clock reading: the
                    # grace period starts NOW
                    m[client] = (seq, now)
                elif now - issued > self.session_timeout:
                    del m[client]
                    self.caps.get(ino, {}).pop(client, None)
                    if not self.caps.get(ino):
                        self._cap_paths.pop(ino, None)
            if not m:
                del self.revoking[ino]
                self._kick(ino)

    # ---- capabilities (Locker-lite) ---------------------------------------
    def _issue(self, client: str, ino: int, want: int,
               msg: MClientRequest) -> Optional[int]:
        """Grant *want* caps to *client*, revoking conflicts first.
        Returns the granted bits, or None if the request must wait for
        a revoke round (it has been parked)."""
        holders = self.caps.setdefault(ino, {})
        conflicts = []
        for other, bits in holders.items():
            if other == client:
                continue
            if want & CEPH_CAP_FILE_BUFFER:
                conflicts.append(other)          # BUFFER is exclusive
            elif bits & CEPH_CAP_FILE_BUFFER:
                conflicts.append(other)          # CACHE vs their BUFFER
        pending = self.revoking.setdefault(ino, {})
        newly = [c for c in conflicts if c not in pending]
        for other in newly:
            self.cap_seq += 1
            pending[other] = (self.cap_seq,
                              self.now if self.now else None)
            self.messenger.send_message(MClientCaps(
                op=MClientCaps.OP_REVOKE, ino=ino,
                caps=holders[other], seq=self.cap_seq), other)
        if pending:
            self.waiting.setdefault(ino, []).append(msg)
            return None
        if not self.revoking.get(ino):
            self.revoking.pop(ino, None)
        holders[client] = holders.get(client, 0) | want
        return holders[client]

    def _handle_caps(self, msg: MClientCaps) -> None:
        if msg.op != MClientCaps.OP_FLUSH:
            return
        ino = msg.ino
        # only a CURRENT cap holder (or a revoke still outstanding)
        # may flush: an evicted client's delayed flush must not roll
        # metadata back under the new holder's feet
        if msg.src not in self.caps.get(ino, {}) and \
                msg.src not in self.revoking.get(ino, {}):
            return
        # the flush carries the holder's write-back results (wrstat):
        # journal + apply them before anyone else touches the file.
        # The ino's CURRENT path (our cap bookkeeping, kept fresh
        # across renames) outranks the client's open-time path — the
        # reference's cap flushes are ino-addressed for this reason.
        path = self._cap_paths.get(ino) or msg.data.get("path")
        if path is not None and "size" in msg.data:
            try:
                self._journal_and_apply("wrstat", {
                    "path": path,
                    "size": msg.data["size"],
                    "mtime": msg.data.get("mtime", time.time())})
            except FsError:
                pass             # file unlinked while caps were out
        m = self.revoking.get(ino)
        if m is not None:
            m.pop(msg.src, None)
            if not m:
                del self.revoking[ino]
        self.caps.get(ino, {}).pop(msg.src, None)
        if not self.caps.get(ino):
            self.caps.pop(ino, None)
            self._cap_paths.pop(ino, None)
        self._kick(ino)

    def _kick(self, ino: int) -> None:
        for req in self.waiting.pop(ino, []):
            self._handle_request(req)

    # ---- request handling --------------------------------------------------
    def _reply(self, msg: MClientRequest, result: int,
               data: Optional[Dict] = None) -> None:
        self.messenger.send_message(MClientReply(
            tid=msg.tid, result=result, data=data or {}), msg.src)

    def _handle_request(self, msg: MClientRequest) -> None:
        """Instrumented intake: every request lands one sample in the
        per-daemon request-latency histogram, and (tracer on) runs
        under a span parented by the client's (Server.cc
        handle_client_request's mds_server perf counters + blkin
        trace role)."""
        from ..trace import g_tracer
        t0 = time.perf_counter()
        if g_tracer.enabled:
            with g_tracer.span(f"mds_req:{msg.op}", daemon=self.name,
                               trace_id=msg.trace_id,
                               parent_id=msg.parent_span_id):
                self._do_handle_request(msg)
        else:
            self._do_handle_request(msg)
        self._hist_req.inc((time.perf_counter() - t0) * 1e6)

    def _do_handle_request(self, msg: MClientRequest) -> None:
        op, args = msg.op, dict(msg.args)
        try:
            reqid = getattr(msg, "reqid", "")
            if op in _JOURNALED and reqid \
                    and reqid in self._completed:
                # a failover retry of an op WE already journaled (or
                # replayed): answer from effect, never re-execute
                # (mkdir would EEXIST, rename would ENOENT, snap ids
                # would double-allocate).  Checked BEFORE routing —
                # the subtree may have been repinned since the
                # original ran, and forwarding the retry would
                # re-execute it on the new auth rank.
                self._reply(msg, 0, self._replayed_reply(op, args))
                return
            fwd = self._route(op, args)
            if fwd is not None:
                # not our subtree: point the client at the auth rank
                # (MClientRequestForward at lite scale)
                self._reply(msg, MDS_FORWARD, {
                    "forward_rank": fwd,
                    "mds": self.mds_map.get(fwd, "")})
                return
            if op == "set_dir_pin":
                out = self._op_set_dir_pin(msg, args)
                if out is None:
                    return           # parked on the cap drain
            elif op == "open":
                out = self._op_open(msg, args)
                if out is None:
                    return               # parked on a revoke round
            elif op == "release":
                ino = int(args["ino"])
                self.caps.get(ino, {}).pop(msg.src, None)
                if not self.caps.get(ino):
                    self._cap_paths.pop(ino, None)
                out = {}
            elif op == "wrstat" and not self._wrstat_allowed(msg,
                                                             args):
                self._reply(msg, -13, {"error": "stale cap flush"})
                return
            elif op in _JOURNALED:
                if op in _CREATES_DENTRY:
                    self._check_files_quota(
                        args["newpath"] if op == "hardlink"
                        else args["path"])
                if op == "truncate":
                    # setattr-size growth rides byte quotas
                    # (Client.cc:6660-6664)
                    inode = self.fs._resolve(args["path"],
                                             follow_final=True)
                    growth = int(args["size"]) - \
                        int(inode.get("size", 0))
                    if growth > 0:
                        for q in self._quota_chain(args["path"]):
                            if q["max_bytes"] and \
                                    q["used_bytes"] + growth > \
                                    q["max_bytes"]:
                                raise FsError("quota", -122)
                if op == "rename":
                    self._check_rename_quota(args["src"],
                                             args["dst"])
                if op == "rename" and len(self.mds_map) > 1 and \
                        self._auth_rank(args["dst"]) != self.rank:
                    # a rename OUT of our authority moves any open
                    # handle beyond our cap bookkeeping's reach — the
                    # destination auth could never drain it.  Revoke
                    # + flush first, like the set_dir_pin handoff.
                    if self._drain_caps(msg, self._subtree_cap_inos(
                            args["src"])):
                        return       # parked on the drain
                out = self._journal_and_apply(op, args, reqid)
            elif op in _READONLY:
                out = self._apply(op, args)
            else:
                self._reply(msg, -22, {"error": f"unknown op {op!r}"})
                return
        except FsError as e:
            self._reply(msg, e.result, {"error": str(e)})
            return
        except (KeyError, ValueError, TypeError) as e:
            self._reply(msg, -22, {"error": repr(e)})
            return
        self._reply(msg, 0, out)

    def _wrstat_allowed(self, msg, args: Dict) -> bool:
        """The MClientCaps flush path refuses stale writers (evicted
        sessions); the REQUEST-shaped wrstat must enforce the same:
        if anyone currently holds caps on the ino, only a holder may
        write back size/mtime."""
        try:
            _d, _n, inode = self.fs._resolve_dentry(args["path"])
        except FsError:
            return True          # path-level errors surface in _apply
        holders = self.caps.get(inode["ino"])
        return not holders or msg.src in holders

    def _replayed_reply(self, op: str, args: Dict) -> Dict:
        """Reconstruct the reply for an already-applied duplicate:
        ino-returning ops re-resolve; the rest have no payload."""
        if op in ("mkdir", "create", "symlink"):
            try:
                return {"ino": self.fs._resolve(
                    args["path"], follow_final=False)["ino"],
                    "replayed": True}
            except FsError:
                return {"replayed": True}
        if op == "snap_create":
            # the snapshot exists: hand back its recorded ids
            try:
                inode = self.fs._resolve(args["path"],
                                         follow_final=True)
                e = self._realm_snaps(inode["ino"]).get(args["name"])
                if e is not None:
                    return {"ino": inode["ino"], "md": e["md"],
                            "data": e["data"], "replayed": True}
            except FsError:
                pass
            return {"replayed": True}
        return {"replayed": True}

    def _op_open(self, msg: MClientRequest,
                 args: Dict) -> Optional[Dict]:
        """Resolve + cap issue: the client gets the inode, its data
        SnapContext (realm chain), and the granted caps."""
        path = args["path"]
        want = int(args.get("want", CEPH_CAP_FILE_CACHE))
        create = bool(args.get("create"))
        try:
            dino, name, inode = self.fs._resolve_dentry(path)
        except FsError as e:
            if e.result != -2 or not create:
                raise
            # O_CREAT is a dentry creation like any other: same
            # max_files gate as the create op
            self._check_files_quota(path)
            self._journal_and_apply("create", {"path": path},
                                    getattr(msg, "reqid", ""))
            dino, name, inode = self.fs._resolve_dentry(path)
        if inode["type"] == "dir":
            raise FsError("open", -21)           # EISDIR
        granted = self._issue(msg.src, inode["ino"], want, msg)
        if granted is None:
            return None
        self._cap_paths[inode["ino"]] = path
        seq, snaps = self._file_snapc(path)
        return {"inode": inode, "caps": granted,
                "snapc_seq": seq, "snapc_snaps": snaps,
                "path": path,
                # the quota realm chain, cached client-side for the
                # data path's byte-quota checks (Client.cc's in->quota)
                "quotas": self._quota_chain(path)}

    # ---- quotas + layouts (Client.cc quota realms / file layouts) ----------
    def _ancestor_dirs(self, path: str):
        """(path, inode) for every EXISTING directory from root down
        to *path*'s deepest dir component — the quota realm chain."""
        out = []
        cur_ino = ROOT_INO
        cur_path = ""
        parts = self.fs._split(path)
        for part in parts:
            try:
                inode = self.fs._lookup(cur_ino, part)
            except FsError:
                break
            if inode.get("type") != "dir":
                break
            cur_ino = inode["ino"]
            cur_path = cur_path + "/" + part
            out.append((cur_path, inode))
        return out

    def _subtree_usage(self, path: str):
        """(bytes, files) under a directory: the rstat role
        (rbytes / rfiles+rsubdirs) computed on demand at lite scale."""
        used_bytes = 0
        used_files = 0
        stack = [path]
        while stack:
            p = stack.pop()
            for name, inode in self.fs.listdir(p).items():
                used_files += 1
                child = p.rstrip("/") + "/" + name
                if inode.get("type") == "dir":
                    stack.append(child)
                else:
                    used_bytes += int(inode.get("size", 0))
        return used_bytes, used_files

    def _quota_chain(self, path: str):
        """Every quota-bearing ancestor with its limits and current
        usage, outermost first (the realm chain a client enforces
        writes against, Client.cc:4627)."""
        out = []
        for p, inode in self._ancestor_dirs(path):
            mb = int(inode.get("quota_max_bytes", 0) or 0)
            mf = int(inode.get("quota_max_files", 0) or 0)
            if not (mb or mf):
                continue
            ub, uf = self._subtree_usage(p)
            out.append({"path": p, "max_bytes": mb, "max_files": mf,
                        "used_bytes": ub, "used_files": uf})
        return out

    def _check_files_quota(self, path: str) -> None:
        """EDQUOT when adding one dentry at *path* would exceed any
        ancestor max_files (the chain walk stops at the deepest
        existing directory, so the not-yet-created leaf is fine)."""
        for q in self._quota_chain(path):
            if q["max_files"] and q["used_files"] + 1 > \
                    q["max_files"]:
                raise FsError("quota", -122)         # EDQUOT

    def _check_rename_quota(self, src: str, dst: str) -> None:
        """A rename INTO a quota realm absorbs the moved subtree's
        dentries and bytes (Server.cc's rename quota gate): realms
        covering dst but NOT src must fit the increment."""
        src_realms = {q["path"] for q in self._quota_chain(src)}
        dst_chain = [q for q in self._quota_chain(dst)
                     if q["path"] not in src_realms]
        if not dst_chain:
            return
        inode = self.fs._resolve(src, follow_final=False)
        if inode.get("type") == "dir":
            sp = "/" + "/".join(self.fs._split(src))
            add_bytes, add_files = self._subtree_usage(sp)
            add_files += 1                       # the moved dir itself
        else:
            add_bytes, add_files = int(inode.get("size", 0)), 1
        for q in dst_chain:
            if q["max_files"] and \
                    q["used_files"] + add_files > q["max_files"]:
                raise FsError("quota", -122)
            if q["max_bytes"] and add_bytes and \
                    q["used_bytes"] + add_bytes > q["max_bytes"]:
                raise FsError("quota", -122)

    def _inherited_layout(self, path: str):
        """Nearest ancestor dir layout (ceph.dir.layout inheritance:
        fixed into the file inode at create, Client.cc:11645)."""
        layout = None
        for _p, inode in self._ancestor_dirs(path):
            if inode.get("layout"):
                layout = inode["layout"]
        return layout

    def _op_set_quota(self, args: Dict) -> Dict:
        dino, name, inode = self.fs._resolve_dentry(args["path"])
        if inode["type"] != "dir":
            raise FsError("set_quota", -20)          # ENOTDIR
        self.fs._update(dino, name,
                        quota_max_bytes=int(args.get("max_bytes", 0)),
                        quota_max_files=int(args.get("max_files", 0)))
        return {"ino": inode["ino"]}

    def _op_set_layout(self, args: Dict) -> Dict:
        """ceph.dir.layout / ceph.file.layout vxattrs: {order, pool}.
        Fields MERGE into an existing layout (setfattr of one
        ceph.dir.layout.* field keeps the others).  A FILE's layout
        is only settable while it is empty (the reference's
        layout-after-data EINVAL)."""
        dino, name, inode = self.fs._resolve_dentry(args["path"])
        layout = dict(inode.get("layout") or {})
        if args.get("order") is not None:
            layout["order"] = int(args["order"])
        if args.get("pool"):
            layout["pool"] = args["pool"]
        if inode["type"] == "dir":
            self.fs._update(dino, name, layout=layout)
        elif inode["type"] == "file":
            if int(inode.get("size", 0)):
                raise FsError("set_layout", -22)     # EINVAL
            attrs = {}
            if "order" in layout:
                attrs["order"] = layout["order"]
            if "pool" in layout:
                attrs["pool"] = layout["pool"]
            self.fs._update(dino, name, **attrs)
        else:
            raise FsError("set_layout", -22)
        return {"ino": inode["ino"]}

    # ---- snap realms -------------------------------------------------------
    def _realm_snaps(self, ino: int) -> Dict[str, Dict]:
        try:
            return json.loads(self.fs._call(realm_oid(ino), "snap_ls"))
        except FsError as e:
            if e.result in (-2, -116):
                return {}
            raise

    def _ancestor_inos(self, path: str) -> List[int]:
        """Realm chain: every directory ino from root down to the
        file's parent (SnapRealm parent links)."""
        out = [ROOT_INO]
        cur = ROOT_INO
        parts = self.fs._split(path)
        for part in parts[:-1]:
            inode = self.fs._lookup(cur, part)
            if inode["type"] != "dir":
                break
            cur = inode["ino"]
            out.append(cur)
        return out

    def _file_snapc(self, path: str) -> Tuple[int, List[int]]:
        """Write SnapContext for the file at *path*: union of data
        snaps over the ancestor realm chain (newest first, like the
        reference's SnapContext)."""
        snaps: Set[int] = set()
        for ino in self._ancestor_inos(path):
            for e in self._realm_snaps(ino).values():
                snaps.add(int(e["data"]))
        ordered = sorted(snaps, reverse=True)
        return (ordered[0] if ordered else 0), ordered

    def _op_snap_create(self, args: Dict) -> Dict:
        """Per-directory snapshot (mkdir .snap/<name>): ids recorded in
        the DIRECTORY's realm, so only its subtree is covered."""
        path = args["path"]
        name = args["name"]
        inode = self.fs._resolve(path, follow_final=True)
        if inode["type"] != "dir":
            raise FsError("snap_create", -20)
        md_sid = self.rados.selfmanaged_snap_create(self.mdpool)
        data_sid = self.rados.selfmanaged_snap_create(self.dpool)
        try:
            self.fs._call(realm_oid(inode["ino"]), "snap_add",
                          {"name": name, "md_sid": md_sid,
                           "data_sid": data_sid,
                           "stamp": args.get("stamp", 0.0)})
        except FsError:
            self.rados.selfmanaged_snap_remove(self.mdpool, md_sid)
            self.rados.selfmanaged_snap_remove(self.dpool, data_sid)
            raise
        self._install_md_snapc()
        return {"ino": inode["ino"], "md": md_sid, "data": data_sid}

    def _op_snap_remove(self, args: Dict) -> Dict:
        inode = self.fs._resolve(args["path"], follow_final=True)
        gone = json.loads(self.fs._call(
            realm_oid(inode["ino"]), "snap_rm", {"name": args["name"]}))
        self.rados.selfmanaged_snap_remove(self.mdpool, gone["md"])
        self.rados.selfmanaged_snap_remove(self.dpool, gone["data"])
        self._install_md_snapc()
        return gone

    def _all_realm_md_snaps(self) -> List[int]:
        """Union of metadata snap ids over every realm.  The MDS
        writes metadata with ALL realms' md snaps in context — cloning
        a dentry object outside a snapshotted subtree is invisible to
        every view (views resolve only under their realm root), while
        per-FILE data snapc stays strictly per-realm-chain."""
        snaps: Set[int] = set()
        stack = ["/"]
        inos = [ROOT_INO]
        while stack:
            path = stack.pop()
            for name, inode in self.fs.listdir(path).items():
                if inode.get("type") == "dir":
                    inos.append(inode["ino"])
                    stack.append(path.rstrip("/") + "/" + name)
        for ino in inos:
            for e in self._realm_snaps(ino).values():
                snaps.add(int(e["md"]))
        return sorted(snaps)

    def _install_md_snapc(self) -> None:
        md = self._all_realm_md_snaps()
        self.rados.set_write_ctx(self.mdpool, md[-1] if md else 0, md)

    def _op_walk_snapc(self, args: Dict) -> Dict:
        seq, snaps = self._file_snapc(args["path"])
        return {"snapc_seq": seq, "snapc_snaps": snaps}

    def _op_lssnap(self, args: Dict) -> Dict:
        inode = self.fs._resolve(args["path"], follow_final=True)
        return {"snaps": self._realm_snaps(inode["ino"]),
                "ino": inode["ino"]}

    # ---- op table ----------------------------------------------------------
    def _apply(self, op: str, args: Dict):
        fs = self.fs
        if op == "mkdir":
            return {"ino": fs.mkdir(args["path"])}
        if op == "create":
            # layout inheritance: the nearest ancestor dir layout is
            # FIXED into the file inode at create (Client.cc:11645)
            layout = self._inherited_layout(args["path"]) or {}
            order = int(args.get("order") or
                        layout.get("order") or 22)
            ino = fs.create(args["path"], order=order)
            if layout.get("pool"):
                dino, name, _ = fs._resolve_dentry(args["path"])
                fs._update(dino, name, pool=layout["pool"])
            return {"ino": ino}
        if op == "symlink":
            return {"ino": fs.symlink(args["path"], args["target"])}
        if op == "hardlink":
            fs.hardlink(args["existing"], args["newpath"])
            return {}
        if op == "unlink":
            fs.unlink(args["path"])
            return {}
        if op == "rmdir":
            fs.rmdir(args["path"])
            return {}
        if op == "rename":
            fs.rename(args["src"], args["dst"])
            # cap bookkeeping follows the namespace: open handles on
            # renamed files must still be found by a later subtree
            # cap drain (set_dir_pin under the NEW path)
            src = "/" + "/".join(fs._split(args["src"]))
            dst = "/" + "/".join(fs._split(args["dst"]))
            for ino, p in list(self._cap_paths.items()):
                q = "/" + "/".join(fs._split(p))
                if q == src:
                    self._cap_paths[ino] = dst
                elif q.startswith(src + "/"):
                    self._cap_paths[ino] = dst + q[len(src):]
            return {}
        if op == "setattr":
            fs.setattr(args["path"],
                       mode=args.get("mode"), uid=args.get("uid"),
                       gid=args.get("gid"), mtime=args.get("mtime"))
            return {}
        if op == "truncate":
            fs.truncate(args["path"], int(args["size"]))
            return {}
        if op == "wrstat":
            # size/mtime write-back from a cap flush
            # (Locker::file_update_finish role)
            dino, name, inode = fs._resolve_dentry(args["path"])
            attrs = {"size": int(args["size"])}
            if args.get("mtime") is not None:
                attrs["mtime"] = float(args["mtime"])
            tgt_dino, tgt_name, _ = fs._primary_of(dino, name, inode)
            fs._update(tgt_dino, tgt_name, **attrs)
            return {}
        if op == "set_quota":
            return self._op_set_quota(args)
        if op == "set_layout":
            return self._op_set_layout(args)
        if op == "get_quota":
            return {"quotas": self._quota_chain(args["path"])}
        if op == "set_dir_pin":
            # the handoff record: one atomic attr merge on the dir's
            # dentry; authority flips for the whole subtree
            dino, name, inode = fs._resolve_dentry(args["path"])
            fs._update(dino, name, pin=int(args["rank"]))
            return {"ino": inode["ino"], "rank": int(args["rank"])}
        if op == "snap_create":
            return self._op_snap_create(args)
        if op == "snap_remove":
            return self._op_snap_remove(args)
        if op == "lssnap":
            return self._op_lssnap(args)
        if op == "walk_snapc":
            return self._op_walk_snapc(args)
        if op == "stat":
            if args.get("nofollow"):
                # lstat flavor: the client's replayed-symlink ino
                # recovery must see the link itself, not its target
                return {"inode": fs._resolve(args["path"],
                                             follow_final=False)}
            return {"inode": fs.stat(args["path"])}
        if op == "resolve":
            return {"inode": fs._resolve(args["path"],
                                         follow_final=True)}
        if op == "exists":
            return {"exists": fs.exists(args["path"])}
        if op == "listdir":
            return {"entries": fs.listdir(args["path"])}
        if op == "readlink":
            return {"target": fs.readlink(args["path"])}
        raise FsError(op, -22)
