from .server import MDSDaemon  # noqa: F401
