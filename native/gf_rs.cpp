// Native GF(2^8) region coder — the SIMD-class host path.
//
// Plays the role of the reference's isa-l/jerasure native libraries
// (ec_encode_data; reference src/erasure-code/isa/ErasureCodeIsa.cc:128):
// region multiply-accumulate over GF(2^8) using 4-bit split tables, which
// GCC auto-vectorizes.  Used as the CPU benchmark baseline and as a second
// implementation cross-checking the Python/numpy codec.

#include <cstdint>
#include <cstring>

namespace {

constexpr unsigned POLY = 0x11d;

struct Tables {
  uint8_t mul[256][256];
  bool ready = false;
} g;

void init_tables() {
  if (g.ready) return;
  uint8_t exp[512];
  int log[256] = {0};
  unsigned x = 1;
  for (int i = 0; i < 255; i++) {
    exp[i] = (uint8_t)x;
    log[x] = i;
    x <<= 1;
    if (x & 0x100) x ^= POLY;
  }
  for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
  for (int a = 1; a < 256; a++)
    for (int b = 1; b < 256; b++)
      g.mul[a][b] = exp[log[a] + log[b]];
  memset(g.mul[0], 0, 256);
  for (int a = 0; a < 256; a++) g.mul[a][0] = 0;
  g.ready = true;
}

// dst ^= coeff * src over a region, via split lo/hi nibble tables
void region_mad(uint8_t coeff, const uint8_t* src, uint8_t* dst, int64_t n) {
  if (coeff == 0) return;
  if (coeff == 1) {
    for (int64_t i = 0; i < n; i++) dst[i] ^= src[i];
    return;
  }
  uint8_t lo[16], hi[16];
  for (int v = 0; v < 16; v++) {
    lo[v] = g.mul[coeff][v];
    hi[v] = g.mul[coeff][v << 4];
  }
  for (int64_t i = 0; i < n; i++) {
    uint8_t b = src[i];
    dst[i] ^= (uint8_t)(lo[b & 0xf] ^ hi[b >> 4]);
  }
}

}  // namespace

extern "C" {

// coding[r][*] = sum_j matrix[r*k+j] * data[j][*]; data/coding are
// contiguous (k, n) and (rows, n) uint8 buffers.
void gf_rs_encode(const uint8_t* matrix, int rows, int k,
                  const uint8_t* data, uint8_t* coding, int64_t n) {
  init_tables();
  memset(coding, 0, (size_t)rows * n);
  for (int r = 0; r < rows; r++)
    for (int j = 0; j < k; j++)
      region_mad(matrix[r * k + j], data + (int64_t)j * n,
                 coding + (int64_t)r * n, n);
}

void gf_region_xor(const uint8_t* a, const uint8_t* b, uint8_t* out,
                   int64_t n) {
  for (int64_t i = 0; i < n; i++) out[i] = a[i] ^ b[i];
}

uint8_t gf_mul_c(uint8_t a, uint8_t b) {
  init_tables();
  return g.mul[a][b];
}

// crc32c (Castagnoli), table-driven, in Ceph's convention: the raw table
// update with NO pre/post bit inversion (reference include/crc32c.h
// ceph_crc32c -> common/sctp_crc32.c update_crc32; golden vectors in
// test/common/test_crc32c.cc, e.g. crc32c(0, "foo bar baz") = 4119623852).
uint32_t ceph_crc32c(uint32_t crc, const uint8_t* data, int64_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int j = 0; j < 8; j++)
        c = (c & 1) ? (c >> 1) ^ 0x82f63b78u : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  for (int64_t i = 0; i < n; i++)
    crc = table[(crc ^ data[i]) & 0xff] ^ (crc >> 8);
  return crc;
}

}  // extern "C"
