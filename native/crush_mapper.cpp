// Native CRUSH map evaluator — independent C++ implementation.
//
// Second, independently written implementation of the CRUSH mapping
// semantics (reference: src/crush/mapper.c) used to cross-validate the
// Python host mapper and the TPU kernels, and as the fast CPU batch
// baseline (the ParallelPGMapper analog, reference osd/OSDMapMapping.h).
//
// The map arrives as a flat int64 blob serialized by
// ceph_tpu/native.py:serialize_map; see that file for the layout.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint32_t HASH_SEED = 1315423911u;

#define MIXER(a, b, c)                                    \
  do {                                                    \
    a = a - b; a = a - c; a = a ^ (c >> 13);              \
    b = b - c; b = b - a; b = b ^ (a << 8);               \
    c = c - a; c = c - b; c = c ^ (b >> 13);              \
    a = a - b; a = a - c; a = a ^ (c >> 12);              \
    b = b - c; b = b - a; b = b ^ (a << 16);              \
    c = c - a; c = c - b; c = c ^ (b >> 5);               \
    a = a - b; a = a - c; a = a ^ (c >> 3);               \
    b = b - c; b = b - a; b = b ^ (a << 10);              \
    c = c - a; c = c - b; c = c ^ (b >> 15);              \
  } while (0)

uint32_t hash2(uint32_t a, uint32_t b) {
  uint32_t h = HASH_SEED ^ a ^ b, x = 231232u, y = 1232u;
  MIXER(a, b, h);
  MIXER(x, a, h);
  MIXER(b, y, h);
  return h;
}

uint32_t hash3(uint32_t a, uint32_t b, uint32_t c) {
  uint32_t h = HASH_SEED ^ a ^ b ^ c, x = 231232u, y = 1232u;
  MIXER(a, b, h);
  MIXER(c, x, h);
  MIXER(y, a, h);
  MIXER(b, x, h);
  MIXER(y, c, h);
  return h;
}

uint32_t hash4(uint32_t a, uint32_t b, uint32_t c, uint32_t d) {
  uint32_t h = HASH_SEED ^ a ^ b ^ c ^ d, x = 231232u, y = 1232u;
  MIXER(a, b, h);
  MIXER(c, d, h);
  MIXER(a, x, h);
  MIXER(y, b, h);
  MIXER(c, x, h);
  MIXER(y, d, h);
  return h;
}

// ---- crush_ln: fixed point 2^44*log2(x+1); tables injected from python ---
static int64_t g_rh_lh[258];
static int64_t g_ll[256];

int64_t crush_ln_fp(uint32_t xin) {
  uint32_t x = xin + 1;
  int iexpon = 15;
  if (!(x & 0x18000)) {
    int bits = __builtin_clz(x & 0x1FFFF) - 16;
    x <<= bits;
    iexpon = 15 - bits;
  }
  int index1 = (x >> 8) << 1;
  uint64_t RH = (uint64_t)g_rh_lh[index1 - 256];
  uint64_t LH = (uint64_t)g_rh_lh[index1 + 1 - 256];
  uint64_t xl64 = ((uint64_t)x * RH) >> 48;
  int index2 = xl64 & 0xff;
  uint64_t LL = (uint64_t)g_ll[index2];
  uint64_t result = ((uint64_t)iexpon << 44) + ((LH + LL) >> 4);
  return (int64_t)result;
}

// ---- flattened map --------------------------------------------------------

enum Alg { UNIFORM = 1, LIST = 2, TREE = 3, STRAW = 4, STRAW2 = 5 };

struct FlatBucket {
  int32_t id = 0, alg = 0, type = 0, size = 0;
  const int64_t* items = nullptr;
  const int64_t* weights = nullptr;      // per-item (list/straw/straw2)
  const int64_t* aux = nullptr;          // sum_weights / straws
  const int64_t* node_weights = nullptr; // tree
  int64_t item_weight = 0;               // uniform
  int32_t num_nodes = 0;
  bool present = false;
  // choose_args overrides (crush.h crush_choose_arg): straw2 hashing
  // ids and per-position weight replacements
  const int64_t* arg_ids = nullptr;
  const int64_t* arg_weights = nullptr;  // (arg_npos, size) row-major
  int32_t arg_npos = 0;
};

struct FlatRule {
  int32_t ruleset, type, min_size, max_size, len;
  const int64_t* steps;  // 3 per step
  bool present = false;
};

struct FlatMap {
  int64_t max_devices = 0;
  int64_t choose_local_tries = 0, choose_local_fallback_tries = 0;
  int64_t choose_total_tries = 50, chooseleaf_descend_once = 1;
  int64_t chooseleaf_vary_r = 1, chooseleaf_stable = 1;
  std::vector<FlatBucket> buckets;
  std::vector<FlatRule> rules;

  const FlatBucket* bucket(int64_t item) const {
    int64_t bno = -1 - item;
    if (bno < 0 || bno >= (int64_t)buckets.size() || !buckets[bno].present)
      return nullptr;
    return &buckets[bno];
  }
};

bool parse_map(const int64_t* p, int64_t n, FlatMap* m) {
  int64_t i = 0;
  if (n < 10) return false;
  m->max_devices = p[i++];
  m->choose_local_tries = p[i++];
  m->choose_local_fallback_tries = p[i++];
  m->choose_total_tries = p[i++];
  m->chooseleaf_descend_once = p[i++];
  m->chooseleaf_vary_r = p[i++];
  m->chooseleaf_stable = p[i++];
  int64_t nb = p[i++];
  int64_t nr = p[i++];
  m->buckets.resize(nb);
  for (int64_t b = 0; b < nb; b++) {
    FlatBucket& fb = m->buckets[b];
    int64_t present = p[i++];
    if (!present) continue;
    fb.present = true;
    fb.id = (int32_t)p[i++];
    fb.alg = (int32_t)p[i++];
    fb.type = (int32_t)p[i++];
    fb.size = (int32_t)p[i++];
    fb.items = &p[i]; i += fb.size;
    switch (fb.alg) {
      case UNIFORM:
        fb.item_weight = p[i++];
        break;
      case LIST:
        fb.weights = &p[i]; i += fb.size;
        fb.aux = &p[i]; i += fb.size;   // cumulative sums
        break;
      case TREE:
        fb.num_nodes = (int32_t)p[i++];
        fb.node_weights = &p[i]; i += fb.num_nodes;
        break;
      case STRAW:
        fb.weights = &p[i]; i += fb.size;
        fb.aux = &p[i]; i += fb.size;   // straw scalers
        break;
      case STRAW2:
        fb.weights = &p[i]; i += fb.size;
        break;
      default:
        return false;
    }
  }
  m->rules.resize(nr);
  for (int64_t r = 0; r < nr; r++) {
    FlatRule& fr = m->rules[r];
    int64_t present = p[i++];
    if (!present) continue;
    fr.present = true;
    fr.ruleset = (int32_t)p[i++];
    fr.type = (int32_t)p[i++];
    fr.min_size = (int32_t)p[i++];
    fr.max_size = (int32_t)p[i++];
    fr.len = (int32_t)p[i++];
    fr.steps = &p[i]; i += 3 * fr.len;
  }
  // trailing choose_args section (older blobs simply end here).
  // Every advance is bounds-checked BEFORE the dereference — a
  // truncated blob must fail the parse, not read past the buffer.
  if (i < n) {
    int64_t nca = p[i++];
    for (int64_t e = 0; e < nca; e++) {
      if (i + 3 > n) return false;
      int64_t bno = p[i++];
      int64_t has_ids = p[i++];
      int64_t size = p[i++];
      if (bno < 0 || bno >= nb || size < 0 ||
          size != m->buckets[bno].size)
        return false;
      FlatBucket& fb = m->buckets[bno];
      if (has_ids) {
        if (i + size > n) return false;
        fb.arg_ids = &p[i]; i += size;
      }
      if (i + 1 > n) return false;
      int64_t npos = p[i++];
      if (npos < 0 || npos > (n - i) / (size ? size : 1)) return false;
      fb.arg_npos = (int32_t)npos;
      if (npos) {
        if (i + npos * size > n) return false;
        fb.arg_weights = &p[i]; i += npos * size;
      }
    }
  }
  return i <= n;
}

// ---- bucket choosers ------------------------------------------------------

int64_t perm_choose(const FlatBucket* b, int64_t x, int64_t r) {
  int size = b->size;
  unsigned pr = (unsigned)(r % size);
  std::vector<uint32_t> perm(size);
  for (int i = 0; i < size; i++) perm[i] = i;
  for (unsigned p = 0; p <= pr; p++) {
    if ((int)p < size - 1) {
      unsigned i = hash3((uint32_t)x, (uint32_t)b->id, p) % (size - p);
      if (i) std::swap(perm[p], perm[p + i]);
    }
  }
  return b->items[perm[pr]];
}

int64_t list_choose(const FlatBucket* b, int64_t x, int64_t r) {
  for (int i = b->size - 1; i >= 0; i--) {
    uint64_t w = hash4((uint32_t)x, (uint32_t)b->items[i], (uint32_t)r,
                       (uint32_t)b->id) & 0xffff;
    w = (w * (uint64_t)b->aux[i]) >> 16;
    if ((int64_t)w < b->weights[i]) return b->items[i];
  }
  return b->items[0];
}

int64_t tree_choose(const FlatBucket* b, int64_t x, int64_t r) {
  int n = b->num_nodes >> 1;
  while (!(n & 1)) {
    uint64_t w = (uint64_t)b->node_weights[n];
    uint64_t t = ((uint64_t)hash4((uint32_t)x, (uint32_t)n, (uint32_t)r,
                                  (uint32_t)b->id) * w) >> 32;
    int h = __builtin_ctz(n);
    int left = n - (1 << (h - 1));
    if ((int64_t)t < b->node_weights[left])
      n = left;
    else
      n = left + (1 << h);
  }
  return b->items[n >> 1];
}

int64_t straw_choose(const FlatBucket* b, int64_t x, int64_t r) {
  int high = 0;
  uint64_t high_draw = 0;
  for (int i = 0; i < b->size; i++) {
    uint64_t draw = hash3((uint32_t)x, (uint32_t)b->items[i],
                          (uint32_t)r) & 0xffff;
    draw *= (uint64_t)b->aux[i];
    if (i == 0 || draw > high_draw) {
      high = i;
      high_draw = draw;
    }
  }
  return b->items[high];
}

int64_t straw2_choose(const FlatBucket* b, int64_t x, int64_t r,
                      int position) {
  // choose_args override the weights (clamped position, mapper.c:
  // get_choose_arg_weights) and the ids hashed (get_choose_arg_ids);
  // only straw2 consumes them (crush_bucket_choose)
  const int64_t* weights = b->weights;
  if (b->arg_npos > 0) {
    int pos = position >= b->arg_npos ? b->arg_npos - 1 : position;
    weights = b->arg_weights + (int64_t)pos * b->size;
  }
  const int64_t* ids = b->arg_ids ? b->arg_ids : b->items;
  int high = 0;
  int64_t high_draw = 0;
  for (int i = 0; i < b->size; i++) {
    int64_t w = weights[i];
    int64_t draw;
    if (w) {
      uint32_t u = hash3((uint32_t)x, (uint32_t)ids[i],
                         (uint32_t)r) & 0xffff;
      int64_t ln = crush_ln_fp(u) - 0x1000000000000ll;
      draw = ln / w;  // C++ division truncates toward zero, as required
    } else {
      draw = INT64_MIN;
    }
    if (i == 0 || draw > high_draw) {
      high = i;
      high_draw = draw;
    }
  }
  return b->items[high];
}

int64_t bucket_choose(const FlatMap& m, const FlatBucket* b, int64_t x,
                      int64_t r, int position) {
  switch (b->alg) {
    case UNIFORM: return perm_choose(b, x, r);
    case LIST:    return list_choose(b, x, r);
    case TREE:    return tree_choose(b, x, r);
    case STRAW:   return straw_choose(b, x, r);
    case STRAW2:  return straw2_choose(b, x, r, position);
  }
  return b->items[0];
}

bool is_out(const FlatMap& m, const uint32_t* weight, int64_t weight_max,
            int64_t item, int64_t x) {
  if (item >= weight_max) return true;
  uint32_t w = weight[item];
  if (w >= 0x10000) return false;
  if (w == 0) return true;
  return (hash2((uint32_t)x, (uint32_t)item) & 0xffff) >= w;
}

constexpr int64_t ITEM_NONE = 0x7fffffff;
constexpr int64_t ITEM_UNDEF = 0x7ffffffe;

// ---- choose firstn/indep --------------------------------------------------

int choose_firstn(const FlatMap& m, const FlatBucket* bucket,
                  const uint32_t* weight, int64_t weight_max, int64_t x,
                  int numrep, int type, int64_t* out, int outpos,
                  int out_size, int tries, int recurse_tries,
                  int local_retries, int local_fallback_retries,
                  bool recurse_to_leaf, int vary_r, int stable,
                  int64_t* out2, int parent_r) {
  int count = out_size;
  int64_t item = 0;
  for (int rep = stable ? 0 : outpos; rep < numrep && count > 0; rep++) {
    unsigned ftotal = 0;
    bool skip_rep = false;
    bool retry_descent = true;
    while (retry_descent) {
      retry_descent = false;
      const FlatBucket* in = bucket;
      unsigned flocal = 0;
      bool retry_bucket = true;
      while (retry_bucket) {
        retry_bucket = false;
        bool collide = false, reject = false;
        int64_t r = rep + parent_r + ftotal;
        if (in->size == 0) {
          reject = true;
        } else {
          if (local_fallback_retries > 0 &&
              flocal >= (unsigned)(in->size >> 1) &&
              flocal > (unsigned)local_fallback_retries)
            item = perm_choose(in, x, r);
          else
            // position = outpos, the dynamic success count
            // (mapper.c:513)
            item = bucket_choose(m, in, x, r, outpos);
          if (item >= m.max_devices) {
            skip_rep = true;
            break;
          }
          int itemtype = 0;
          if (item < 0) {
            const FlatBucket* sub = m.bucket(item);
            if (!sub) { skip_rep = true; break; }
            itemtype = sub->type;
          }
          if (itemtype != type) {
            const FlatBucket* sub = (item < 0) ? m.bucket(item) : nullptr;
            if (!sub) { skip_rep = true; break; }
            in = sub;
            retry_bucket = true;
            continue;
          }
          for (int i = 0; i < outpos; i++) {
            if (out[i] == item) { collide = true; break; }
          }
          if (!collide && recurse_to_leaf) {
            if (item < 0) {
              int sub_r = vary_r ? (int)(r >> (vary_r - 1)) : 0;
              if (choose_firstn(m, m.bucket(item), weight, weight_max, x,
                                stable ? 1 : outpos + 1, 0, out2, outpos,
                                count, recurse_tries, 0, local_retries,
                                local_fallback_retries, false, vary_r,
                                stable, nullptr, sub_r) <= outpos)
                reject = true;
            } else {
              out2[outpos] = item;
            }
          }
          if (!reject && !collide && itemtype == 0)
            reject = is_out(m, weight, weight_max, item, x);
        }
        if (reject || collide) {
          ftotal++;
          flocal++;
          if (collide && flocal <= (unsigned)local_retries)
            retry_bucket = true;
          else if (local_fallback_retries > 0 &&
                   flocal <= (unsigned)(in->size + local_fallback_retries))
            retry_bucket = true;
          else if (ftotal < (unsigned)tries)
            retry_descent = true;
          else
            skip_rep = true;
          if (!retry_bucket) break;
        }
      }
    }
    if (skip_rep) continue;
    out[outpos] = item;
    outpos++;
    count--;
  }
  return outpos;
}

void choose_indep(const FlatMap& m, const FlatBucket* bucket,
                  const uint32_t* weight, int64_t weight_max, int64_t x,
                  int left, int numrep, int type, int64_t* out, int outpos,
                  int tries, int recurse_tries, bool recurse_to_leaf,
                  int64_t* out2, int64_t parent_r) {
  int endpos = outpos + left;
  for (int rep = outpos; rep < endpos; rep++) {
    out[rep] = ITEM_UNDEF;
    if (out2) out2[rep] = ITEM_UNDEF;
  }
  for (unsigned ftotal = 0; left > 0 && ftotal < (unsigned)tries; ftotal++) {
    for (int rep = outpos; rep < endpos; rep++) {
      if (out[rep] != ITEM_UNDEF) continue;
      const FlatBucket* in = bucket;
      for (;;) {
        int64_t r = rep + parent_r;
        if (in->alg == UNIFORM && in->size % numrep == 0)
          r += (numrep + 1) * ftotal;
        else
          r += numrep * ftotal;
        if (in->size == 0) break;
        // position = the invocation's constant starting outpos
        // (mapper.c:723) — 0 from do_rule, rep inside leaf recursion
        int64_t item = bucket_choose(m, in, x, r, outpos);
        if (item >= m.max_devices) {
          out[rep] = ITEM_NONE;
          if (out2) out2[rep] = ITEM_NONE;
          left--;
          break;
        }
        int itemtype = 0;
        if (item < 0) {
          const FlatBucket* sub = m.bucket(item);
          if (!sub) {
            out[rep] = ITEM_NONE;
            if (out2) out2[rep] = ITEM_NONE;
            left--;
            break;
          }
          itemtype = sub->type;
        }
        if (itemtype != type) {
          const FlatBucket* sub = (item < 0) ? m.bucket(item) : nullptr;
          if (!sub) {
            out[rep] = ITEM_NONE;
            if (out2) out2[rep] = ITEM_NONE;
            left--;
            break;
          }
          in = sub;
          continue;
        }
        bool collide = false;
        for (int i = outpos; i < endpos; i++) {
          if (out[i] == item) { collide = true; break; }
        }
        if (collide) break;
        if (recurse_to_leaf) {
          if (item < 0) {
            choose_indep(m, m.bucket(item), weight, weight_max, x, 1, numrep,
                         0, out2, rep, recurse_tries, 0, false, nullptr, r);
            if (out2[rep] == ITEM_NONE) break;
          } else {
            out2[rep] = item;
          }
        }
        if (itemtype == 0 && is_out(m, weight, weight_max, item, x)) break;
        out[rep] = item;
        left--;
        break;
      }
    }
  }
  for (int rep = outpos; rep < endpos; rep++) {
    if (out[rep] == ITEM_UNDEF) out[rep] = ITEM_NONE;
    if (out2 && out2[rep] == ITEM_UNDEF) out2[rep] = ITEM_NONE;
  }
}

enum Op {
  OP_NOOP = 0, OP_TAKE = 1, OP_CHOOSE_FIRSTN = 2, OP_CHOOSE_INDEP = 3,
  OP_EMIT = 4, OP_CHOOSELEAF_FIRSTN = 6, OP_CHOOSELEAF_INDEP = 7,
  OP_SET_CHOOSE_TRIES = 8, OP_SET_CHOOSELEAF_TRIES = 9,
  OP_SET_CHOOSE_LOCAL_TRIES = 10, OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11,
  OP_SET_CHOOSELEAF_VARY_R = 12, OP_SET_CHOOSELEAF_STABLE = 13,
};

int do_rule(const FlatMap& m, int ruleno, int64_t x, int64_t* result,
            int result_max, const uint32_t* weight, int64_t weight_max) {
  if (ruleno < 0 || ruleno >= (int)m.rules.size() ||
      !m.rules[ruleno].present)
    return 0;
  const FlatRule& rule = m.rules[ruleno];

  std::vector<int64_t> a(result_max), b(result_max), c(result_max);
  int64_t* w = a.data();
  int64_t* o = b.data();
  int wsize = 0, result_len = 0;

  int choose_tries = (int)m.choose_total_tries + 1;
  int choose_leaf_tries = 0;
  int choose_local_retries = (int)m.choose_local_tries;
  int choose_local_fallback_retries = (int)m.choose_local_fallback_tries;
  int vary_r = (int)m.chooseleaf_vary_r;
  int stable = (int)m.chooseleaf_stable;

  for (int s = 0; s < rule.len; s++) {
    int op = (int)rule.steps[3 * s];
    int64_t arg1 = rule.steps[3 * s + 1];
    int64_t arg2 = rule.steps[3 * s + 2];
    bool firstn = false;
    switch (op) {
      case OP_TAKE:
        if ((arg1 >= 0 && arg1 < m.max_devices) || m.bucket(arg1)) {
          w[0] = arg1;
          wsize = 1;
        }
        break;
      case OP_SET_CHOOSE_TRIES:
        if (arg1 > 0) choose_tries = (int)arg1;
        break;
      case OP_SET_CHOOSELEAF_TRIES:
        if (arg1 > 0) choose_leaf_tries = (int)arg1;
        break;
      case OP_SET_CHOOSE_LOCAL_TRIES:
        if (arg1 >= 0) choose_local_retries = (int)arg1;
        break;
      case OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
        if (arg1 >= 0) choose_local_fallback_retries = (int)arg1;
        break;
      case OP_SET_CHOOSELEAF_VARY_R:
        if (arg1 >= 0) vary_r = (int)arg1;
        break;
      case OP_SET_CHOOSELEAF_STABLE:
        if (arg1 >= 0) stable = (int)arg1;
        break;
      case OP_CHOOSELEAF_FIRSTN:
      case OP_CHOOSE_FIRSTN:
        firstn = true;
        [[fallthrough]];
      case OP_CHOOSELEAF_INDEP:
      case OP_CHOOSE_INDEP: {
        if (wsize == 0) break;
        bool recurse_to_leaf =
            (op == OP_CHOOSELEAF_FIRSTN || op == OP_CHOOSELEAF_INDEP);
        int osize = 0;
        for (int i = 0; i < wsize; i++) {
          int numrep = (int)arg1;
          if (numrep <= 0) {
            numrep += result_max;
            if (numrep <= 0) continue;
          }
          const FlatBucket* bkt = m.bucket(w[i]);
          if (!bkt) continue;
          if (firstn) {
            int recurse_tries;
            if (choose_leaf_tries)
              recurse_tries = choose_leaf_tries;
            else if (m.chooseleaf_descend_once)
              recurse_tries = 1;
            else
              recurse_tries = choose_tries;
            osize += choose_firstn(
                m, bkt, weight, weight_max, x, numrep, (int)arg2, o + osize,
                0, result_max - osize, choose_tries, recurse_tries,
                choose_local_retries, choose_local_fallback_retries,
                recurse_to_leaf, vary_r, stable, c.data() + osize, 0);
          } else {
            int out_size = numrep < (result_max - osize)
                               ? numrep : (result_max - osize);
            choose_indep(m, bkt, weight, weight_max, x, out_size, numrep,
                         (int)arg2, o + osize, 0, choose_tries,
                         choose_leaf_tries ? choose_leaf_tries : 1,
                         recurse_to_leaf, c.data() + osize, 0);
            osize += out_size;
          }
        }
        if (recurse_to_leaf) memcpy(o, c.data(), osize * sizeof(int64_t));
        std::swap(w, o);
        wsize = osize;
        break;
      }
      case OP_EMIT:
        for (int i = 0; i < wsize && result_len < result_max; i++)
          result[result_len++] = w[i];
        wsize = 0;
        break;
      default:
        break;
    }
  }
  return result_len;
}

}  // namespace

extern "C" {

void crush_set_ln_tables(const int64_t* rh_lh, const int64_t* ll) {
  memcpy(g_rh_lh, rh_lh, sizeof(g_rh_lh));
  memcpy(g_ll, ll, sizeof(g_ll));
}

// Evaluate one x; returns result length.
int crush_do_rule_c(const int64_t* blob, int64_t blob_len, int ruleno,
                    int64_t x, int64_t* result, int result_max,
                    const uint32_t* weight, int64_t weight_max) {
  FlatMap m;
  if (!parse_map(blob, blob_len, &m)) return -1;
  return do_rule(m, ruleno, x, result, result_max, weight, weight_max);
}

// Batch evaluate xs[0..nx); out is (nx, result_max), NONE-padded.
// Lengths land in out_len[0..nx).  This is the CPU baseline the TPU
// kernel is benchmarked against.
int crush_do_rule_batch(const int64_t* blob, int64_t blob_len, int ruleno,
                        const int64_t* xs, int64_t nx, int64_t* out,
                        int result_max, int32_t* out_len,
                        const uint32_t* weight, int64_t weight_max) {
  FlatMap m;
  if (!parse_map(blob, blob_len, &m)) return -1;
  for (int64_t i = 0; i < nx; i++) {
    int64_t* row = out + i * result_max;
    for (int j = 0; j < result_max; j++) row[j] = ITEM_NONE;
    out_len[i] = do_rule(m, ruleno, xs[i], row, result_max, weight,
                         weight_max);
  }
  return 0;
}

}  // extern "C"
